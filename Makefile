# Developer entry points (reference parity: the reference ships a Makefile
# driving tests and its four docker images).

.PHONY: test testfast bench bench-serving images builder-image server-image watchman-image

test:
	python -m pytest tests/ -q

testfast:
	python -m pytest tests/ -q -x -m "not slow"

bench:
	python bench.py

bench-serving:
	python bench_serving.py

images: builder-image server-image watchman-image

builder-image:
	docker build -t gordo-tpu-builder --build-arg ROLE=builder -f Dockerfile .

server-image:
	docker build -t gordo-tpu-server --build-arg ROLE=server -f Dockerfile .

watchman-image:
	docker build -t gordo-tpu-watchman --build-arg ROLE=watchman -f Dockerfile .
