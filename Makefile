# Developer entry points (reference parity: the reference ships a Makefile
# driving tests and its four docker images).

.PHONY: lint test testfast bench bench-serving metrics-smoke chaos-smoke store-fsck perf-smoke trace-smoke coldstart-smoke megabatch-smoke router-smoke slo-smoke quant-smoke autopilot-smoke capacity-smoke mesh-smoke telemetry-smoke qos-smoke reconcile-smoke layout-smoke incident-smoke smoke images builder-image server-image watchman-image

# invariant linter (docs/ARCHITECTURE.md §17/§21): lock discipline
# against the declared hierarchy, blocking-calls-under-hot-locks,
# guarded-state ownership (GUARDED_FIELDS only under their lock),
# wire contracts (routes / X-Gordo-* headers / smoke-asserted series
# cross-referenced producer↔consumer), fault-seam coverage, exception
# hygiene (counterless broad swallows), unbound span seams, gordo_*
# metric conventions, GORDO_* knob registry + generated README table
# sync. Pure stdlib — runs in seconds, no jax (--jobs N parallelizes,
# --format json for CI). The gate is "no NEW violations"
# (lint_baseline.json grandfathers the deliberate keeps, each with a
# reason — empty reasons expire).
lint:
	python -m gordo_components_tpu.analysis

test:
	python -m pytest tests/ -q

testfast:
	python -m pytest tests/ -q -x -m "not slow"

bench:
	python bench.py

bench-serving:
	python bench_serving.py

# end-to-end exposition check: build a throwaway model, serve it, warm it,
# scrape /metrics?format=prometheus, fail on malformed output or missing
# standard series
metrics-smoke:
	JAX_PLATFORMS=cpu python tools/scrape_metrics.py --spawn

# end-to-end resilience check: boot a fleet server with injected faults
# (one slow dispatch, one dead artifact) and assert degraded-but-alive:
# healthy 200s, 503/504 + Retry-After on the sick machines, /healthz
# degraded naming them, gordo_resilience_* series in the exposition
chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos_smoke.py

# end-to-end model-store integrity check: build a throwaway models tree
# with a torn CURRENT generation, an unrecoverable machine, and crash
# debris; assert fsck detects everything, repairs via rollback +
# quarantine, and sweeps the debris (tools/store_fsck.py --selftest)
store-fsck:
	JAX_PLATFORMS=cpu python tools/store_fsck.py --selftest

# serving data-plane check: two-format (npz/JSON) parity, pipelined-vs-
# serial dispatch bit-identity, and a short saturation sweep that must
# not collapse under concurrency (CPU backend; no absolute-RPS gates)
perf-smoke:
	JAX_PLATFORMS=cpu python tools/perf_smoke.py

# span-timeline attribution check: drive a request through a
# fault-injected 200ms dispatch delay and assert the flight recorder
# shows the delay in the dispatch stage, the Chrome trace export is
# Perfetto-valid JSON, `gordo trace dump` works, exemplars link
# histograms to the trace, and watchman surfaces the slow request
trace-smoke:
	JAX_PLATFORMS=cpu python tools/trace_smoke.py

# persistent-compile-cache check: a warm boot pays zero fresh XLA
# compiles (load-not-compile), /reload and rollback adopt generations
# recompile-free, and corrupt/stale/torn cache entries fall back to JIT
# with bit-identical scores
coldstart-smoke:
	JAX_PLATFORMS=cpu python tools/coldstart_smoke.py

# cross-machine megabatching check: the fused stacked program is
# bit-identical to the per-machine path at matched batches, 12 threads
# spread over 8 machines fuse into fewer device dispatches than requests
# (fusion ratio > 1.5), and shard mode falls back cleanly
megabatch-smoke:
	JAX_PLATFORMS=cpu python tools/megabatch_smoke.py

# horizontal serving tier check: 3 real worker processes behind the
# router — consistent-hash placement (X-Gordo-Worker echo), SIGKILL one
# worker mid-traffic (re-route, no 5xx burst beyond the breaker budget,
# eject + respawn), graceful SIGTERM drain (zero dropped requests), and
# a canary → sweep generation rollout plus fleet rollback paying zero
# fresh XLA compiles via the shared compile-cache store
router-smoke:
	JAX_PLATFORMS=cpu python tools/router_smoke.py

# fleet observability check: 2 real worker processes behind the router —
# a routed request renders ONE merged two-lane Perfetto trace (router +
# placed worker, clock-aligned, pull fallback for truncated stitches),
# the aggregate scrape parses with worker labels + merged buckets, and
# injected dispatch latency trips the fast-window burn-rate crossing
# (quiet without faults)
slo-smoke:
	JAX_PLATFORMS=cpu python tools/slo_smoke.py

# precision-ladder check (§19): a mixed f32/bf16/int8 fleet scores
# within each rung's declared parity budget of the all-f32 reference
# (f32 bit-identical; threshold-flip drift reported), the fused
# megabatch path never mixes dtypes, a warm boot of the quantized fleet
# pays zero fresh XLA compiles, and --precision pins survive the
# build → manifest → /healthz round trip
quant-smoke:
	JAX_PLATFORMS=cpu python tools/quant_smoke.py

# closed-loop autopilot check (§20): scripted-signal convergence under
# a step load change (bounded ticks, ≤1 direction flip per window —
# the oscillation guard), injected dispatch latency driving a journaled
# downscale on a real server (flight-recorder event + gordo_autopilot_*
# series + runtime kill switch), and the elastic tier retiring a worker
# on sustained idle (drain-before-retire, ZERO dropped requests) and
# spawning one on sustained burn, with /autopilot ↔ CLI parity
autopilot-smoke:
	JAX_PLATFORMS=cpu python tools/autopilot_smoke.py

# fleet-scale hot-path check (§22): a 2k-machine synthetic fleet —
# FLEET_INDEX lazy boot ≥5x faster than the full scan, the host-RAM
# spill tier serving a demoted machine ≥3x faster than the store path,
# placement candidate lookups in the microsecond regime at a 64-worker
# ring (incremental join beats full rebuild), production-shaped load
# through 2 lazy workers at zero failures / zero SLO breaches, and the
# Prometheus exposition size-bounded (top-K + `other` machine labels)
# at any fleet size. GORDO_CAPACITY_MACHINES/SECONDS resize; the 10k+
# sweep lives in the bench `capacity` block and the `slow` test
capacity-smoke:
	JAX_PLATFORMS=cpu python tools/capacity_smoke.py

# multi-host mesh serving check (§23): a 6-machine fleet sharded across
# a 2-process serving mesh — layout-routed scoring byte-identical (f32)
# to the single-host reference, SIGKILL of one shard host degrading to
# the surviving shard's spill fallback rung with ZERO client-visible
# errors, and a warm re-boot of the same layout paying ZERO fresh XLA
# compiles through the shared compile-cache store
mesh-smoke:
	JAX_PLATFORMS=cpu python tools/mesh_smoke.py

# telemetry warehouse check (§24): Zipf load through 2 shard workers —
# the merged /telemetry traffic sketch ranks machines exactly as the
# load generator sent them, the measured-cost ledger reports nonzero
# device bytes per precision rung and nonzero host-tier bytes, the
# ?view=export layout-input document schema-validates and reproduces
# the Zipf head, and a paired noise-floored gate holds the accounting
# overhead <= 3% of request throughput
telemetry-smoke:
	JAX_PLATFORMS=cpu python tools/telemetry_smoke.py

# multi-tenant QoS check (§25): the three-principal mix (premium
# interactive + saturating bulk + over-quota abuser) through 2 router
# workers against a small admission gate — premium p99 holds with ZERO
# sheds while the bulk tenant saturates at 12 threads and is actually
# shed, quota exhaustion answers 429 + Retry-After (never an
# overload-shaped 503), and scores stay byte-identical bare vs
# tenant-stamped vs the forced-bulk endpoint
qos-smoke:
	JAX_PLATFORMS=cpu python tools/qos_smoke.py

# declarative fleet reconciler check (§26): a 6-machine tier with three
# seeded divergences — SIGKILLed worker, stale CURRENT pointer, machine
# declared bf16 while built f32 — self-heals to the journaled spec
# through the real seams (respawn / pin / precision rebuild /
# canary→sweep reload) with ZERO client-visible errors under trickle
# traffic; then two mid-sweep kill drills assert the WAL's exactly-once
# contract (crashed step re-executes, landed-but-unmarked step resumes
# without re-running)
reconcile-smoke:
	JAX_PLATFORMS=cpu python tools/reconcile_smoke.py

# fleet layout compiler check (§27): a skewed-Zipf 48-machine fleet
# through the real 2-worker router tier — the live telemetry export
# compiles into a deterministic plan whose cost block beats the uniform
# name-hash baseline, the plan applied live through the journaled spec
# at ZERO client-visible errors and ZERO fresh XLA compiles for
# rung-unchanged machines, the re-run Zipf schedule lands a lower
# measured p99 than name-hash, the parity-budgeted variant projects
# more machines-per-GiB, and /fleet/rollback converges the plan away
# cleanly. GORDO_LAYOUT_SMOKE_MACHINES/SECONDS resize
layout-smoke:
	JAX_PLATFORMS=cpu python tools/layout_smoke.py

# fleet black box check (§28): kill -9 a ledger writer mid-append and
# assert the reload contract (torn tail truncated, contiguous seq
# prefix, zero pre-tail loss); then the full 2-worker tier with an
# activated GORDO_FAULTS dispatch stall AND a planted innocent
# autopilot downscale — within 3 scrape ticks a DURABLE incident
# report's TOP ranked candidate names the injected fault seam; every
# control loop's ledger events schema-validate in the same run.
# GORDO_INCIDENT_SMOKE_MACHINES/SECONDS resize
incident-smoke:
	JAX_PLATFORMS=cpu python tools/incident_smoke.py

# the full smoke battery: invariant lint + exposition + resilience +
# store integrity + serving data plane + span attribution + cold-start
# economics + cross-machine megabatching + the horizontal serving tier
# + the fleet observability plane (stitching / aggregation / SLO)
# + the precision ladder (parity budgets / dtype routing / warm boots)
# + the closed-loop autopilot (convergence / journal / elastic tier)
# + the fleet-scale hot paths (index boot / spill tier / placement /
#   bounded scrape)
# + multi-host mesh serving (layout routing / fallback rung / warm boots)
# + the telemetry warehouse (traffic top-K / cost ledger / export /
#   accounting overhead)
# + multi-tenant QoS (quotas / priority classes / class-ordered sheds)
# + the declarative fleet reconciler (journaled specs / self-healing
#   convergence / WAL exactly-once disaster drills)
# + the fleet layout compiler (measured-cost plans / zero-compile live
#   apply / p99 + density gates / rollback)
# + the fleet black box (crash-safe control ledger / incident
#   root-cause attribution)
smoke: lint metrics-smoke chaos-smoke store-fsck perf-smoke trace-smoke coldstart-smoke megabatch-smoke router-smoke slo-smoke quant-smoke autopilot-smoke capacity-smoke mesh-smoke telemetry-smoke qos-smoke reconcile-smoke layout-smoke incident-smoke

images: builder-image server-image watchman-image

builder-image:
	docker build -t gordo-tpu-builder --build-arg ROLE=builder -f Dockerfile .

server-image:
	docker build -t gordo-tpu-server --build-arg ROLE=server -f Dockerfile .

watchman-image:
	docker build -t gordo-tpu-watchman --build-arg ROLE=watchman -f Dockerfile .
