"""Definition dict/YAML → live pipeline.

Reference parity: ``gordo_components/serializer/from_definition.py``
[UNVERIFIED]. A definition node is either

- a dotted path string (instantiated with no kwargs),
- ``{dotted.path.Class: {kwargs}}`` (single-key mapping), or
- inside kwargs, lists/dicts recursed into (``steps`` lists, nested
  regressors, FunctionTransformer funcs).

Ported gordo configs name ``sklearn.*`` and ``gordo_components.*`` classes;
an alias table rewrites those onto this package's TPU-native equivalents so
reference fleet YAML loads unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Union

import yaml

from ..utils.config import resolve_dotted_path

# reference-world dotted paths → TPU-native equivalents
_ALIASES: Dict[str, str] = {
    # sklearn surface the reference's configs use
    "sklearn.pipeline.Pipeline": "gordo_components_tpu.models.pipeline.Pipeline",
    "sklearn.pipeline.FeatureUnion": (
        "gordo_components_tpu.models.pipeline.FeatureUnion"
    ),
    "sklearn.compose.TransformedTargetRegressor": (
        "gordo_components_tpu.models.pipeline.TransformedTargetRegressor"
    ),
    "sklearn.preprocessing.MinMaxScaler": (
        "gordo_components_tpu.models.transformers.MinMaxScaler"
    ),
    "sklearn.preprocessing.data.MinMaxScaler": (
        "gordo_components_tpu.models.transformers.MinMaxScaler"
    ),
    "sklearn.preprocessing.StandardScaler": (
        "gordo_components_tpu.models.transformers.StandardScaler"
    ),
    "sklearn.preprocessing.data.StandardScaler": (
        "gordo_components_tpu.models.transformers.StandardScaler"
    ),
    "sklearn.preprocessing.FunctionTransformer": (
        "gordo_components_tpu.models.transformers.FunctionTransformer"
    ),
    # the reference's own package paths
    "gordo_components.model.models.KerasAutoEncoder": (
        "gordo_components_tpu.models.models.DenseAutoEncoder"
    ),
    "gordo_components.model.models.KerasLSTMAutoEncoder": (
        "gordo_components_tpu.models.models.LSTMAutoEncoder"
    ),
    "gordo_components.model.models.KerasLSTMForecast": (
        "gordo_components_tpu.models.models.LSTMForecast"
    ),
    "gordo_components.model.anomaly.diff.DiffBasedAnomalyDetector": (
        "gordo_components_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
    ),
    "gordo_components.model.transformer_funcs.general.multiply": (
        "gordo_components_tpu.models.transformers.multiply"
    ),
    "gordo_components.model.transformers.imputer.InfImputer": (
        "gordo_components_tpu.models.transformers.InfImputer"
    ),
}
# short names for the local zoo, so hand-written configs stay terse
_SHORT_NAMES: Dict[str, str] = {
    name: f"gordo_components_tpu.models.models.{name}"
    for name in (
        "DenseAutoEncoder",
        "LSTMAutoEncoder",
        "LSTMForecast",
        "MultiStepForecast",
        "PatchTSTAutoEncoder",
        "PatchTSTForecast",
        "KerasAutoEncoder",
        "KerasLSTMAutoEncoder",
        "KerasLSTMForecast",
    )
}
_SHORT_NAMES.update(
    {
        "Pipeline": "gordo_components_tpu.models.pipeline.Pipeline",
        "FeatureUnion": "gordo_components_tpu.models.pipeline.FeatureUnion",
        "TransformedTargetRegressor": (
            "gordo_components_tpu.models.pipeline.TransformedTargetRegressor"
        ),
        "MinMaxScaler": "gordo_components_tpu.models.transformers.MinMaxScaler",
        "StandardScaler": "gordo_components_tpu.models.transformers.StandardScaler",
        "InfImputer": "gordo_components_tpu.models.transformers.InfImputer",
        "FunctionTransformer": (
            "gordo_components_tpu.models.transformers.FunctionTransformer"
        ),
        "DiffBasedAnomalyDetector": (
            "gordo_components_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
        ),
    }
)


# prefixes an *untrusted* definition (one loaded from an artifact rather
# than authored by the operator) is allowed to resolve into; operators
# deploying their own plugin package may append its prefix here once at
# startup (that is an explicit trust decision, like installing the plugin)
_TRUSTED_PREFIXES: list = ["gordo_components_tpu."]


def resolve_class_path(path: str, *, allow_external: bool = True) -> Any:
    """Alias- and short-name-aware dotted-path resolution (also used by
    FunctionTransformer to resolve ``func`` strings lazily).

    ``allow_external=False`` is the artifact-load mode: resolution is
    restricted to this package (every alias/short name lands there), so a
    definition.json from a spoofed server cannot instantiate arbitrary
    importables (e.g. ``os.system``) with attacker kwargs.
    """
    path = _ALIASES.get(path, path)
    path = _SHORT_NAMES.get(path, path)
    if "." not in path:
        raise ValueError(
            f"Unknown class short name {path!r}; known: {sorted(_SHORT_NAMES)}"
        )
    if not allow_external and not path.startswith(tuple(_TRUSTED_PREFIXES)):
        raise ValueError(
            f"Refusing to resolve external dotted path {path!r} while "
            "loading an artifact: artifact definitions may only reference "
            "gordo_components_tpu classes (or their sklearn/"
            "gordo_components aliases). Rebuild the model locally, or load "
            "its definition yourself via pipeline_from_definition(...) if "
            "you authored and trust it."
        )
    return resolve_dotted_path(path)


def _is_class_definition(node: Any) -> bool:
    """A single-key mapping whose key looks like a class reference."""
    if isinstance(node, dict) and len(node) == 1:
        key = next(iter(node))
        return isinstance(key, str) and (
            key in _SHORT_NAMES or key in _ALIASES or "." in key
        )
    return False


def _build_string(s: str, allow_external: bool) -> Any:
    """Instantiate strings that resolve to classes (bare steps like
    ``sklearn.preprocessing.data.MinMaxScaler``); keep everything else —
    including function dotted paths like FunctionTransformer's ``func``,
    which resolve lazily — as plain strings."""
    if not (s in _SHORT_NAMES or s in _ALIASES or "." in s):
        return s
    try:
        target = resolve_class_path(s, allow_external=allow_external)
    except ValueError:
        if not allow_external and (s in _SHORT_NAMES or s in _ALIASES):
            raise  # a known name refused by the trust gate must not degrade
            # into a silently-passed-through string
        return s
    return target() if isinstance(target, type) else s


def _build(node: Any, allow_external: bool = True) -> Any:
    if isinstance(node, str):
        return _build_string(node, allow_external)
    if _is_class_definition(node):
        path, kwargs = next(iter(node.items()))
        target = resolve_class_path(path, allow_external=allow_external)
        if not isinstance(target, type):
            raise ValueError(f"{path!r} resolves to a non-class; cannot take kwargs")
        if kwargs is None:
            kwargs = {}
        if not isinstance(kwargs, dict):
            raise ValueError(
                f"Definition for {path!r} must map to kwargs, got {type(kwargs)}"
            )
        built_kwargs = {
            k: (
                _build_steps(v, allow_external)
                if k in ("steps", "transformer_list") and isinstance(v, list)
                else _build_value(v, allow_external)
            )
            for k, v in kwargs.items()
        }
        instance = target(**built_kwargs)
        if not allow_external:
            # lazily-resolved function strings (FunctionTransformer.func)
            # must inherit the trust gate, or 'os.system' would execute on
            # the first transform() of a loaded artifact
            try:
                instance._allow_external_funcs = False
            except AttributeError:
                pass
        return instance
    return node


def _build_steps(value: list, allow_external: bool) -> list:
    """Steps / transformer lists: a ``[name, definition]`` 2-list element is
    a NAMED step pair (into_definition writes these) — the name must stay a
    plain string even when it collides with a class short name like
    ``"MinMaxScaler"``, or the pair would degenerate into a broken bare
    step. Everything else is an ordinary (unnamed) step definition."""
    out = []
    for el in value:
        if (
            isinstance(el, list)
            and len(el) == 2
            and isinstance(el[0], str)
            and (_is_class_definition(el[1]) or isinstance(el[1], str))
        ):
            out.append((el[0], _build_value(el[1], allow_external)))
        else:
            out.append(_build_value(el, allow_external))
    return out


def _build_value(value: Any, allow_external: bool = True) -> Any:
    """Recurse into kwarg values: lists of definitions (steps lists), nested
    definitions (regressor/base_estimator), plain data otherwise."""
    if isinstance(value, str):
        return _build_string(value, allow_external)
    if _is_class_definition(value):
        return _build(value, allow_external)
    if isinstance(value, list):
        return [_build_value(v, allow_external) for v in value]
    if isinstance(value, dict):
        return {k: _build_value(v, allow_external) for k, v in value.items()}
    return value


def pipeline_from_definition(
    definition: Union[str, Dict[str, Any]], *, allow_external: bool = True
) -> Any:
    """Materialize a model definition (dict, or YAML string) into a live
    (unfitted) pipeline/estimator graph.

    ``allow_external=True`` (default) is the *build* path: the operator
    authored the config, so dotted paths outside this package are a plugin
    feature. ``allow_external=False`` is the *artifact-load* path
    (``serializer.load``/``loads``): definitions are data from disk or a
    remote server and may only reference this package's classes.
    """
    if isinstance(definition, str):
        definition = yaml.safe_load(definition)
    built = _build(definition, allow_external)
    if isinstance(built, (str, dict)) or built is definition:
        raise ValueError(
            "Model definition must be a single-key {dotted.path: kwargs} "
            f"mapping or a class dotted-path string; got: {definition!r}"
        )
    return built


# reference-era alias
from_definition = pipeline_from_definition
