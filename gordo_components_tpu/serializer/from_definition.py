"""Definition dict/YAML → live pipeline.

Reference parity: ``gordo_components/serializer/from_definition.py``
[UNVERIFIED]. A definition node is either

- a dotted path string (instantiated with no kwargs),
- ``{dotted.path.Class: {kwargs}}`` (single-key mapping), or
- inside kwargs, lists/dicts recursed into (``steps`` lists, nested
  regressors, FunctionTransformer funcs).

Ported gordo configs name ``sklearn.*`` and ``gordo_components.*`` classes;
an alias table rewrites those onto this package's TPU-native equivalents so
reference fleet YAML loads unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Union

import yaml

from ..utils.config import resolve_dotted_path

# reference-world dotted paths → TPU-native equivalents
_ALIASES: Dict[str, str] = {
    # sklearn surface the reference's configs use
    "sklearn.pipeline.Pipeline": "gordo_components_tpu.models.pipeline.Pipeline",
    "sklearn.pipeline.FeatureUnion": (
        "gordo_components_tpu.models.pipeline.FeatureUnion"
    ),
    "sklearn.compose.TransformedTargetRegressor": (
        "gordo_components_tpu.models.pipeline.TransformedTargetRegressor"
    ),
    "sklearn.preprocessing.MinMaxScaler": (
        "gordo_components_tpu.models.transformers.MinMaxScaler"
    ),
    "sklearn.preprocessing.data.MinMaxScaler": (
        "gordo_components_tpu.models.transformers.MinMaxScaler"
    ),
    "sklearn.preprocessing.StandardScaler": (
        "gordo_components_tpu.models.transformers.StandardScaler"
    ),
    "sklearn.preprocessing.data.StandardScaler": (
        "gordo_components_tpu.models.transformers.StandardScaler"
    ),
    "sklearn.preprocessing.FunctionTransformer": (
        "gordo_components_tpu.models.transformers.FunctionTransformer"
    ),
    # the reference's own package paths
    "gordo_components.model.models.KerasAutoEncoder": (
        "gordo_components_tpu.models.models.DenseAutoEncoder"
    ),
    "gordo_components.model.models.KerasLSTMAutoEncoder": (
        "gordo_components_tpu.models.models.LSTMAutoEncoder"
    ),
    "gordo_components.model.models.KerasLSTMForecast": (
        "gordo_components_tpu.models.models.LSTMForecast"
    ),
    "gordo_components.model.anomaly.diff.DiffBasedAnomalyDetector": (
        "gordo_components_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
    ),
    "gordo_components.model.transformer_funcs.general.multiply": (
        "gordo_components_tpu.models.transformers.multiply"
    ),
    "gordo_components.model.transformers.imputer.InfImputer": (
        "gordo_components_tpu.models.transformers.InfImputer"
    ),
}
# short names for the local zoo, so hand-written configs stay terse
_SHORT_NAMES: Dict[str, str] = {
    name: f"gordo_components_tpu.models.models.{name}"
    for name in (
        "DenseAutoEncoder",
        "LSTMAutoEncoder",
        "LSTMForecast",
        "PatchTSTAutoEncoder",
        "PatchTSTForecast",
        "KerasAutoEncoder",
        "KerasLSTMAutoEncoder",
        "KerasLSTMForecast",
    )
}
_SHORT_NAMES.update(
    {
        "Pipeline": "gordo_components_tpu.models.pipeline.Pipeline",
        "FeatureUnion": "gordo_components_tpu.models.pipeline.FeatureUnion",
        "TransformedTargetRegressor": (
            "gordo_components_tpu.models.pipeline.TransformedTargetRegressor"
        ),
        "MinMaxScaler": "gordo_components_tpu.models.transformers.MinMaxScaler",
        "StandardScaler": "gordo_components_tpu.models.transformers.StandardScaler",
        "InfImputer": "gordo_components_tpu.models.transformers.InfImputer",
        "FunctionTransformer": (
            "gordo_components_tpu.models.transformers.FunctionTransformer"
        ),
        "DiffBasedAnomalyDetector": (
            "gordo_components_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
        ),
    }
)


def resolve_class_path(path: str) -> Any:
    """Alias- and short-name-aware dotted-path resolution (also used by
    FunctionTransformer to resolve ``func`` strings lazily)."""
    path = _ALIASES.get(path, path)
    path = _SHORT_NAMES.get(path, path)
    if "." not in path:
        raise ValueError(
            f"Unknown class short name {path!r}; known: {sorted(_SHORT_NAMES)}"
        )
    return resolve_dotted_path(path)


def _is_class_definition(node: Any) -> bool:
    """A single-key mapping whose key looks like a class reference."""
    if isinstance(node, dict) and len(node) == 1:
        key = next(iter(node))
        return isinstance(key, str) and (
            key in _SHORT_NAMES or key in _ALIASES or "." in key
        )
    return False


def _build_string(s: str) -> Any:
    """Instantiate strings that resolve to classes (bare steps like
    ``sklearn.preprocessing.data.MinMaxScaler``); keep everything else —
    including function dotted paths like FunctionTransformer's ``func``,
    which resolve lazily — as plain strings."""
    if not (s in _SHORT_NAMES or s in _ALIASES or "." in s):
        return s
    try:
        target = resolve_class_path(s)
    except ValueError:
        return s
    return target() if isinstance(target, type) else s


def _build(node: Any) -> Any:
    if isinstance(node, str):
        return _build_string(node)
    if _is_class_definition(node):
        path, kwargs = next(iter(node.items()))
        target = resolve_class_path(path)
        if not isinstance(target, type):
            raise ValueError(f"{path!r} resolves to a non-class; cannot take kwargs")
        if kwargs is None:
            kwargs = {}
        if not isinstance(kwargs, dict):
            raise ValueError(
                f"Definition for {path!r} must map to kwargs, got {type(kwargs)}"
            )
        return target(**{k: _build_value(v) for k, v in kwargs.items()})
    return node


def _build_value(value: Any) -> Any:
    """Recurse into kwarg values: lists of definitions (steps lists), nested
    definitions (regressor/base_estimator), plain data otherwise."""
    if isinstance(value, str):
        return _build_string(value)
    if _is_class_definition(value):
        return _build(value)
    if isinstance(value, list):
        return [_build_value(v) for v in value]
    if isinstance(value, dict):
        return {k: _build_value(v) for k, v in value.items()}
    return value


def pipeline_from_definition(definition: Union[str, Dict[str, Any]]) -> Any:
    """Materialize a model definition (dict, or YAML string) into a live
    (unfitted) pipeline/estimator graph."""
    if isinstance(definition, str):
        definition = yaml.safe_load(definition)
    built = _build(definition)
    if isinstance(built, (str, dict)) or built is definition:
        raise ValueError(
            "Model definition must be a single-key {dotted.path: kwargs} "
            f"mapping or a class dotted-path string; got: {definition!r}"
        )
    return built


# reference-era alias
from_definition = pipeline_from_definition
