"""Serializer: YAML/dict model definitions ⇄ live pipelines ⇄ disk artifacts.

Reference parity: ``gordo_components/serializer/`` [UNVERIFIED] —
``pipeline_from_definition`` / ``pipeline_into_definition`` (the config
system's heart: dotted-path classes + kwargs, recursively) and ``dump`` /
``load`` persisting a fitted pipeline to a directory tree, plus
``load_metadata``. The artifact format here is pure-state: per-step numpy
``.npz`` + JSON (no pickle on the load path), which is what lets a serving
process mmap many machines' params and the fleet engine stack them.
"""

from .from_definition import pipeline_from_definition, from_definition
from .into_definition import pipeline_into_definition, into_definition
from .persistence import dump, dumps, load, loads, load_metadata, METADATA_FILE

__all__ = [
    "pipeline_from_definition",
    "from_definition",
    "pipeline_into_definition",
    "into_definition",
    "dump",
    "dumps",
    "load",
    "loads",
    "load_metadata",
    "METADATA_FILE",
]
