"""Disk persistence for fitted pipelines.

Reference parity: ``gordo_components/serializer/__init__.py`` dump/load —
the reference persists a dir tree of per-step pickles + keras HDF5
[UNVERIFIED]. Here the artifact is pure-state and pickle-free on the load
path:

```
model_dir/
  definition.json       # into_definition output (class graph + kwargs)
  state.npz             # every fitted array, flattened "step/sub/key" paths
  state_meta.json       # non-array fitted state (history, shapes, …)
  metadata.json         # caller-provided build metadata (optional)
  MANIFEST.json         # per-file SHA-256 + size + format version (store/)
```

Crash-safety contract (``store/``): ``dump`` stages into a hidden sibling
dir, fsyncs everything, writes the checksummed manifest, and renames into
place — a crash leaves the destination untouched. ``load`` VERIFIES the
manifest before deserializing anything and raises the store's typed
errors (``ManifestMissing`` / ``ArtifactIncomplete`` / ``ArtifactCorrupt``)
on any disagreement — a torn artifact is an exception, never a silently
half-loaded pipeline. ``load``/``load_metadata`` also resolve generation
roots (``CURRENT`` → ``gen-NNNN/``), so callers can hold one path per
machine whichever layout it uses.

``dumps``/``loads`` wrap the same format in an in-memory tar for the
``/download-model`` endpoint and client-side reloads. ``dumps`` is
byte-deterministic (zeroed tar/gzip/zip timestamps and ownership, sorted
members), so the same artifact always produces an identical blob and a
downloaded model's manifest hashes match the server's. ``loads`` bounds
extraction (member count, total decompressed bytes, duplicate names) so
a spoofed server cannot decompression-bomb the client.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import zipfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..store.atomic import atomic_commit
from ..store.generations import resolve_artifact_dir
from ..store.manifest import verify_artifact
from .from_definition import pipeline_from_definition
from .into_definition import pipeline_into_definition

METADATA_FILE = "metadata.json"
DEFINITION_FILE = "definition.json"
STATE_FILE = "state.npz"
STATE_META_FILE = "state_meta.json"
_SEP = "/"

# tar-extraction bounds for loads(): an artifact is ≤ 5 files, so a blob
# claiming hundreds of members or absurd decompressed sizes is an attack
# (or corruption), not a model. Total-bytes ceiling is env-tunable for
# genuinely huge plant fleets.
MAX_TAR_MEMBERS = 128
MAX_TAR_TOTAL_BYTES_ENV = "GORDO_MAX_ARTIFACT_BYTES"
DEFAULT_MAX_TAR_TOTAL_BYTES = 2 << 30  # 2 GiB

# fixed zip timestamp (the ZIP epoch) for deterministic state.npz bytes
_ZIP_EPOCH = (1980, 1, 1, 0, 0, 0)


def _flatten_state(
    state: Dict[str, Any], prefix: str = ""
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    arrays: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Any] = {}
    for key, value in state.items():
        if _SEP in str(key):
            raise ValueError(f"State key {key!r} must not contain {_SEP!r}")
        path = f"{prefix}{_SEP}{key}" if prefix else str(key)
        if isinstance(value, dict):
            sub_arrays, sub_scalars = _flatten_state(value, path)
            arrays.update(sub_arrays)
            scalars.update(sub_scalars)
        elif hasattr(value, "__array__") and not isinstance(value, (int, float, bool)):
            arrays[path] = np.asarray(value)
        else:
            scalars[path] = value
    return arrays, scalars


def _unflatten_state(
    arrays: Dict[str, np.ndarray], scalars: Dict[str, Any]
) -> Dict[str, Any]:
    state: Dict[str, Any] = {}
    for path, value in list(arrays.items()) + list(scalars.items()):
        parts = path.split(_SEP)
        node = state
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return state


def _write_state_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """``np.savez`` twin with DETERMINISTIC bytes: numpy stamps each zip
    member with the wall clock, so two saves of identical arrays differ —
    which would break manifest-hash comparison between a server's artifact
    and its ``/download-model`` blob. Same format (``np.load`` reads it),
    fixed ZIP-epoch timestamps, sorted member order."""
    from numpy.lib import format as npformat

    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED, allowZip64=True) as zf:
        for name in sorted(arrays):
            buffer = io.BytesIO()
            npformat.write_array(
                buffer, np.asarray(arrays[name]), allow_pickle=False
            )
            info = zipfile.ZipInfo(name + ".npy", date_time=_ZIP_EPOCH)
            info.external_attr = 0o644 << 16
            zf.writestr(info, buffer.getvalue())


def write_artifact_files(
    obj: Any,
    dest_dir: str,
    metadata: Optional[Dict[str, Any]] = None,
    precision: Optional[str] = None,
) -> None:
    """Write the raw artifact files (NO atomicity, NO manifest) into an
    existing directory — the writer the store's staged commits wrap. Only
    :func:`dump` and ``store.commit_generation`` callers should use this
    directly.

    ``precision``: the machine's rung on the precision ladder (§19).
    ``"int8"`` additionally writes ``quant_int8.npz`` — the per-tensor
    quantized weights + scales — beside ``state.npz``, through the same
    staged commit, so the manifest hashes it like every other artifact
    file. The f32 state file is always written untouched (the host path
    and any future re-precision build read it)."""
    from .. import precision as precision_mod

    definition = pipeline_into_definition(obj)
    with open(os.path.join(dest_dir, DEFINITION_FILE), "w") as fh:
        json.dump(definition, fh, indent=2)
    state = obj.get_state() if hasattr(obj, "get_state") else {}
    arrays, scalars = _flatten_state(state)
    _write_state_npz(os.path.join(dest_dir, STATE_FILE), arrays)
    with open(os.path.join(dest_dir, STATE_META_FILE), "w") as fh:
        json.dump(scalars, fh, indent=2, sort_keys=True)
    if precision_mod.validate(precision) == "int8":
        quant = precision_mod.quantized_arrays_for(obj)
        if quant is not None:
            _write_state_npz(
                os.path.join(dest_dir, precision_mod.QUANT_INT8_FILE), quant
            )
    if metadata is not None:
        with open(os.path.join(dest_dir, METADATA_FILE), "w") as fh:
            json.dump(metadata, fh, indent=2, default=str)


def dump(obj: Any, dest_dir: str, metadata: Optional[Dict[str, Any]] = None) -> str:
    """Persist a fitted pipeline/estimator to ``dest_dir``; returns the dir.

    All-or-nothing: files are staged in a hidden sibling dir, fsync'd,
    manifested (per-file SHA-256 — see ``store/``), and renamed into
    place. A crash mid-dump leaves any previous ``dest_dir`` content
    untouched and serving."""
    with atomic_commit(dest_dir, name=os.path.basename(dest_dir)) as staging:
        write_artifact_files(obj, staging, metadata=metadata)
    return dest_dir


def load(source_dir: str, *, allow_external: bool = False) -> Any:
    """Rebuild the fitted pipeline persisted by :func:`dump`.

    Integrity first: the artifact's manifest is verified (every file
    present, sizes and SHA-256 matching) BEFORE anything is deserialized;
    a torn or tampered artifact raises the store's typed errors
    (``ManifestMissing`` / ``ArtifactIncomplete`` / ``ArtifactCorrupt`` —
    all ``StoreError``), which the server maps to quarantine rather than
    a 500. Generation roots resolve through their ``CURRENT`` pointer.

    The artifact's definition is treated as *data*, not config: by default
    class/function resolution is restricted to this package, so a tampered
    ``definition.json`` (e.g. fetched from a spoofed server via
    ``/download-model``) cannot instantiate arbitrary importables.
    Artifacts that legitimately reference an external plugin class load
    with ``allow_external=True`` (an explicit trust statement about the
    artifact), or after appending the plugin's package prefix to
    ``from_definition._TRUSTED_PREFIXES`` once at startup.
    """
    source_dir = resolve_artifact_dir(source_dir)
    verify_artifact(source_dir)
    with open(os.path.join(source_dir, DEFINITION_FILE)) as fh:
        definition = json.load(fh)
    obj = pipeline_from_definition(definition, allow_external=allow_external)
    with np.load(os.path.join(source_dir, STATE_FILE)) as npz:
        arrays = {key: npz[key] for key in npz.files}
    scalars: Dict[str, Any] = {}
    meta_path = os.path.join(source_dir, STATE_META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            scalars = json.load(fh)
    state = _unflatten_state(arrays, scalars)
    if hasattr(obj, "set_state"):
        obj.set_state(state)
    return obj


def load_metadata(source_dir: str) -> Dict[str, Any]:
    try:
        source_dir = resolve_artifact_dir(source_dir)
    except Exception:  # lint: allow-swallow(torn generation root: metadata is best-effort context; verified load is the loud path)
        return {}
    path = os.path.join(source_dir, METADATA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return json.load(fh)


def dumps(obj: Any, metadata: Optional[Dict[str, Any]] = None) -> bytes:
    """Single-blob form of :func:`dump` (in-memory tar) — the payload of the
    server's ``GET /download-model``.

    Byte-deterministic: tar headers carry zeroed mtime/uid/gid/ownership,
    members are sorted, the gzip wrapper's mtime is zeroed, and the inner
    ``state.npz`` uses fixed zip timestamps — so the same fitted object
    always produces an identical blob, and its per-file manifest hashes
    match the server's on-disk artifact."""
    import gzip
    import tempfile

    buffer = io.BytesIO()
    with tempfile.TemporaryDirectory() as tmp:
        dump(obj, tmp, metadata=metadata)
        with gzip.GzipFile(fileobj=buffer, mode="wb", mtime=0) as gz:
            with tarfile.open(fileobj=gz, mode="w") as tar:
                for name in sorted(os.listdir(tmp)):
                    path = os.path.join(tmp, name)
                    info = tar.gettarinfo(path, arcname=name)
                    info.mtime = 0
                    info.uid = info.gid = 0
                    info.uname = info.gname = ""
                    info.mode = 0o644
                    with open(path, "rb") as fh:
                        tar.addfile(info, fh)
    return buffer.getvalue()


def _max_tar_total_bytes() -> int:
    raw = os.environ.get(MAX_TAR_TOTAL_BYTES_ENV, "")
    return int(raw) if raw else DEFAULT_MAX_TAR_TOTAL_BYTES


def _check_tar_bounds(tar: tarfile.TarFile) -> None:
    """Pre-extraction guard rails: a spoofed ``/download-model`` response
    must not be able to decompression-bomb the client. Header-declared
    sizes are authoritative for extraction (tarfile reads exactly
    ``member.size`` bytes per member), so checking headers bounds the
    bytes written. Duplicate member names are rejected outright — the
    last-wins overwrite they imply is only ever an attack.

    Streams member headers one at a time and bails at the FIRST violation
    — ``getmembers()`` up front would itself be bombable (a few-MB gzip
    blob can declare millions of zero-size members, and materializing a
    ``TarInfo`` per header OOMs the guard before any limit is checked)."""
    limit = _max_tar_total_bytes()
    count = 0
    total = 0
    seen = set()
    while True:
        member = tar.next()
        if member is None:
            break
        count += 1
        if count > MAX_TAR_MEMBERS:
            raise ValueError(
                f"Artifact tar has over {MAX_TAR_MEMBERS} members; a model "
                "artifact has at most a handful — refusing to extract"
            )
        total += max(0, member.size)
        if total > limit:
            raise ValueError(
                f"Artifact tar declares over {limit} decompressed bytes "
                f"({MAX_TAR_TOTAL_BYTES_ENV} to raise) — refusing to extract"
            )
        name = os.path.normpath(member.name)
        if name in seen:
            raise ValueError(
                f"Artifact tar repeats member {member.name!r} — refusing "
                "to extract (duplicate names imply overwrite games)"
            )
        seen.add(name)


def loads(blob: bytes, *, allow_external: bool = False) -> Any:
    """Inverse of :func:`dumps` (same trust gate as :func:`load`)."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
            _check_tar_bounds(tar)
            try:
                tar.extractall(tmp, filter="data")
            except TypeError:
                # Python < 3.10.12/3.11.4 lacks extractall(filter=); apply
                # the same path-traversal guard manually rather than
                # extracting unfiltered
                _safe_extract(tar, tmp)
        return load(tmp, allow_external=allow_external)


def _safe_extract(tar: tarfile.TarFile, dest: str) -> None:
    """Manual equivalent of ``filter="data"``: plain files/dirs only, no
    absolute paths, no ``..`` escapes, no links."""
    dest_real = os.path.realpath(dest)
    for member in tar.getmembers():
        if not (member.isfile() or member.isdir()):
            raise ValueError(
                f"Refusing to extract non-regular member {member.name!r}"
            )
        target = os.path.realpath(os.path.join(dest, member.name))
        if not (target == dest_real or target.startswith(dest_real + os.sep)):
            raise ValueError(
                f"Refusing to extract {member.name!r} outside target dir"
            )
    tar.extractall(dest)
