"""Disk persistence for fitted pipelines.

Reference parity: ``gordo_components/serializer/__init__.py`` dump/load —
the reference persists a dir tree of per-step pickles + keras HDF5
[UNVERIFIED]. Here the artifact is pure-state and pickle-free on the load
path:

```
model_dir/
  definition.json       # into_definition output (class graph + kwargs)
  state.npz             # every fitted array, flattened "step/sub/key" paths
  state_meta.json       # non-array fitted state (history, shapes, …)
  metadata.json         # caller-provided build metadata (optional)
```

``dumps``/``loads`` wrap the same format in an in-memory tar for the
``/download-model`` endpoint and client-side reloads.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .from_definition import pipeline_from_definition
from .into_definition import pipeline_into_definition

METADATA_FILE = "metadata.json"
DEFINITION_FILE = "definition.json"
STATE_FILE = "state.npz"
STATE_META_FILE = "state_meta.json"
_SEP = "/"


def _flatten_state(
    state: Dict[str, Any], prefix: str = ""
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    arrays: Dict[str, np.ndarray] = {}
    scalars: Dict[str, Any] = {}
    for key, value in state.items():
        if _SEP in str(key):
            raise ValueError(f"State key {key!r} must not contain {_SEP!r}")
        path = f"{prefix}{_SEP}{key}" if prefix else str(key)
        if isinstance(value, dict):
            sub_arrays, sub_scalars = _flatten_state(value, path)
            arrays.update(sub_arrays)
            scalars.update(sub_scalars)
        elif hasattr(value, "__array__") and not isinstance(value, (int, float, bool)):
            arrays[path] = np.asarray(value)
        else:
            scalars[path] = value
    return arrays, scalars


def _unflatten_state(
    arrays: Dict[str, np.ndarray], scalars: Dict[str, Any]
) -> Dict[str, Any]:
    state: Dict[str, Any] = {}
    for path, value in list(arrays.items()) + list(scalars.items()):
        parts = path.split(_SEP)
        node = state
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return state


def dump(obj: Any, dest_dir: str, metadata: Optional[Dict[str, Any]] = None) -> str:
    """Persist a fitted pipeline/estimator to ``dest_dir``; returns the dir."""
    os.makedirs(dest_dir, exist_ok=True)
    definition = pipeline_into_definition(obj)
    with open(os.path.join(dest_dir, DEFINITION_FILE), "w") as fh:
        json.dump(definition, fh, indent=2)
    state = obj.get_state() if hasattr(obj, "get_state") else {}
    arrays, scalars = _flatten_state(state)
    np.savez(os.path.join(dest_dir, STATE_FILE), **arrays)
    with open(os.path.join(dest_dir, STATE_META_FILE), "w") as fh:
        json.dump(scalars, fh, indent=2)
    if metadata is not None:
        with open(os.path.join(dest_dir, METADATA_FILE), "w") as fh:
            json.dump(metadata, fh, indent=2, default=str)
    return dest_dir


def load(source_dir: str, *, allow_external: bool = False) -> Any:
    """Rebuild the fitted pipeline persisted by :func:`dump`.

    The artifact's definition is treated as *data*, not config: by default
    class/function resolution is restricted to this package, so a tampered
    ``definition.json`` (e.g. fetched from a spoofed server via
    ``/download-model``) cannot instantiate arbitrary importables.
    Artifacts that legitimately reference an external plugin class load
    with ``allow_external=True`` (an explicit trust statement about the
    artifact), or after appending the plugin's package prefix to
    ``from_definition._TRUSTED_PREFIXES`` once at startup.
    """
    with open(os.path.join(source_dir, DEFINITION_FILE)) as fh:
        definition = json.load(fh)
    obj = pipeline_from_definition(definition, allow_external=allow_external)
    with np.load(os.path.join(source_dir, STATE_FILE)) as npz:
        arrays = {key: npz[key] for key in npz.files}
    scalars: Dict[str, Any] = {}
    meta_path = os.path.join(source_dir, STATE_META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as fh:
            scalars = json.load(fh)
    state = _unflatten_state(arrays, scalars)
    if hasattr(obj, "set_state"):
        obj.set_state(state)
    return obj


def load_metadata(source_dir: str) -> Dict[str, Any]:
    path = os.path.join(source_dir, METADATA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        return json.load(fh)


def dumps(obj: Any, metadata: Optional[Dict[str, Any]] = None) -> bytes:
    """Single-blob form of :func:`dump` (in-memory tar) — the payload of the
    server's ``GET /download-model``."""
    import tempfile

    buffer = io.BytesIO()
    with tempfile.TemporaryDirectory() as tmp:
        dump(obj, tmp, metadata=metadata)
        with tarfile.open(fileobj=buffer, mode="w:gz") as tar:
            for name in sorted(os.listdir(tmp)):
                tar.add(os.path.join(tmp, name), arcname=name)
    return buffer.getvalue()


def loads(blob: bytes, *, allow_external: bool = False) -> Any:
    """Inverse of :func:`dumps` (same trust gate as :func:`load`)."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tar:
            try:
                tar.extractall(tmp, filter="data")
            except TypeError:
                # Python < 3.10.12/3.11.4 lacks extractall(filter=); apply
                # the same path-traversal guard manually rather than
                # extracting unfiltered
                _safe_extract(tar, tmp)
        return load(tmp, allow_external=allow_external)


def _safe_extract(tar: tarfile.TarFile, dest: str) -> None:
    """Manual equivalent of ``filter="data"``: plain files/dirs only, no
    absolute paths, no ``..`` escapes, no links."""
    dest_real = os.path.realpath(dest)
    for member in tar.getmembers():
        if not (member.isfile() or member.isdir()):
            raise ValueError(
                f"Refusing to extract non-regular member {member.name!r}"
            )
        target = os.path.realpath(os.path.join(dest, member.name))
        if not (target == dest_real or target.startswith(dest_real + os.sep)):
            raise ValueError(
                f"Refusing to extract {member.name!r} outside target dir"
            )
    tar.extractall(dest)
