"""Live pipeline → definition dict (inverse of ``from_definition``).

Reference parity: ``gordo_components/serializer/into_definition.py``
[UNVERIFIED]. Walks ``get_params`` recursively, emitting
``{dotted.path.Class: {kwargs}}`` nodes — the round-trip
``from_definition(into_definition(p))`` must reproduce an equivalent
unfitted pipeline (pinned in tests/test_serializer.py).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np
import yaml


def _class_path(obj: Any) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _plain(value: Any) -> Any:
    """JSON/YAML-safe conversion of a kwarg value."""
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, tuple):
        return [_plain(v) for v in value]
    if isinstance(value, list):
        return [_plain(v) for v in value]
    if isinstance(value, dict):
        return {k: _plain(v) for k, v in value.items()}
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if hasattr(value, "get_params"):
        return _definition_of(value)
    raise ValueError(
        f"Cannot serialize {value!r} ({type(value)}) into a definition"
    )


def _definition_of(obj: Any) -> Dict[str, Any]:
    params = obj.get_params(deep=False) if _takes_deep(obj) else obj.get_params()
    kwargs: Dict[str, Any] = {}
    for key, value in params.items():
        if key in ("steps", "transformer_list") and isinstance(value, list):
            # Pipeline steps / FeatureUnion transformers: [(name, est), …]
            # → [name, definition] pairs. Names must survive the round-trip:
            # FeatureUnion.transformer_weights is keyed by them
            # (from_definition rebuilds pairs via _name_steps)
            kwargs[key] = [
                (
                    [step[0], _definition_of(step[1])]
                    if isinstance(step, (tuple, list))
                    else _definition_of(step)
                )
                for step in value
            ]
        else:
            kwargs[key] = _plain(value)
    return {_class_path(obj): kwargs}


def _takes_deep(obj: Any) -> bool:
    try:
        import inspect

        return "deep" in inspect.signature(obj.get_params).parameters
    except (TypeError, ValueError):
        return False


def pipeline_into_definition(pipeline: Any) -> Dict[str, Any]:
    """Serialize an (un)fitted pipeline/estimator graph back into the
    definition-dict shape ``pipeline_from_definition`` accepts."""
    return _definition_of(pipeline)


def into_definition_yaml(pipeline: Any) -> str:
    return yaml.safe_dump(pipeline_into_definition(pipeline), sort_keys=False)


# reference-era alias
into_definition = pipeline_into_definition
