"""The ``gordo-layout-plan/v1`` contract: validator, fingerprint, explain.

Dependency-free on purpose (stdlib only, no engine/server imports): the
spec journal validates plans at parse time and the reconciler validates
them at apply time, and neither may grow a heavyweight import for it.
The document shape is a CONTRACT — bump :data:`PLAN_SCHEMA` on any
breaking change; additive optional fields keep v1.

A plan carries four decisions plus their provenance:

- ``weights``   — per-worker ring weight overrides (1.0 = uniform)
- ``residency`` — per-worker resident machine sets + the expected hit
  rate the cost model predicts for them (optional ``cap`` resizes the
  megabatch residency height fleet-wide)
- ``precision`` — per-machine precision rung downgrades, chosen within
  the traffic × parity budget
- ``prefetch``  — per-worker spill-tier warm hints (non-resident but
  non-trivial machines)

``source`` records WHAT the plan was computed from (input schema,
horizon, total rps, the top machine rates) so staleness can be judged
without re-finding the original telemetry; ``cost`` records the model's
baseline-vs-plan projection so ``explain`` can say why; ``moves`` names
every machine whose primary worker changed.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List

PLAN_SCHEMA = "gordo-layout-plan/v1"

#: the decision fields hashed into the fingerprint — provenance and
#: projections (source/cost/moves/generated_t) are EXCLUDED so two plans
#: that would drive the fleet identically share a fingerprint even when
#: computed from different telemetry ticks
FINGERPRINT_FIELDS = ("workers", "weights", "residency", "precision",
                      "prefetch")

_VALID_RUNGS = ("f32", "bf16", "int8")


def plan_fingerprint(plan: Dict[str, Any]) -> str:
    """Canonical sha1 over the plan's DECISION fields (sorted-key JSON,
    no whitespace drift). This is the identity workers report back in
    ``/healthz`` and the reconciler converges on."""
    decisions = {key: plan.get(key) for key in FINGERPRINT_FIELDS}
    blob = json.dumps(decisions, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def validate_layout_plan(doc: Any) -> List[str]:
    """Schema check for a layout plan, dependency-free. Returns a list
    of problems — empty means the document honours the v1 contract.
    Validation is STRUCTURAL only: machines or workers that no longer
    exist in the live fleet are an application-time degrade (skip), not
    a validation error — a stale-but-well-formed plan must never wedge
    the spec journal or the reconciler."""
    problems: List[str] = []

    def num(value: Any) -> bool:
        return isinstance(value, (int, float)) and not isinstance(value, bool)

    if not isinstance(doc, dict):
        return ["plan is not an object"]
    if doc.get("schema") != PLAN_SCHEMA:
        problems.append(
            f"schema: expected {PLAN_SCHEMA!r}, got {doc.get('schema')!r}"
        )
    if not isinstance(doc.get("fingerprint"), str) or not doc.get(
        "fingerprint"
    ):
        problems.append("fingerprint: missing or not a string")
    if not num(doc.get("generated_t")):
        problems.append("generated_t: missing or not a number")
    workers = doc.get("workers")
    if not isinstance(workers, list) or not all(
        isinstance(w, str) and w for w in workers
    ):
        problems.append("workers: missing or not a list of names")
        workers = []
    weights = doc.get("weights")
    if not isinstance(weights, dict):
        problems.append("weights: missing or not a map")
    else:
        for worker, weight in weights.items():
            if not num(weight) or weight <= 0:
                problems.append(f"weights[{worker}]: not a positive number")
    residency = doc.get("residency")
    if not isinstance(residency, dict):
        problems.append("residency: missing or not an object")
    else:
        cap = residency.get("cap")
        if cap is not None and (not num(cap) or cap < 0):
            problems.append("residency.cap: not a non-negative number")
        per_worker = residency.get("workers")
        if not isinstance(per_worker, dict):
            problems.append("residency.workers: missing or not a map")
        else:
            for worker, entry in per_worker.items():
                if not isinstance(entry, dict):
                    problems.append(
                        f"residency.workers[{worker}]: not an object"
                    )
                    continue
                resident = entry.get("resident")
                if not isinstance(resident, list) or not all(
                    isinstance(m, str) for m in resident
                ):
                    problems.append(
                        f"residency.workers[{worker}].resident: not a list "
                        "of machine names"
                    )
                hit = entry.get("expected_hit_rate")
                if hit is not None and (not num(hit) or not 0 <= hit <= 1):
                    problems.append(
                        f"residency.workers[{worker}].expected_hit_rate: "
                        "not in [0, 1]"
                    )
    precision = doc.get("precision")
    if not isinstance(precision, dict):
        problems.append("precision: missing or not a map")
    else:
        for machine, rung in precision.items():
            if rung not in _VALID_RUNGS:
                problems.append(
                    f"precision[{machine}]: {rung!r} is not one of "
                    f"{_VALID_RUNGS}"
                )
    prefetch = doc.get("prefetch")
    if not isinstance(prefetch, dict):
        problems.append("prefetch: missing or not a map")
    else:
        for worker, names in prefetch.items():
            if not isinstance(names, list) or not all(
                isinstance(m, str) for m in names
            ):
                problems.append(
                    f"prefetch[{worker}]: not a list of machine names"
                )
    source = doc.get("source")
    if source is not None and not isinstance(source, dict):
        problems.append("source: not an object")
    if not problems and isinstance(doc.get("fingerprint"), str):
        expected = plan_fingerprint(doc)
        if doc["fingerprint"] != expected:
            problems.append(
                f"fingerprint: {doc['fingerprint']!r} does not match the "
                f"decision fields (expected {expected!r}) — plan was edited "
                "after emission"
            )
    return problems


def explain_plan(plan: Dict[str, Any]) -> str:
    """Human rendering of a plan: what was decided, from what evidence,
    and WHY each machine moved. Pure function of the plan document —
    works offline on a saved artifact."""
    lines: List[str] = []
    source = plan.get("source") or {}
    lines.append(
        f"layout plan {plan.get('fingerprint', '?')} "
        f"(schema {plan.get('schema', '?')})"
    )
    lines.append(
        f"  computed over horizon {source.get('horizon', '?')} "
        f"({source.get('total_rps', 0.0):.1f} rps total, "
        f"{len(source.get('rates') or {})} machines measured)"
    )
    cost = plan.get("cost") or {}
    baseline, projected = cost.get("baseline") or {}, cost.get("plan") or {}
    if baseline and projected:
        lines.append(
            "  cost: load imbalance {:.2f} -> {:.2f}, expected hit rate "
            "{:.0%} -> {:.0%}, machines/GiB {:.1f} -> {:.1f}".format(
                baseline.get("imbalance", 0.0),
                projected.get("imbalance", 0.0),
                baseline.get("expected_hit_rate", 0.0),
                projected.get("expected_hit_rate", 0.0),
                baseline.get("machines_per_gib", 0.0),
                projected.get("machines_per_gib", 0.0),
            )
        )
    weights = plan.get("weights") or {}
    if weights:
        rendered = ", ".join(
            f"{worker}={weight:g}" for worker, weight in sorted(
                weights.items()
            )
        )
        lines.append(f"  ring weights: {rendered}")
    else:
        lines.append("  ring weights: uniform (no overrides)")
    residency = (plan.get("residency") or {}).get("workers") or {}
    for worker in sorted(residency):
        entry = residency[worker] or {}
        resident = entry.get("resident") or []
        hit = entry.get("expected_hit_rate")
        lines.append(
            f"  {worker}: {len(resident)} resident"
            + (f" (expected hit rate {hit:.0%})" if hit is not None else "")
            + (": " + ", ".join(resident[:6]) if resident else "")
            + (" ..." if len(resident) > 6 else "")
        )
    precision = plan.get("precision") or {}
    if precision:
        for machine in sorted(precision):
            lines.append(f"  precision: {machine} -> {precision[machine]}")
    moves = plan.get("moves") or []
    if moves:
        lines.append(f"  {len(moves)} machine(s) moved:")
        for move in moves:
            lines.append(
                f"    {move.get('machine')}: {move.get('from', '?')} -> "
                f"{move.get('to', '?')} ({move.get('reason', 'rebalance')})"
            )
    else:
        lines.append("  no machines moved")
    return "\n".join(lines)
