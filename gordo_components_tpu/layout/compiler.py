"""Compile a ``gordo-layout-input/v1`` document into a layout plan.

The compiler is DETERMINISTIC: same input document + same parameters →
byte-identical plan (and therefore the same fingerprint). Nothing here
reads a clock or RNG — ``generated_t`` is copied from the input doc,
iteration orders are sorted, and weights are quantized to 1/32 so
floating-point noise cannot leak into the artifact.

Placement optimization simulates the REAL ring (``HashRing`` from
router.placement — pure stdlib) under candidate weight vectors, so what
the plan promises is exactly what ``Placement.set_worker_weights``
produces at apply time. The loop is a damped multiplicative-weights
rebalance: a few rounds of ``weight *= (mean/load)^0.5`` against the
measured per-machine rates, keeping the best-scoring round. Bounded
key movement is inherited from the ring (a weight change resizes only
that worker's arcs), so even a large rebalance moves few machines.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from ..observability.telemetry import validate_layout_input
from ..router.placement import HashRing
from .costmodel import CostModel
from .plan import PLAN_SCHEMA, plan_fingerprint

#: compiler weight clamp — tighter than the ring's own [0.1, 8.0] guard
#: rail: a computed plan should nudge shares, not starve a worker
_WEIGHT_MIN, _WEIGHT_MAX = 0.25, 4.0
_WEIGHT_GRAIN = 32.0  # quantize to 1/32 — determinism + readable plans
_REBALANCE_ROUNDS = 6
#: prefetch hints per worker: enough to pre-warm the next-hottest spill
#: machines without turning the hint into a full fleet load
_PREFETCH_PER_WORKER = 4
#: machine rates recorded into plan.source for the drift check
_SOURCE_RATES_TOP = 64

#: parity budget each downgraded rung spends, per unit of traffic share
#: (matches precision._DEFAULT_BUDGETS — the quant smoke's measured
#: normalized-error budgets)
_RUNG_PARITY_COST = {"bf16": 0.02, "int8": 0.08}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return default


def _quantize(weight: float) -> float:
    weight = min(_WEIGHT_MAX, max(_WEIGHT_MIN, weight))
    return round(weight * _WEIGHT_GRAIN) / _WEIGHT_GRAIN


def _assignment(ring: HashRing, machines: List[str]) -> Dict[str, str]:
    return {
        machine: ring.primary(machine) or "" for machine in machines
    }


def _resident_sets(
    assignment: Dict[str, str],
    rates: Dict[str, float],
    workers: List[str],
    cap: Optional[int],
) -> Dict[str, List[str]]:
    """Per-worker resident set: the worker's assigned machines by
    descending measured rate, up to ``cap`` (zero-rate machines are
    never pinned — a pin they don't use would squat a megabatch slot).
    This replaces 2-hit LRU promotion ALONE with expected-hit-rate
    choice; the LRU still runs underneath for unplanned traffic."""
    by_worker: Dict[str, List[str]] = {worker: [] for worker in workers}
    for machine, worker in assignment.items():
        if worker in by_worker:
            by_worker[worker].append(machine)
    resident: Dict[str, List[str]] = {}
    for worker, names in by_worker.items():
        hot = sorted(
            (m for m in names if rates.get(m, 0.0) > 0.0),
            key=lambda m: (-rates.get(m, 0.0), m),
        )
        limit = int(cap) if cap is not None else min(16, len(hot))
        resident[worker] = hot[:limit]
    return resident


def _plan_precision(
    rates: Dict[str, float],
    total_rps: float,
    parity_budget: float,
    spec_precisions: Optional[Dict[str, str]],
) -> Dict[str, str]:
    """Greedy precision downgrades within the traffic × parity budget:
    each downgraded machine spends ``(its traffic share) × (its rung's
    parity budget)`` of the fleet budget. Coldest machines first — the
    byte savings per machine are equal (fleet-mean footprint) while the
    parity spend is rate-proportional, so ascending-rate order downgrades
    the MOST machines (and the least latency-critical ones) per unit of
    budget. Machines the spec pins explicitly are never overridden —
    the declared spec owns precision; the plan only fills the gaps."""
    if parity_budget <= 0.0 or total_rps <= 0.0:
        return {}
    pinned = spec_precisions or {}
    spent = 0.0
    plan: Dict[str, str] = {}
    for machine in sorted(rates, key=lambda m: (rates[m], m)):
        if machine in pinned:
            continue
        share = rates[machine] / total_rps
        for rung in ("int8", "bf16"):
            cost = share * _RUNG_PARITY_COST[rung]
            if spent + cost <= parity_budget:
                plan[machine] = rung
                spent += cost
                break
    return plan


def compile_plan(
    doc: Dict[str, Any],
    workers: Optional[List[str]] = None,
    vnodes: int = 64,
    residency_cap: Optional[int] = None,
    parity_budget: Optional[float] = None,
    spec_precisions: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Compile a validated layout-input document into a
    ``gordo-layout-plan/v1`` artifact. Raises ``ValueError`` on an
    invalid input document (callers decide whether that is a CLI error
    or a skipped re-derive). ``workers`` overrides the doc's own source
    worker list (the live reconciler passes the CURRENT ready set so a
    plan never assigns to a worker that already left); ``vnodes`` must
    match the live ring for the simulation to be exact (the fleet-wide
    default is 64). ``spec_precisions`` are the FleetSpec's explicit
    per-machine pins, which always win over the compiler's choices."""
    problems = validate_layout_input(doc)
    if problems:
        raise ValueError(
            "layout-input document invalid: " + "; ".join(problems[:5])
        )
    if parity_budget is None:
        parity_budget = _env_float("GORDO_LAYOUT_PARITY_BUDGET", 0.0)
    model = CostModel(doc)
    rates = model.rates
    machines = sorted(rates)
    if workers is None:
        workers = [
            str(w) for w in (doc.get("source") or {}).get("workers") or ()
            if w
        ]
    workers = sorted(set(workers))
    if not workers:
        raise ValueError("layout-input document names no workers")

    # baseline: the uniform name-hash ring (what the fleet does today)
    ring = HashRing(workers, vnodes=vnodes)
    baseline_assignment = _assignment(ring, machines)
    baseline_resident = _resident_sets(
        baseline_assignment, rates, workers, residency_cap
    )
    _, baseline_cost = model.score(
        baseline_assignment, workers, baseline_resident
    )

    # damped multiplicative-weights rebalance against the measured rates
    weights = {worker: 1.0 for worker in workers}
    best = (baseline_assignment, dict(weights))
    best_score, _ = model.score(
        baseline_assignment, workers, baseline_resident
    )
    for _ in range(_REBALANCE_ROUNDS):
        loads = model.worker_loads(_assignment(ring, machines), workers)
        mean = sum(loads.values()) / len(workers)
        if mean <= 0:
            break
        changed = False
        for worker in workers:
            # floor idle workers at 5% of mean so one empty worker
            # cannot demand an unbounded weight in a single round
            load = max(loads[worker], 0.05 * mean)
            target = _quantize(weights[worker] * (mean / load) ** 0.5)
            if target != weights[worker]:
                weights[worker] = target
                ring.set_weight(worker, target)
                changed = True
        candidate = _assignment(ring, machines)
        resident = _resident_sets(candidate, rates, workers, residency_cap)
        score, _ = model.score(candidate, workers, resident)
        if score < best_score:
            best_score = score
            best = (candidate, dict(weights))
        if not changed:
            break
    assignment, weights = best
    weights = {
        worker: weight for worker, weight in weights.items()
        if weight != 1.0
    }

    resident = _resident_sets(assignment, rates, workers, residency_cap)
    precision = _plan_precision(
        rates, model.total_rps, parity_budget, spec_precisions
    )
    _, plan_cost = model.score(assignment, workers, resident, precision)

    residency_workers: Dict[str, Any] = {}
    for worker in workers:
        names = resident.get(worker) or []
        worker_rps = sum(
            rates.get(m, 0.0)
            for m, w in assignment.items() if w == worker
        )
        hit = (
            sum(rates.get(m, 0.0) for m in names) / worker_rps
            if worker_rps > 0 else None
        )
        residency_workers[worker] = {
            "resident": names,
            "expected_hit_rate": round(hit, 4) if hit is not None else None,
        }

    prefetch: Dict[str, List[str]] = {}
    for worker in workers:
        pinned = set(resident.get(worker) or ())
        spill = sorted(
            (
                m for m, w in assignment.items()
                if w == worker and m not in pinned
                and rates.get(m, 0.0) > 0.0
            ),
            key=lambda m: (-rates.get(m, 0.0), m),
        )[:_PREFETCH_PER_WORKER]
        if spill:
            prefetch[worker] = spill

    baseline_loads = model.worker_loads(baseline_assignment, workers)
    mean_load = (
        sum(baseline_loads.values()) / len(workers) if workers else 0.0
    )
    moves = []
    for machine in machines:
        src = baseline_assignment.get(machine, "")
        dst = assignment.get(machine, "")
        if src == dst:
            continue
        src_ratio = (
            baseline_loads.get(src, 0.0) / mean_load if mean_load > 0
            else 0.0
        )
        moves.append({
            "machine": machine,
            "from": src,
            "to": dst,
            "rps": round(rates.get(machine, 0.0), 3),
            "reason": (
                f"{src} carried {src_ratio:.2f}x the mean measured load"
                if src_ratio > 1.0 else "ring arcs resized by weights"
            ),
        })

    plan: Dict[str, Any] = {
        "schema": PLAN_SCHEMA,
        "generated_t": float(doc.get("generated_t") or 0.0),
        "workers": workers,
        "weights": weights,
        "residency": {
            "cap": int(residency_cap) if residency_cap is not None else None,
            "workers": residency_workers,
        },
        "precision": precision,
        "prefetch": prefetch,
        "source": {
            "schema": doc.get("schema"),
            "generated_t": float(doc.get("generated_t") or 0.0),
            "window_s": float(doc.get("window_s") or 0.0),
            "horizon": doc.get("horizon"),
            "total_rps": round(model.total_rps, 3),
            "rates": {
                machine: round(rates[machine], 3)
                for machine in sorted(
                    rates, key=lambda m: (-rates[m], m)
                )[:_SOURCE_RATES_TOP]
            },
        },
        "cost": {"baseline": baseline_cost, "plan": plan_cost},
        "moves": moves,
    }
    plan["fingerprint"] = plan_fingerprint(plan)
    return plan


def staleness(
    plan: Dict[str, Any],
    doc: Dict[str, Any],
    max_age_s: Optional[float] = None,
    drift_limit: Optional[float] = None,
) -> Optional[str]:
    """Judge a committed plan against FRESH telemetry: returns a reason
    string when the plan should be re-derived, None while it stands.
    Two triggers (ARCHITECTURE §27's staleness contract):

    - **age** — the telemetry the plan was computed from is older than
      ``GORDO_LAYOUT_MAX_AGE`` seconds relative to the fresh doc.
    - **drift** — the measured rate DISTRIBUTION moved: total variation
      distance between the plan's recorded machine-rate shares and the
      fresh ones exceeds ``GORDO_LAYOUT_DRIFT`` (0..1; 0.5 means half
      the traffic mass moved machines).

    Both clocks come from the telemetry documents themselves, so the
    check is valid wherever those timestamps are mutually consistent
    (same warehouse lineage) and degrades to age-only when not."""
    if max_age_s is None:
        max_age_s = _env_float("GORDO_LAYOUT_MAX_AGE", 900.0)
    if drift_limit is None:
        drift_limit = _env_float("GORDO_LAYOUT_DRIFT", 0.35)
    source = plan.get("source") or {}
    plan_t = float(source.get("generated_t") or plan.get("generated_t")
                   or 0.0)
    doc_t = float(doc.get("generated_t") or 0.0)
    if max_age_s > 0 and plan_t > 0 and doc_t - plan_t > max_age_s:
        return (
            f"plan telemetry is {doc_t - plan_t:.0f}s old "
            f"(max {max_age_s:.0f}s)"
        )
    old = {
        str(machine): max(0.0, float(rate))
        for machine, rate in (source.get("rates") or {}).items()
    }
    new = machine_rates_for_drift(doc)
    old_total, new_total = sum(old.values()), sum(new.values())
    if drift_limit > 0 and old_total > 0 and new_total > 0:
        tv = 0.5 * sum(
            abs(old.get(m, 0.0) / old_total - new.get(m, 0.0) / new_total)
            for m in set(old) | set(new)
        )
        if tv > drift_limit:
            return (
                f"rate distribution drifted {tv:.2f} "
                f"(limit {drift_limit:.2f})"
            )
    return None


def machine_rates_for_drift(doc: Dict[str, Any]) -> Dict[str, float]:
    """The fresh doc's machine rates, tolerant of invalid documents
    (staleness runs on every reconciler tick — a malformed scrape must
    degrade to 'no drift signal', never raise)."""
    try:
        from .costmodel import machine_rates

        return machine_rates(doc)
    except (TypeError, ValueError, AttributeError, KeyError):
        return {}
