"""Measured-cost scoring for candidate fleet layouts.

The model is intentionally a PROXY, not a simulator: it ranks candidate
layouts on three terms the telemetry warehouse actually measures, and
the smoke/bench harnesses gate the REAL p99 and bytes numbers on a live
fleet (tools/layout_smoke.py) — the model only has to order candidates
correctly, not predict latencies absolutely.

Terms (all computed from one ``gordo-layout-input/v1`` document plus a
candidate machine→worker assignment):

- **imbalance** — max worker load / mean worker load over the measured
  per-machine rates. The single-worker ceiling is the serving tier's
  binding constraint; queueing delay grows superlinearly in utilization,
  so the p99 proxy weights this term quadratically.
- **expected residency hit rate** — the traffic share landing on
  machines inside their worker's resident set. A megabatch-resident
  machine dispatches through the stacked program; everything else pays
  the host path, so (1 - hit rate) is the model's slow-path mass.
- **device bytes / machines-per-GiB** — per-rung device bytes from the
  engine's cost ledger, with precision downgrades projected at the
  ladder's byte ratios (bf16 halves, int8 quarters the stacked tree).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: device-byte ratio of each rung relative to f32 (ARCHITECTURE §19)
RUNG_BYTE_RATIO = {"f32": 1.0, "bf16": 0.5, "int8": 0.25}

_GIB = float(1 << 30)


def machine_rates(doc: Dict[str, Any]) -> Dict[str, float]:
    """Per-machine representative request rate from a layout-input doc:
    the resolved ``rate`` field when the exporter provided one, else the
    doc's own horizon label looked up in the multi-horizon map, else
    the first horizon present. Machines with no measured rate at all
    plan at 0.0 (they still get placed — by name hash, like today)."""
    horizon = doc.get("horizon")
    rates: Dict[str, float] = {}
    for m in doc.get("machines") or ():
        name = m.get("machine")
        if not name:
            continue
        rate = m.get("rate")
        if rate is None:
            table = m.get("rates") or {}
            if horizon in table:
                rate = table[horizon]
            elif table:
                rate = next(iter(table.values()))
            else:
                rate = 0.0
        rates[str(name)] = max(0.0, float(rate))
    return rates


def mean_machine_bytes(doc: Dict[str, Any]) -> float:
    """Fleet-mean device bytes per machine from the per-rung cost
    ledger. The export aggregates bytes per RUNG, not per machine, so
    the model works in fleet means — good enough to rank layouts (the
    bench measures the real number)."""
    total_bytes = 0.0
    total_machines = 0.0
    for entry in (doc.get("rungs") or {}).values():
        total_bytes += float(entry.get("device_bytes") or 0.0)
        total_machines += float(entry.get("machines") or 0.0)
    if total_machines <= 0:
        return 0.0
    return total_bytes / total_machines


def base_latency_s(doc: Dict[str, Any]) -> float:
    """Request-weighted mean dispatch latency across rungs — the p99
    proxy's scale factor."""
    seconds = 0.0
    requests = 0.0
    for entry in (doc.get("rungs") or {}).values():
        seconds += float(entry.get("dispatch_seconds_total") or 0.0)
        requests += float(entry.get("requests") or 0.0)
    if requests <= 0:
        return 0.0
    return seconds / requests


class CostModel:
    """Scores a candidate layout against one layout-input document."""

    def __init__(self, doc: Dict[str, Any]):
        self.doc = doc
        self.rates = machine_rates(doc)
        self.total_rps = sum(self.rates.values())
        self.bytes_per_machine = mean_machine_bytes(doc)
        self.base_latency_s = base_latency_s(doc)

    # -- per-term metrics ----------------------------------------------------
    def worker_loads(
        self, assignment: Dict[str, str], workers: List[str]
    ) -> Dict[str, float]:
        """Measured rps landing on each worker under ``assignment``
        (machine → worker). Workers with no machines still appear (their
        idle capacity is exactly what a rebalance should use)."""
        loads = {worker: 0.0 for worker in workers}
        for machine, worker in assignment.items():
            if worker in loads:
                loads[worker] += self.rates.get(machine, 0.0)
        return loads

    def imbalance(self, loads: Dict[str, float]) -> float:
        """max/mean worker load; 1.0 = perfectly balanced. An empty or
        idle fleet scores a neutral 1.0 (nothing to balance)."""
        if not loads:
            return 1.0
        mean = sum(loads.values()) / len(loads)
        if mean <= 0:
            return 1.0
        return max(loads.values()) / mean

    def expected_hit_rate(
        self,
        assignment: Dict[str, str],
        resident: Dict[str, List[str]],
    ) -> float:
        """Traffic share landing on megabatch-resident machines: the
        fleet-wide expected residency hit rate under the measured rate
        distribution."""
        if self.total_rps <= 0:
            return 1.0
        resident_sets = {
            worker: set(names) for worker, names in resident.items()
        }
        hit = sum(
            self.rates.get(machine, 0.0)
            for machine, worker in assignment.items()
            if machine in resident_sets.get(worker, ())
        )
        return min(1.0, hit / self.total_rps)

    def device_bytes(self, precision: Dict[str, str]) -> float:
        """Projected fleet device bytes after the plan's precision
        downgrades (machines not in ``precision`` keep their measured
        mean footprint)."""
        n_machines = len(self.rates) or len(
            self.doc.get("machines") or ()
        )
        base = self.bytes_per_machine * n_machines
        if base <= 0:
            return 0.0
        saved = sum(
            self.bytes_per_machine * (1.0 - RUNG_BYTE_RATIO.get(rung, 1.0))
            for machine, rung in precision.items()
            if machine in self.rates
        )
        return max(0.0, base - saved)

    def machines_per_gib(self, precision: Dict[str, str]) -> float:
        """Machines served per GiB of device bytes — the density metric
        the acceptance gate compares (higher is better)."""
        projected = self.device_bytes(precision)
        if projected <= 0:
            return 0.0
        n_machines = len(self.rates) or len(
            self.doc.get("machines") or ()
        )
        return n_machines / (projected / _GIB)

    def p99_proxy_s(self, loads: Dict[str, float], hit_rate: float) -> float:
        """Traffic-weighted p99 contribution proxy: base dispatch
        latency scaled by the squared imbalance (queueing grows
        superlinearly toward the hottest worker's ceiling) plus the
        slow-path mass that misses residency. A ranking device, not a
        latency prediction."""
        imbalance = self.imbalance(loads)
        return self.base_latency_s * (
            imbalance * imbalance + 2.0 * (1.0 - hit_rate)
        )

    # -- the scalar objective ------------------------------------------------
    def score(
        self,
        assignment: Dict[str, str],
        workers: List[str],
        resident: Dict[str, List[str]],
        precision: Optional[Dict[str, str]] = None,
    ) -> Tuple[float, Dict[str, float]]:
        """Scalar cost (lower is better) plus the per-term breakdown
        recorded into the plan's ``cost`` block."""
        precision = precision or {}
        loads = self.worker_loads(assignment, workers)
        imbalance = self.imbalance(loads)
        hit_rate = self.expected_hit_rate(assignment, resident)
        per_gib = self.machines_per_gib(precision)
        p99 = self.p99_proxy_s(loads, hit_rate)
        # normalized terms: imbalance dominates (it is the measured
        # binding constraint), residency misses next, bytes last (a
        # tie-breaker — the parity budget already bounds the downgrades)
        scalar = (
            (imbalance - 1.0)
            + (1.0 - hit_rate)
            + 0.1 * (1.0 / (1.0 + per_gib) if per_gib > 0 else 0.0)
        )
        return scalar, {
            "imbalance": round(imbalance, 4),
            "expected_hit_rate": round(hit_rate, 4),
            "machines_per_gib": round(per_gib, 2),
            "device_gib": round(self.device_bytes(precision) / _GIB, 4),
            "p99_proxy_ms": round(p99 * 1000.0, 3),
            "worker_rps": {
                worker: round(load, 3)
                for worker, load in sorted(loads.items())
            },
        }
