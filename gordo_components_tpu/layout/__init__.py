"""Fleet layout compiler (ARCHITECTURE §27, ROADMAP item 5).

Four placement axes — ring shard assignment, megabatch residency,
precision rung, host-RAM spill prefetch — were each tuned by an
independent fixed rule (pure name hash, 2-hit LRU promotion, hand-set
precision maps, reactive spill loads). Automap and Mesh-TensorFlow
(PAPERS.md) both argue layout should be ONE compiled, cost-model-driven
decision; this package is that compiler for the serving tier:

- :mod:`costmodel` scores candidate layouts on measured telemetry (the
  ``gordo-layout-input/v1`` export): device-bytes-per-worker balance,
  expected residency hit rate under the observed rate distribution, and
  a traffic-weighted p99 proxy.
- :mod:`compiler` emits the deterministic, versioned
  ``gordo-layout-plan/v1`` artifact and checks a committed plan's
  staleness against fresh telemetry.
- :mod:`plan` is the dependency-free plan contract: validator,
  canonical fingerprint, and the ``explain`` rendering that names why
  each machine moved.

The plan is DECLARED (a ``FleetSpec.layout`` field, journaled like
every other spec change) and APPLIED by the reconciler through existing
seams only — placement weight overrides, engine residency pins,
precision rebuilds, ``/prefetch`` hints. Rollback is a new spec
revision, exactly like any other fleet change.
"""

from .compiler import compile_plan, staleness  # noqa: F401
from .costmodel import CostModel, machine_rates  # noqa: F401
from .plan import (  # noqa: F401
    PLAN_SCHEMA,
    explain_plan,
    plan_fingerprint,
    validate_layout_plan,
)
