"""The per-machine precision ladder: f32 / bf16 / int8 scoring.

A machine's numeric precision is a FIRST-CLASS artifact property, chosen
at build time (``gordo build --precision``, fleet ``--precision-map``),
recorded in the artifact's build metadata, validated on load, and carried
through every serving layer (docs/ARCHITECTURE.md §19):

- **f32** — the default; the scoring path is bit-identical to a build
  that never heard of this module.
- **bf16** — weights are stored (host and device) as bfloat16 and the
  network forward pass computes in bf16; everything around it — scaler
  affines, residuals, error scaling, the L2 — stays f32, and every
  output array is f32. Halves the stacked tree's device bytes.
- **int8** — weights are quantized per-tensor (symmetric, scale =
  max|w|/127) and stay int8 ON DEVICE; the jitted closure dequantizes
  into f32 and accumulates in f32. Quarters the stacked tree's weight
  bytes. The quantized arrays + scales are committed INTO the artifact
  (``quant_int8.npz``, hashed by the manifest like every other file) so
  serve-time quantization is a load, not a recompute — and the f32
  ``state.npz`` stays untouched for the host path and for rebuilding at
  another precision.

Downgraded precisions trade accuracy for speed and residency; the trade
is GATED, not assumed: the parity budgets below bound how far bf16/int8
total anomaly scores may drift from the f32 reference (normalized to the
f32 score scale — raw relative error explodes where residuals cancel to
~0), and ``tools/quant_smoke.py`` + the bench's ``precision`` block
measure them on every run. Anomaly-threshold flip rates across
precisions are measured and reported there too, never silently absorbed.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: the ladder, in descending width; also the `--precision` CLI choices
PRECISIONS = ("f32", "bf16", "int8")
DEFAULT_PRECISION = "f32"

#: artifact file holding the int8-quantized weights + per-tensor scales
#: (committed beside state.npz through the same atomic path, so the
#: manifest hashes it and a torn/tampered copy fails verification)
QUANT_INT8_FILE = "quant_int8.npz"

# parity error budgets: max |downgraded - f32| of total_anomaly_score,
# normalized by the mean f32 total score over the comparison set (see
# parity_error). Raw rtol is the wrong ruler here — residuals that
# cancel toward zero make per-element relative error unbounded while the
# actual anomaly signal is unaffected. Defaults hold with margin on the
# bench shapes (measured in tools/quant_smoke.py); GORDO_PARITY_RTOL_*
# override for fleets whose models are more (or less) sensitive.
_DEFAULT_BUDGETS = {"f32": 0.0, "bf16": 0.02, "int8": 0.08}
_BUDGET_ENV = {
    "bf16": "GORDO_PARITY_RTOL_BF16",
    "int8": "GORDO_PARITY_RTOL_INT8",
}


def validate(value: Optional[str]) -> str:
    """Normalize + validate a precision value (None/"" → f32). Raises
    ``ValueError`` on anything outside the ladder — the load path turns
    that into a quarantined machine, never a silently-f32 one."""
    if value in (None, ""):
        return DEFAULT_PRECISION
    normalized = str(value).strip().lower()
    if normalized not in PRECISIONS:
        raise ValueError(
            f"unknown precision {value!r} (expected one of {PRECISIONS})"
        )
    return normalized


def resolve_default(explicit: Optional[str] = None) -> str:
    """Build-time precision resolution: explicit flag beats the
    ``GORDO_PRECISION_DEFAULT`` env default beats f32. A bad env value
    fails loudly here — at build time, where it is cheap — rather than
    producing a fleet of mislabeled artifacts."""
    if explicit:
        return validate(explicit)
    return validate(os.environ.get("GORDO_PRECISION_DEFAULT"))


def of_metadata(metadata: Dict[str, Any]) -> str:
    """The validated precision an artifact's build metadata pins
    (absent → f32, so every pre-ladder artifact keeps serving f32)."""
    return validate((metadata or {}).get("precision"))


def error_budget(precision: str) -> float:
    """The declared parity budget for a precision (see module docstring
    for the normalization), env-overridable per rung."""
    precision = validate(precision)
    env = _BUDGET_ENV.get(precision)
    if env:
        raw = os.environ.get(env)
        if raw:
            try:
                return max(0.0, float(raw))
            except ValueError:
                logger.warning(
                    "%s=%r is not a float; using the default %s budget",
                    env, raw, precision,
                )
    return _DEFAULT_BUDGETS[precision]


def parity_error(reference: np.ndarray, candidate: np.ndarray) -> float:
    """Normalized parity error between two total-anomaly-score arrays:
    ``max|candidate - reference| / mean|reference|``. The one ruler the
    smoke harness, the bench block, and the tests all measure with."""
    reference = np.asarray(reference, np.float64)
    candidate = np.asarray(candidate, np.float64)
    scale = float(np.mean(np.abs(reference)))
    if scale == 0.0:
        scale = 1.0
    return float(np.max(np.abs(candidate - reference))) / scale


# -- int8 quantization -------------------------------------------------------
def quantize_array_int8(array: np.ndarray) -> Tuple[np.ndarray, np.float32]:
    """Symmetric per-tensor int8 quantization: ``q = round(w / scale)``
    with ``scale = max|w| / 127``. Deterministic (pure numpy, no RNG), so
    build-time and serve-time quantization of the same weights are
    byte-identical — which is what lets the stored sidecar and an
    on-the-fly fallback serve the same scores."""
    array = np.asarray(array, np.float32)
    peak = float(np.max(np.abs(array))) if array.size else 0.0
    scale = peak / 127.0 if peak > 0.0 else 1.0
    q = np.clip(np.round(array / scale), -127, 127).astype(np.int8)
    return q, np.float32(scale)


def quantize_tree_int8(params: Any) -> Tuple[Any, Any]:
    """Quantize every leaf of a params pytree; returns ``(q_tree,
    scale_tree)`` with the SAME treedef (the engine stacks and gathers
    them in lockstep with the scales)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    pairs = [quantize_array_int8(leaf) for leaf in leaves]
    qs = [q for q, _ in pairs]
    scales = [s for _, s in pairs]
    return (
        jax.tree_util.tree_unflatten(treedef, qs),
        jax.tree_util.tree_unflatten(treedef, scales),
    )


def dequantize_tree_int8(q_tree: Any, scale_tree: Any) -> Any:
    """Host-side inverse (tests, drift analysis); the serving closure
    does the same math in-program with jnp."""
    import jax

    return jax.tree_util.tree_map(
        lambda q, s: np.asarray(q, np.float32) * np.float32(s),
        q_tree, scale_tree,
    )


def quantized_arrays_for(model: Any) -> Optional[Dict[str, np.ndarray]]:
    """Flattened ``{"q/<path>": int8, "s/<path>": f32-scale}`` arrays for
    an anomaly pipeline's estimator params — the ``quant_int8.npz``
    payload. ``None`` when the model has no liftable estimator (the
    engine would skip it to the host path anyway, which always serves
    f32)."""
    from .models.analysis import analyze_model
    from .serializer.persistence import _flatten_state

    try:
        est = analyze_model(model).estimator
        params = est.params_
        if params is None:
            return None
        import jax

        params = jax.device_get(params)
    except (ValueError, AttributeError, TypeError):
        return None
    q_tree, scale_tree = quantize_tree_int8(params)
    arrays, _ = _flatten_state({"q": q_tree, "s": scale_tree})
    return arrays


def load_quantized(artifact_dir: str) -> Optional[Tuple[Any, Any]]:
    """The ``(q_tree, scale_tree)`` pair stored in an artifact's
    ``quant_int8.npz``, or ``None`` when the artifact carries none (the
    engine then quantizes the f32 params on the fly — same formula, same
    bytes). Callers pass a RESOLVED artifact dir; integrity is the
    manifest's job (``load``/``verify_artifact`` already hashed this file
    before anything trusts the directory)."""
    from .serializer.persistence import _unflatten_state

    path = os.path.join(artifact_dir, QUANT_INT8_FILE)
    if not os.path.isfile(path):
        return None
    with np.load(path) as npz:
        arrays = {key: npz[key] for key in npz.files}
    tree = _unflatten_state(arrays, {})
    q_tree, scale_tree = tree.get("q"), tree.get("s")
    if q_tree is None or scale_tree is None:
        raise ValueError(
            f"{path}: malformed quantized sidecar (missing q/ or s/ trees)"
        )
    return q_tree, scale_tree


def parse_precision_map(spec: Optional[str]) -> Dict[str, str]:
    """``--precision-map`` parser: ``name=precision`` pairs (comma- or
    semicolon-separated), or a path to a YAML file mapping names to
    precisions. Every value is validated here so a typo fails the build
    command, not a fleet of artifacts later."""
    if not spec:
        return {}
    mapping: Dict[str, str] = {}
    if os.path.exists(spec):
        import yaml

        with open(spec) as fh:
            loaded = yaml.safe_load(fh)
        if not isinstance(loaded, dict):
            raise ValueError(
                f"--precision-map file {spec!r} must parse to a mapping"
            )
        items = loaded.items()
    else:
        items = []
        for pair in spec.replace(";", ",").split(","):
            pair = pair.strip()
            if not pair:
                continue
            if "=" not in pair:
                raise ValueError(
                    f"--precision-map entry {pair!r} is not name=precision"
                )
            name, _, value = pair.partition("=")
            items.append((name.strip(), value.strip()))
    for name, value in items:
        if not name:
            raise ValueError("--precision-map entry has an empty name")
        mapping[str(name)] = validate(str(value))
    return mapping
