"""gordo-components-tpu — a TPU-native fleet-scale framework for industrial
time-series anomaly detection.

Re-implements the full capability surface of the reference project
``ryanjdillon/gordo-components`` (``gordo_components/`` — see ``SURVEY.md``;
the reference mount was empty during the survey so citations are at
file-path granularity) as a brand-new JAX/Flax/pjit-first design:

- the Keras model zoo (``KerasAutoEncoder``, ``KerasLSTMAutoEncoder``,
  ``KerasLSTMForecast``) becomes Flax modules trained by jitted optax steps,
- the pod-per-machine Argo fan-out becomes ``vmap``-over-``shard_map`` fleet
  training on a TPU mesh (see :mod:`gordo_components_tpu.parallel`),
- the Flask serving layer becomes a werkzeug WSGI app dispatching to
  jit-compiled batched scoring functions,
- dataset windowing is a static-shape gather that XLA fuses on-device.
"""

__version__ = "0.3.0"
