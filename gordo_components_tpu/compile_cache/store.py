"""Store-backed persistent compilation cache: AOT-serialized executables.

Every server boot, ``/reload``, generation swap, and ``gordo rollback``
otherwise re-pays full XLA compilation for every (architecture ×
row-bucket × batch-size) scoring program — warmup hides it from the first
request but not from the boot clock. This store persists the compiled
executables themselves (``jax.experimental.serialize_executable`` — the
loaded binary, not re-lowerable IR), so adopting a generation is O(load):
deserialize, one probe dispatch, serve.

Layout — one entry per executable, committed through the model store's
atomic machinery so cache entries inherit its guarantees (a torn write is
invisible; a damaged entry FAILS VERIFICATION instead of loading)::

    <root>/
      cc-<sha256(key)[:32]>/
        KEY.json         # full key: program identity + backend fingerprint
        executable.bin   # serialize_executable payload
        treedefs.pkl     # pickled (in_tree, out_tree)
        MANIFEST.json    # per-file SHA-256 + size (store/atomic.py)

The fallback contract (the load path is NEVER fatal):

- entry absent → **miss** (caller JIT-compiles, writes back);
- manifest fails, payload unreadable, deserialization raises, or the
  caller's probe dispatch fails → **invalid** (caller JIT-compiles and
  the write-back overwrites the bad entry — self-healing);
- stored ``KEY.json`` disagrees with the expected key (fingerprint
  tamper, hash collision) → **stale** (same JIT fallback);
- a crash mid-write leaves only ``.staging-*`` debris the atomic-commit
  rename never published — the next boot misses cleanly.

Scores from a fallen-back JIT path are bit-identical to the cached path
(same lowering → same executable; gated end-to-end by
``tools/coldstart_smoke.py``).

Security note: ``treedefs.pkl`` and the executable payload are pickle
(jax's serialization format). The manifest's SHA-256 pass runs BEFORE any
unpickling — same trust model as the serializer's model artifacts — so a
flipped bit fails typed, but the cache root must be as trusted as the
model store it lives beside.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from ..observability.registry import REGISTRY
from ..store import StoreError, atomic_commit, sweep_leftovers, verify_artifact
from . import fingerprint as fp

logger = logging.getLogger(__name__)

KEY_FILE = "KEY.json"
EXEC_FILE = "executable.bin"
TREES_FILE = "treedefs.pkl"
# sidecar measurements (NOT part of the cache key): the measured XLA
# compile seconds this entry saved, read back by the §24 cost ledger.
# Pre-ledger entries simply lack the file — `entries()` reports None.
META_FILE = "META.json"

# env knob read by the server/CLI wiring (a path, or "off" to disable the
# cache even when a models_root would default one on)
STORE_ENV = "GORDO_COMPILE_CACHE_STORE"

_M_LOOKUPS = REGISTRY.counter(
    "gordo_compile_cache_lookups_total",
    "Persistent compile-cache lookups by program kind and outcome: hit "
    "(executable loaded, no XLA compile), miss (no entry), stale (entry's "
    "stored key disagrees — e.g. jaxlib fingerprint mismatch), invalid "
    "(corrupt/unreadable/failed-probe entry). Everything but 'hit' falls "
    "back to JIT and is never fatal",
    labels=("kind", "outcome"),
)
_M_WRITES = REGISTRY.counter(
    "gordo_compile_cache_writes_total",
    "Persistent compile-cache write-backs, by outcome (ok / error / "
    "unserializable)",
    labels=("outcome",),
)
_M_LOAD_SECONDS = REGISTRY.histogram(
    "gordo_compile_cache_load_seconds",
    "Duration of a successful cache-entry load (verify + deserialize) — "
    "the O(load) cost that replaces an O(compile) one",
)


class CompileCacheStore:
    """One cache root; thread-safe (entries are immutable once committed,
    commits are atomic renames, concurrent writers of one key last-win).

    Instance ``counters`` track THIS store object's lookups (a fresh boot
    diff, next to the process-cumulative registry series).
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.counters: Dict[str, int] = {
            "hit": 0, "miss": 0, "stale": 0, "invalid": 0,
            "write": 0, "write_error": 0,
        }

    # -- lookup --------------------------------------------------------------
    def get(
        self,
        program_key: Dict[str, Any],
        probe: Optional[Callable[[Any], None]] = None,
    ) -> Optional[Any]:
        """The loaded executable for ``program_key``, or ``None`` (miss /
        stale / invalid — the caller JIT-compiles either way).

        ``probe``: optional callable run with the loaded executable before
        it is adopted (the engine dispatches a zeros batch through it) — a
        binary that verifies on disk but cannot execute on THIS host
        (moved cache dir, ISA drift inside one fingerprint) downgrades to
        *invalid* here instead of failing live requests later."""
        kind = str(program_key.get("kind", "unknown"))
        key = fp.full_key(program_key)
        path = os.path.join(self.root, fp.entry_name(key))
        if not os.path.isdir(path):
            self._count(kind, "miss")
            return None
        started = time.perf_counter()
        try:
            verify_artifact(path, deep=True)
        except StoreError as exc:
            logger.warning(
                "Compile-cache entry %s fails verification (%s); falling "
                "back to JIT", path, exc,
            )
            self._count(kind, "invalid")
            return None
        try:
            with open(os.path.join(path, KEY_FILE)) as fh:
                stored = fh.read()
            if stored.strip() != fp.canonical(key):
                logger.warning(
                    "Compile-cache entry %s key mismatch (stale fingerprint "
                    "or collision); falling back to JIT", path,
                )
                self._count(kind, "stale")
                return None
            loaded = self._load_entry(path)
            if probe is not None:
                probe(loaded)
        except Exception as exc:
            logger.warning(
                "Compile-cache entry %s unloadable (%s: %s); falling back "
                "to JIT", path, type(exc).__name__, exc,
            )
            self._count(kind, "invalid")
            return None
        _M_LOAD_SECONDS.observe(time.perf_counter() - started)
        self._count(kind, "hit")
        return loaded

    @staticmethod
    def _load_entry(path: str):
        from jax.experimental.serialize_executable import deserialize_and_load

        with open(os.path.join(path, EXEC_FILE), "rb") as fh:
            payload = fh.read()
        with open(os.path.join(path, TREES_FILE), "rb") as fh:
            in_tree, out_tree = pickle.load(fh)
        return deserialize_and_load(payload, in_tree, out_tree)

    # -- write-back ----------------------------------------------------------
    def put(
        self,
        program_key: Dict[str, Any],
        compiled: Any,
        compile_seconds: Optional[float] = None,
    ) -> bool:
        """Serialize ``compiled`` and commit it under ``program_key``
        (atomic; an existing entry — e.g. one that just read invalid — is
        replaced whole). Never raises: a cache that cannot write degrades
        to compile-every-boot, not to a failed build or request.

        ``compile_seconds``: the measured XLA compile duration this entry
        amortizes, persisted as sidecar meta — the §24 cost ledger's
        per-key compile cost, recorded once at the only moment it is
        actually known."""
        key = fp.full_key(program_key)
        path = os.path.join(self.root, fp.entry_name(key))
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            trees = pickle.dumps((in_tree, out_tree))
        except Exception as exc:
            # sharded/exotic executables some backends cannot serialize:
            # a known, logged degradation — the program still serves
            logger.warning(
                "Compile-cache: executable for %s is not serializable "
                "(%s: %s); this program will recompile every boot",
                program_key, type(exc).__name__, exc,
            )
            self.counters["write_error"] += 1
            _M_WRITES.labels("unserializable").inc()
            return False
        try:
            os.makedirs(self.root, exist_ok=True)
            import json

            with atomic_commit(path, name=os.path.basename(path)) as staging:
                with open(os.path.join(staging, KEY_FILE), "w") as fh:
                    fh.write(fp.canonical(key) + "\n")
                with open(os.path.join(staging, EXEC_FILE), "wb") as fh:
                    fh.write(payload)
                with open(os.path.join(staging, TREES_FILE), "wb") as fh:
                    fh.write(trees)
                with open(os.path.join(staging, META_FILE), "w") as fh:
                    json.dump(
                        {
                            "compile_seconds": compile_seconds,
                            "created": time.time(),
                        },
                        fh,
                    )
        except Exception as exc:
            logger.warning(
                "Compile-cache write-back failed for %s (%s: %s)",
                program_key, type(exc).__name__, exc,
            )
            self.counters["write_error"] += 1
            _M_WRITES.labels("error").inc()
            return False
        self.counters["write"] += 1
        _M_WRITES.labels("ok").inc()
        return True

    # -- maintenance (the `gordo cache` verbs) -------------------------------
    def entries(self) -> List[Dict[str, Any]]:
        """One record per entry dir: its stored key, byte size, whether it
        verifies, and whether its backend fingerprint matches THIS process
        (``current`` False = candidate for ``purge --stale``)."""
        import json

        out: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        current_backend = fp.backend_fingerprint()
        for name in names:
            path = os.path.join(self.root, name)
            if not name.startswith(fp.ENTRY_PREFIX) or not os.path.isdir(path):
                continue
            record: Dict[str, Any] = {"name": name, "bytes": _dir_bytes(path)}
            try:
                # deep (hashing) verification: `cache list` must report a
                # size-preserving bitflip as unverified, and `purge
                # --stale` promises to remove entries that fail
                # verification — entries are small, so the hash pass is
                # cheap at operator-CLI cadence
                verify_artifact(path, deep=True)
                record["verified"] = True
            except StoreError as exc:
                record["verified"] = False
                record["error"] = f"{type(exc).__name__}: {exc}"
            try:
                with open(os.path.join(path, KEY_FILE)) as fh:
                    key = json.load(fh)
                record["program"] = key.get("program")
                record["backend"] = key.get("backend")
                record["current"] = key.get("backend") == current_backend
                # §19: the precision rung this executable was compiled
                # for, surfaced top-level so `gordo cache list` makes a
                # mixed-precision cache auditable at a glance (pre-ladder
                # entries carry no field and read f32)
                record["precision"] = (key.get("program") or {}).get(
                    "precision", "f32"
                )
            except Exception:
                record.setdefault("error", "KEY.json unreadable")
                record["current"] = False
            try:
                with open(os.path.join(path, META_FILE)) as fh:
                    meta = json.load(fh)
                record["compile_seconds"] = meta.get("compile_seconds")
                record["created"] = meta.get("created")
            except Exception:  # lint: allow-swallow(pre-ledger entries have no META.json sidecar by design; absence is the signal, recorded as compile_seconds=None)
                record["compile_seconds"] = None
            out.append(record)
        return out

    def purge(self, stale_only: bool = False) -> List[str]:
        """Delete entries (all, or — ``stale_only`` — those whose backend
        fingerprint no longer matches or that fail verification) and sweep
        crash debris (``.staging-*``). Returns the removed names."""
        removed: List[str] = []
        for record in self.entries():
            if stale_only and record.get("current") and record.get("verified"):
                continue
            shutil.rmtree(
                os.path.join(self.root, record["name"]), ignore_errors=True
            )
            removed.append(record["name"])
        removed.extend(sweep_leftovers(self.root))
        return removed

    def _count(self, kind: str, outcome: str) -> None:
        self.counters[outcome] = self.counters.get(outcome, 0) + 1
        _M_LOOKUPS.labels(kind, outcome).inc()


def _dir_bytes(path: str) -> int:
    total = 0
    try:
        for entry in os.scandir(path):
            if entry.is_file():
                total += entry.stat().st_size
    except OSError:
        pass
    return total


def resolve_store(
    explicit: Optional[str] = None, models_root: Optional[str] = None
) -> Optional[CompileCacheStore]:
    """The ONE resolution rule for where the serving compile cache lives,
    shared by the server, the CLI, and the builder export so they can
    never warm different roots: explicit path beats the
    ``GORDO_COMPILE_CACHE_STORE`` env var beats the models-root default
    (``<models_root>/.compile-cache`` — hidden, so the model scan rule
    never mistakes it for a machine). ``"off"`` at any level disables;
    no path resolvable → ``None`` (cache off, today's compile-on-boot)."""
    root = explicit
    if root is None:
        root = os.environ.get(STORE_ENV) or None
    if root is None and models_root:
        root = os.path.join(models_root, ".compile-cache")
    if not root or root == "off":
        return None
    return CompileCacheStore(root)
