"""Persistent compile cache: AOT-serialized executables in the model store.

Makes boot, ``/reload``, and ``gordo rollback`` O(load) instead of
O(compile): the serving engine's scoring programs are AOT-compiled once,
serialized, and committed as checksummed artifacts beside the models they
serve (``docs/ARCHITECTURE.md`` §14 — key schema, invalidation rules, and
the never-fatal JIT fallback contract).
"""

from .fingerprint import backend_fingerprint, canonical, entry_name, full_key
from .store import STORE_ENV, CompileCacheStore, resolve_store

__all__ = [
    "CompileCacheStore",
    "STORE_ENV",
    "backend_fingerprint",
    "canonical",
    "entry_name",
    "export_serving_cache",
    "full_key",
    "resolve_store",
]


def export_serving_cache(*args, **kwargs):
    """Lazy proxy for :func:`.export.export_serving_cache` (pulls in the
    serving engine; the store itself must stay importable from the
    builder without that weight)."""
    from .export import export_serving_cache as _export

    return _export(*args, **kwargs)
