"""Cache-key construction for persisted executables.

An XLA executable serialized on one rig is garbage on another: the bytes
encode the backend (CPU vs TPU), the device generation (v4 vs v5e tile
layouts), the device count a sharded program was partitioned over, and
the jax/jaxlib pair that produced them — none of which the bytes
themselves declare loudly enough to trust. So every cache entry's key
carries two halves:

- the **program identity** the caller supplies (kind — ``serving-cold``
  / ``serving-hot`` / ``serving-mega`` for the fused megabatch program,
  which also carries its resident-stack height — plus architecture
  signature, stacked machine count, shape bucket ``(rows, k)``,
  sharding/donation config, and the bucket's ``precision`` rung
  (f32/bf16/int8 — §19: each rung's executable operates on different
  stacked dtypes, so the variants cache independently and flipping a
  machine's precision is a clean miss, never a stale hit) — see
  ``server/engine.py``), and
- the **backend fingerprint** computed here (jax + jaxlib versions,
  platform, device kind, topology, host ISA).

The entry NAME hashes the canonical JSON of the whole key, so a jaxlib
bump or a device swap simply *misses* (new name) rather than loading an
incompatible binary; the stored ``KEY.json`` is compared byte-for-byte on
load as the second line of defense (a tampered or hash-colliding entry
reads as *stale*, never as a program).

Mesh serving (§23) rides the existing schema: mesh topology and
``process_count`` are already here, and a fleet-sharded engine's
programs key on its OWN shard's stacked machine count (part of the
program identity), so a shard's warm re-boot is recompile-free by
construction — and two shards whose slices happen to stack the same
machine count legitimately SHARE entries, because machine parameters
are runtime arguments, not baked into the executable. Nothing
per-shard is added to the key on purpose: adding one would break that
sharing without buying any correctness.
"""

from __future__ import annotations

import hashlib
import json
import platform
from typing import Any, Dict, Optional

ENTRY_PREFIX = "cc-"

_fingerprint_cache: Optional[Dict[str, Any]] = None


def backend_fingerprint() -> Dict[str, Any]:
    """The environment half of every cache key. Computed once per process
    (device enumeration can touch a slow accelerator transport)."""
    global _fingerprint_cache
    if _fingerprint_cache is None:
        import jax
        import jaxlib

        devices = jax.devices()
        _fingerprint_cache = {
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": devices[0].platform,
            "device_kind": devices[0].device_kind,
            "n_devices": len(devices),
            "process_count": jax.process_count(),
            # XLA:CPU executables embed host-ISA-specific code paths; a
            # cache dir on shared storage must not hand an AVX-512 binary
            # to a host without it
            "machine": platform.machine(),
        }
    return dict(_fingerprint_cache)


def full_key(program_key: Dict[str, Any]) -> Dict[str, Any]:
    """Program identity + backend fingerprint, the complete key one entry
    is stored and validated under."""
    return {"program": dict(program_key), "backend": backend_fingerprint()}


def canonical(key: Dict[str, Any]) -> str:
    """The one rendering of a key — sorted keys, no whitespace — so the
    entry name hash and the stored/loaded ``KEY.json`` comparison can
    never disagree about identity."""
    return json.dumps(key, sort_keys=True, separators=(",", ":"), default=str)


def entry_name(key: Dict[str, Any]) -> str:
    """Directory name for a full key: content-addressed, so stale entries
    (old jaxlib, old topology) age out as unreferenced garbage instead of
    being loaded and mistrusted."""
    digest = hashlib.sha256(canonical(key).encode()).hexdigest()
    return f"{ENTRY_PREFIX}{digest[:32]}"
