"""Build-time export of serving executables into the compile cache.

The fleet builder is the one place that already pays for compiles (every
bucket's training program AOT-compiles in ``parallel/fleet.py``), knows
the full fleet composition, and runs off the serving path — so it is the
right place to ALSO pay the serving compiles, once, into the persistent
cache. A server booting against the same models tree then warms by
loading executables instead of compiling them; ``/reload`` and
``gordo rollback`` adopt generations with zero recompiles.

Implementation: load the freshly-built models and warm a throwaway
:class:`~gordo_components_tpu.server.engine.ServingEngine` wired to the
cache — the exact code path a server boot runs, so the cache keys match
by construction (re-deriving the engine's bucket/shape logic here would
be a second copy that drifts). Because warmup routes through the same
dispatch paths a server uses, the export covers whatever the boot will
need: the ``serving-mega`` fused megabatch executable on replicated
engines (ARCHITECTURE §15), the cold/hot programs in shard mode — under
the same ``GORDO_MEGABATCH*`` env the server will boot with.
Best-effort end to end: a failed export costs the first server boot its
compiles, never the build its artifacts.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


def export_serving_cache(
    model_dirs: Dict[str, str],
    cache_root: str,
    rows: Optional[int] = None,
    shard_fleet: bool = False,
) -> Dict[str, Any]:
    """Warm the serving compile cache at ``cache_root`` for the fleet in
    ``model_dirs`` (``{machine_name: model_dir}``). Returns a summary
    (buckets warmed, cache hits/writes, skipped machines); raises only on
    programmer error — per-machine load failures are skipped and named.

    ``rows``: warm the row bucket real traffic will hit (default: each
    bucket's minimum scorable request, the same default ``warmup()``
    uses). ``shard_fleet``: warm the mesh-sharded engine variant instead
    (must match how the server will boot — sharding is part of the key).
    """
    from .. import precision as precision_mod
    from ..serializer import load, load_metadata
    from ..server.engine import ServingEngine
    from ..store.generations import resolve_artifact_dir
    from .store import CompileCacheStore

    started = time.perf_counter()
    models: Dict[str, Any] = {}
    skipped: Dict[str, str] = {}
    precisions: Dict[str, str] = {}
    quantized: Dict[str, Any] = {}
    for name, model_dir in sorted(model_dirs.items()):
        try:
            models[name] = load(model_dir)
            # §19: warm each machine at its manifest-pinned precision —
            # a bf16 fleet whose export warmed f32 variants would pay
            # full compiles at boot, defeating the export
            precisions[name] = precision_mod.of_metadata(
                load_metadata(model_dir)
            )
            if precisions[name] == "int8":
                pair = precision_mod.load_quantized(
                    resolve_artifact_dir(model_dir)
                )
                if pair is not None:
                    quantized[name] = pair
        except Exception as exc:
            models.pop(name, None)
            skipped[name] = f"{type(exc).__name__}: {exc}"
    if not models:
        return {"buckets": 0, "machines": 0, "skipped": skipped}

    mesh = None
    if shard_fleet:
        from ..parallel.mesh import fleet_mesh

        mesh = fleet_mesh()
    store = CompileCacheStore(cache_root)
    engine = ServingEngine(
        models, mesh=mesh, compile_cache=store,
        precisions=precisions, quantized=quantized,
    )
    try:
        buckets = engine.warmup(rows)
    finally:
        engine.close()
    summary = {
        "buckets": buckets,
        "machines": len(models),
        "skipped": skipped,
        "cache_root": store.root,
        "cache": dict(store.counters),
        "duration_s": round(time.perf_counter() - started, 3),
    }
    logger.info(
        "Serving compile cache export: %d bucket(s) over %d machine(s) in "
        "%.1fs (hits %d, writes %d) -> %s",
        buckets, len(models), summary["duration_s"],
        store.counters.get("hit", 0), store.counters.get("write", 0),
        store.root,
    )
    return summary
