from .client import Client, ClientError, QuotaExceeded
from .forwarders import (
    CsvForwarder,
    ForwardPredictionsIntoInflux,
    PredictionForwarder,
)
from .utils import make_date_ranges

__all__ = [
    "Client",
    "ClientError",
    "QuotaExceeded",
    "PredictionForwarder",
    "CsvForwarder",
    "ForwardPredictionsIntoInflux",
    "make_date_ranges",
]
