from .client import Client, ClientError
from .forwarders import (
    CsvForwarder,
    ForwardPredictionsIntoInflux,
    PredictionForwarder,
)
from .utils import make_date_ranges

__all__ = [
    "Client",
    "ClientError",
    "PredictionForwarder",
    "CsvForwarder",
    "ForwardPredictionsIntoInflux",
    "make_date_ranges",
]
