"""Bulk prediction client.

Reference parity: ``gordo_components/client/client.py`` [UNVERIFIED] —
``Client.predict(start, end)`` resolves machine endpoints, splits the range
into chunks (:func:`make_date_ranges`), fires concurrent HTTP requests with
retry/backoff (aiohttp), assembles per-machine score DataFrames, and hands
them to forwarders. The server does the data fetch + TPU-batched scoring
per chunk (``?start&end`` path — SURVEY.md §4.3).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from datetime import datetime
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from ..observability import tracing
from ..observability.registry import REGISTRY
from ..resilience import deadline
from ..resilience.breaker import BreakerBoard
from .forwarders import PredictionForwarder
from .utils import make_date_ranges

logger = logging.getLogger(__name__)

_M_RETRIES = REGISTRY.counter(
    "gordo_client_retries_total",
    "Client request retries, by cause (timeout / connection / http_5xx / "
    "bad_body) — the client-side flakiness signal",
    labels=("reason",),
)
_M_REQUESTS = REGISTRY.counter(
    "gordo_client_requests_total",
    "Client requests by terminal outcome (ok / permanent_4xx / exhausted "
    "/ circuit_open / budget_exhausted)",
    labels=("outcome",),
)


class ClientError(RuntimeError):
    """A request failed permanently (4xx, or retries exhausted)."""


class Client:
    def __init__(
        self,
        base_url: str,
        project: str = "project",
        machines: Optional[Sequence[str]] = None,
        max_interval: str = "1D",
        parallelism: int = 10,
        retries: int = 3,
        retry_backoff: float = 0.5,
        timeout: float = 60.0,
        retry_budget: Optional[float] = None,
        breaker_recovery: float = 30.0,
        forwarders: Optional[List[PredictionForwarder]] = None,
    ):
        """``retry_budget``: wall-clock cap (seconds) on one call's retries
        + backoff, so a flapping server cannot stretch a call past what the
        caller budgeted (any bound ``resilience.deadline`` tightens it
        further). ``breaker_recovery``: seconds an endpoint's circuit stays
        open after tripping before one probe request tests it again."""
        self.base_url = base_url.rstrip("/")
        self.project = project
        self.machines = list(machines) if machines else None
        self.max_interval = max_interval
        self.parallelism = parallelism
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.timeout = timeout
        self.retry_budget = retry_budget
        # ONE circuit per endpoint, shared by every chunk fetch this client
        # fires: a dead server trips after a few failures and the remaining
        # machine × chunk requests fail in microseconds instead of each
        # paying a full connect/read timeout
        self._breakers = BreakerBoard(recovery_time=breaker_recovery)
        self.forwarders = forwarders or []

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with ±50% jitter: a fleet of clients whose
        chunks all failed on the same server hiccup must not re-arrive in
        one synchronized wave (the bare ``backoff * 2**(n-1)`` did exactly
        that — every chunk of every machine retried on the same beat)."""
        return self.retry_backoff * 2 ** (attempt - 1) * random.uniform(0.5, 1.5)

    def _breaker(self):
        return self._breakers.get(self.base_url)

    def _budget_left(self, started: float) -> Optional[float]:
        """Seconds of retry budget remaining for a call begun at
        ``started`` — the tighter of the per-call ``retry_budget`` and any
        deadline bound on the calling context. None = unbounded."""
        candidates = []
        if self.retry_budget is not None:
            candidates.append(self.retry_budget - (time.monotonic() - started))
        bound = deadline.remaining()
        if bound is not None:
            candidates.append(bound)
        return min(candidates) if candidates else None

    def _retry_delay(
        self,
        attempt: int,
        started: float,
        retry_after: Optional[float] = None,
    ) -> Optional[float]:
        """How long to sleep before retry ``attempt`` — honoring a server's
        ``Retry-After`` hint when it exceeds our own backoff — or None when
        the remaining budget cannot cover the wait plus one more attempt
        (retrying past the caller's deadline only produces answers nobody
        is waiting for)."""
        delay = self._backoff_delay(attempt)
        if retry_after is not None:
            delay = max(delay, retry_after)
        left = self._budget_left(started)
        if left is not None and delay >= left:
            return None
        return delay

    @staticmethod
    def _parse_retry_after(value: Optional[str]) -> Optional[float]:
        """``Retry-After`` seconds form only (our server always sends it);
        an HTTP-date or garbage value forfeits the hint, never errors."""
        if not value:
            return None
        try:
            return max(0.0, float(value))
        except (TypeError, ValueError):
            return None

    def _headers(self) -> Dict[str, str]:
        """Per-request headers: trace id always; the context deadline's
        remaining budget rides ``X-Gordo-Deadline`` so the server can 504
        work we have already given up on."""
        headers = {tracing.TRACE_HEADER: tracing.current_or_new()}
        budget = deadline.header_value()
        if budget is not None:
            headers[deadline.DEADLINE_HEADER] = budget
        return headers

    @staticmethod
    def _refresh_deadline_header(headers: Dict[str, str]) -> None:
        """Retries re-stamp the REMAINING budget (the trace id stays fixed
        for the call): a header frozen at first attempt would overstate
        what the caller still has, and the server would under-504."""
        budget = deadline.header_value()
        if budget is not None:
            headers[deadline.DEADLINE_HEADER] = budget

    # -- endpoint resolution -------------------------------------------------
    def resolve_machines(self) -> List[str]:
        """Explicit machine list, or discovery via the server's /models
        listing (the role watchman's endpoint registry plays upstream)."""
        if self.machines is not None:
            return self.machines
        import requests

        response = requests.get(f"{self.base_url}/models", timeout=self.timeout)
        response.raise_for_status()
        return response.json()["models"]

    # -- async core ----------------------------------------------------------
    async def _fetch_chunk(
        self, session, semaphore, machine: str, start, end
    ) -> Dict[str, Any]:
        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        params = {"start": start.isoformat(), "end": end.isoformat()}
        # one trace id per chunk request (adopting any id already bound to
        # the calling context): the server echoes it and stamps it on its
        # log records, so a slow chunk is grep-able end to end
        headers = self._headers()
        breaker = self._breaker()
        started = time.monotonic()
        last_error: Optional[str] = None
        retry_after: Optional[float] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self._retry_delay(attempt, started, retry_after)
                if delay is None:
                    _M_REQUESTS.labels("budget_exhausted").inc()
                    raise ClientError(
                        f"{machine} [{start}, {end}): retry budget "
                        f"exhausted ({last_error})"
                    )
                await asyncio.sleep(delay)
                self._refresh_deadline_header(headers)
            retry_after = None
            if not breaker.allow():
                # every chunk to this base URL shares the circuit: a dead
                # endpoint costs the few calls that tripped it, the rest
                # fail here in microseconds
                _M_REQUESTS.labels("circuit_open").inc()
                raise ClientError(
                    f"{machine} [{start}, {end}): circuit open for "
                    f"{self.base_url} ({last_error or 'recent failures'})"
                )
            try:
                async with semaphore:
                    async with session.post(
                        url, params=params, headers=headers
                    ) as response:
                        if 400 <= response.status < 500:
                            breaker.record(True)  # alive — the REQUEST is bad
                            body = await response.text()
                            _M_REQUESTS.labels("permanent_4xx").inc()
                            raise ClientError(
                                f"{machine} [{start}, {end}): "
                                f"HTTP {response.status}: {body[:500]}"
                            )
                        if response.status >= 500:
                            hint = self._parse_retry_after(
                                response.headers.get("Retry-After")
                            )
                            # flow control from a LIVE server — a 503 shed
                            # carrying Retry-After, or a 504 for OUR expired
                            # deadline — must not count toward tripping the
                            # circuit; bare 5xx (dead proxy, crash) does
                            breaker.record(
                                response.status == 504
                                or (response.status == 503 and hint is not None)
                            )
                            retry_after = hint
                            last_error = f"HTTP {response.status}"
                            _M_RETRIES.labels("http_5xx").inc()
                            continue
                        payload = await response.json()
                        breaker.record(True)
                        _M_REQUESTS.labels("ok").inc()
                        return payload
            except ClientError:
                raise
            except asyncio.TimeoutError as exc:  # distinct: a timing-out
                # server looks healthy to connection-error counters
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
            except Exception as exc:  # connection errors -> retry
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
        _M_REQUESTS.labels("exhausted").inc()
        raise ClientError(
            f"{machine} [{start}, {end}): retries exhausted ({last_error})"
        )

    async def _predict_async(
        self, machines: List[str], ranges
    ) -> Dict[str, pd.DataFrame]:
        import aiohttp

        semaphore = asyncio.Semaphore(self.parallelism)
        timeout = aiohttp.ClientTimeout(total=self.timeout)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            tasks = {
                (machine, i): asyncio.ensure_future(
                    self._fetch_chunk(session, semaphore, machine, start, end)
                )
                for machine in machines
                for i, (start, end) in enumerate(ranges)
            }
            # return_exceptions: let every chunk finish, then surface the
            # first failure via task.result() below (avoids orphan tasks)
            await asyncio.gather(*tasks.values(), return_exceptions=True)
        frames: Dict[str, pd.DataFrame] = {}
        for machine in machines:
            chunks = [
                self._chunk_frame(tasks[(machine, i)].result())
                for i in range(len(ranges))
            ]
            chunks = [c for c in chunks if c is not None]
            frames[machine] = (
                pd.concat(chunks).sort_index() if chunks else pd.DataFrame()
            )
        return frames

    @staticmethod
    def _chunk_frame(payload: Dict[str, Any]) -> Optional[pd.DataFrame]:
        data = payload.get("data", {})
        total = data.get("total-anomaly-score")
        if not total:
            return None
        scores = np.asarray(data["tag-anomaly-scores"], dtype=np.float64)
        columns = {
            f"tag-anomaly-score-{i}": scores[:, i] for i in range(scores.shape[1])
        }
        columns["total-anomaly-score"] = np.asarray(total, dtype=np.float64)
        index = pd.to_datetime(data["timestamps"]) if "timestamps" in data else None
        return pd.DataFrame(columns, index=index)

    # -- public API ----------------------------------------------------------
    def predict_frame(
        self, machine: str, frame: pd.DataFrame, fmt: str = "parquet"
    ) -> pd.DataFrame:
        """Score a client-held DataFrame directly (no server-side fetch):
        POST it to ``/anomaly/prediction`` as parquet (default — columnar
        and far smaller on the wire than JSON records) or JSON records, and
        return the scored frame (timestamp-indexed when ``frame`` has a
        DatetimeIndex and fmt is parquet)."""
        import requests

        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        if fmt == "parquet":
            import io

            buffer = io.BytesIO()
            frame.to_parquet(buffer)
            kwargs: Dict[str, Any] = {
                "data": buffer.getvalue(),
                "headers": {"Content-Type": "application/x-parquet"},
            }
        elif fmt == "json":
            kwargs = {"json": {"X": frame.to_dict(orient="records")}}
        else:
            raise ValueError(f"fmt must be 'parquet' or 'json', got {fmt!r}")

        # same retry contract as the async path (_fetch_chunk): 4xx is
        # permanent, 5xx/connection errors retry with jittered backoff
        # (honoring any Retry-After and the call's retry budget), the
        # endpoint's shared circuit short-circuits a dead server, and
        # every terminal failure surfaces as ClientError
        kwargs.setdefault("headers", {}).update(self._headers())
        breaker = self._breaker()
        started = time.monotonic()
        last_error: Optional[str] = None
        retry_after: Optional[float] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self._retry_delay(attempt, started, retry_after)
                if delay is None:
                    _M_REQUESTS.labels("budget_exhausted").inc()
                    raise ClientError(
                        f"{machine}: retry budget exhausted ({last_error})"
                    )
                time.sleep(delay)
                self._refresh_deadline_header(kwargs["headers"])
            retry_after = None
            if not breaker.allow():
                _M_REQUESTS.labels("circuit_open").inc()
                raise ClientError(
                    f"{machine}: circuit open for {self.base_url} "
                    f"({last_error or 'recent failures'})"
                )
            try:
                response = requests.post(url, timeout=self.timeout, **kwargs)
            except requests.Timeout as exc:
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
                continue
            except requests.RequestException as exc:
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
                continue
            if 400 <= response.status_code < 500:
                breaker.record(True)  # alive — the REQUEST is bad
                _M_REQUESTS.labels("permanent_4xx").inc()
                raise ClientError(
                    f"{machine}: HTTP {response.status_code}: "
                    f"{response.text[:500]}"
                )
            if response.status_code >= 500:
                hint = self._parse_retry_after(
                    response.headers.get("Retry-After")
                )
                # same live-server carve-outs as the async path: 503+hint
                # and 504 are answers, not deaths
                breaker.record(
                    response.status_code == 504
                    or (response.status_code == 503 and hint is not None)
                )
                retry_after = hint
                last_error = f"HTTP {response.status_code}"
                _M_RETRIES.labels("http_5xx").inc()
                continue
            try:
                payload = response.json()
            except ValueError:  # 2xx with a non-JSON body (broken proxy):
                # retryable, and terminal failures stay ClientError
                breaker.record(False)
                last_error = "2xx response with non-JSON body"
                _M_RETRIES.labels("bad_body").inc()
                continue
            breaker.record(True)
            _M_REQUESTS.labels("ok").inc()
            chunk = self._chunk_frame(payload)
            return chunk if chunk is not None else pd.DataFrame()
        _M_REQUESTS.labels("exhausted").inc()
        raise ClientError(
            f"{machine}: retries exhausted ({last_error})"
        )

    def predict(
        self,
        start: Union[str, datetime],
        end: Union[str, datetime],
        machine_names: Optional[Sequence[str]] = None,
    ) -> Dict[str, pd.DataFrame]:
        """Score ``[start, end)`` for every machine; returns
        ``{machine: DataFrame}`` (timestamp-indexed per-tag + total scores)
        and pushes each frame through the configured forwarders."""
        machines = list(machine_names) if machine_names else self.resolve_machines()
        ranges = make_date_ranges(start, end, self.max_interval)
        logger.info(
            "Client.predict: %d machines x %d chunks", len(machines), len(ranges)
        )
        frames = asyncio.run(self._predict_async(machines, ranges))
        for forwarder in self.forwarders:
            for machine, frame in frames.items():
                forwarder.forward(machine, frame)
        return frames
