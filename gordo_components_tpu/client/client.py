"""Bulk prediction client.

Reference parity: ``gordo_components/client/client.py`` [UNVERIFIED] —
``Client.predict(start, end)`` resolves machine endpoints, splits the range
into chunks (:func:`make_date_ranges`), fires concurrent HTTP requests with
retry/backoff (aiohttp), assembles per-machine score DataFrames, and hands
them to forwarders. The server does the data fetch + TPU-batched scoring
per chunk (``?start&end`` path — SURVEY.md §4.3).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from datetime import datetime
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from ..observability import tracing
from ..observability.registry import REGISTRY
from .forwarders import PredictionForwarder
from .utils import make_date_ranges

logger = logging.getLogger(__name__)

_M_RETRIES = REGISTRY.counter(
    "gordo_client_retries_total",
    "Client request retries, by cause (timeout / connection / http_5xx / "
    "bad_body) — the client-side flakiness signal",
    labels=("reason",),
)
_M_REQUESTS = REGISTRY.counter(
    "gordo_client_requests_total",
    "Client requests by terminal outcome (ok / permanent_4xx / exhausted)",
    labels=("outcome",),
)


class ClientError(RuntimeError):
    """A request failed permanently (4xx, or retries exhausted)."""


class Client:
    def __init__(
        self,
        base_url: str,
        project: str = "project",
        machines: Optional[Sequence[str]] = None,
        max_interval: str = "1D",
        parallelism: int = 10,
        retries: int = 3,
        retry_backoff: float = 0.5,
        timeout: float = 60.0,
        forwarders: Optional[List[PredictionForwarder]] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.project = project
        self.machines = list(machines) if machines else None
        self.max_interval = max_interval
        self.parallelism = parallelism
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.timeout = timeout
        self.forwarders = forwarders or []

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with ±50% jitter: a fleet of clients whose
        chunks all failed on the same server hiccup must not re-arrive in
        one synchronized wave (the bare ``backoff * 2**(n-1)`` did exactly
        that — every chunk of every machine retried on the same beat)."""
        return self.retry_backoff * 2 ** (attempt - 1) * random.uniform(0.5, 1.5)

    # -- endpoint resolution -------------------------------------------------
    def resolve_machines(self) -> List[str]:
        """Explicit machine list, or discovery via the server's /models
        listing (the role watchman's endpoint registry plays upstream)."""
        if self.machines is not None:
            return self.machines
        import requests

        response = requests.get(f"{self.base_url}/models", timeout=self.timeout)
        response.raise_for_status()
        return response.json()["models"]

    # -- async core ----------------------------------------------------------
    async def _fetch_chunk(
        self, session, semaphore, machine: str, start, end
    ) -> Dict[str, Any]:
        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        params = {"start": start.isoformat(), "end": end.isoformat()}
        # one trace id per chunk request (adopting any id already bound to
        # the calling context): the server echoes it and stamps it on its
        # log records, so a slow chunk is grep-able end to end
        headers = {tracing.TRACE_HEADER: tracing.current_or_new()}
        last_error: Optional[str] = None
        for attempt in range(self.retries + 1):
            if attempt:
                await asyncio.sleep(self._backoff_delay(attempt))
            try:
                async with semaphore:
                    async with session.post(
                        url, params=params, headers=headers
                    ) as response:
                        if 400 <= response.status < 500:
                            body = await response.text()
                            _M_REQUESTS.labels("permanent_4xx").inc()
                            raise ClientError(
                                f"{machine} [{start}, {end}): "
                                f"HTTP {response.status}: {body[:500]}"
                            )
                        if response.status >= 500:
                            last_error = f"HTTP {response.status}"
                            _M_RETRIES.labels("http_5xx").inc()
                            continue
                        payload = await response.json()
                        _M_REQUESTS.labels("ok").inc()
                        return payload
            except ClientError:
                raise
            except asyncio.TimeoutError as exc:  # distinct: a timing-out
                # server looks healthy to connection-error counters
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
            except Exception as exc:  # connection errors -> retry
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
        _M_REQUESTS.labels("exhausted").inc()
        raise ClientError(
            f"{machine} [{start}, {end}): retries exhausted ({last_error})"
        )

    async def _predict_async(
        self, machines: List[str], ranges
    ) -> Dict[str, pd.DataFrame]:
        import aiohttp

        semaphore = asyncio.Semaphore(self.parallelism)
        timeout = aiohttp.ClientTimeout(total=self.timeout)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            tasks = {
                (machine, i): asyncio.ensure_future(
                    self._fetch_chunk(session, semaphore, machine, start, end)
                )
                for machine in machines
                for i, (start, end) in enumerate(ranges)
            }
            # return_exceptions: let every chunk finish, then surface the
            # first failure via task.result() below (avoids orphan tasks)
            await asyncio.gather(*tasks.values(), return_exceptions=True)
        frames: Dict[str, pd.DataFrame] = {}
        for machine in machines:
            chunks = [
                self._chunk_frame(tasks[(machine, i)].result())
                for i in range(len(ranges))
            ]
            chunks = [c for c in chunks if c is not None]
            frames[machine] = (
                pd.concat(chunks).sort_index() if chunks else pd.DataFrame()
            )
        return frames

    @staticmethod
    def _chunk_frame(payload: Dict[str, Any]) -> Optional[pd.DataFrame]:
        data = payload.get("data", {})
        total = data.get("total-anomaly-score")
        if not total:
            return None
        scores = np.asarray(data["tag-anomaly-scores"], dtype=np.float64)
        columns = {
            f"tag-anomaly-score-{i}": scores[:, i] for i in range(scores.shape[1])
        }
        columns["total-anomaly-score"] = np.asarray(total, dtype=np.float64)
        index = pd.to_datetime(data["timestamps"]) if "timestamps" in data else None
        return pd.DataFrame(columns, index=index)

    # -- public API ----------------------------------------------------------
    def predict_frame(
        self, machine: str, frame: pd.DataFrame, fmt: str = "parquet"
    ) -> pd.DataFrame:
        """Score a client-held DataFrame directly (no server-side fetch):
        POST it to ``/anomaly/prediction`` as parquet (default — columnar
        and far smaller on the wire than JSON records) or JSON records, and
        return the scored frame (timestamp-indexed when ``frame`` has a
        DatetimeIndex and fmt is parquet)."""
        import requests

        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        if fmt == "parquet":
            import io

            buffer = io.BytesIO()
            frame.to_parquet(buffer)
            kwargs: Dict[str, Any] = {
                "data": buffer.getvalue(),
                "headers": {"Content-Type": "application/x-parquet"},
            }
        elif fmt == "json":
            kwargs = {"json": {"X": frame.to_dict(orient="records")}}
        else:
            raise ValueError(f"fmt must be 'parquet' or 'json', got {fmt!r}")

        # same retry contract as the async path (_fetch_chunk): 4xx is
        # permanent, 5xx/connection errors retry with jittered backoff, and
        # every terminal failure surfaces as ClientError
        kwargs.setdefault("headers", {})[
            tracing.TRACE_HEADER
        ] = tracing.current_or_new()
        last_error: Optional[str] = None
        for attempt in range(self.retries + 1):
            if attempt:
                time.sleep(self._backoff_delay(attempt))
            try:
                response = requests.post(url, timeout=self.timeout, **kwargs)
            except requests.Timeout as exc:
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
                continue
            except requests.RequestException as exc:
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
                continue
            if 400 <= response.status_code < 500:
                _M_REQUESTS.labels("permanent_4xx").inc()
                raise ClientError(
                    f"{machine}: HTTP {response.status_code}: "
                    f"{response.text[:500]}"
                )
            if response.status_code >= 500:
                last_error = f"HTTP {response.status_code}"
                _M_RETRIES.labels("http_5xx").inc()
                continue
            try:
                payload = response.json()
            except ValueError:  # 2xx with a non-JSON body (broken proxy):
                # retryable, and terminal failures stay ClientError
                last_error = "2xx response with non-JSON body"
                _M_RETRIES.labels("bad_body").inc()
                continue
            _M_REQUESTS.labels("ok").inc()
            chunk = self._chunk_frame(payload)
            return chunk if chunk is not None else pd.DataFrame()
        _M_REQUESTS.labels("exhausted").inc()
        raise ClientError(
            f"{machine}: retries exhausted ({last_error})"
        )

    def predict(
        self,
        start: Union[str, datetime],
        end: Union[str, datetime],
        machine_names: Optional[Sequence[str]] = None,
    ) -> Dict[str, pd.DataFrame]:
        """Score ``[start, end)`` for every machine; returns
        ``{machine: DataFrame}`` (timestamp-indexed per-tag + total scores)
        and pushes each frame through the configured forwarders."""
        machines = list(machine_names) if machine_names else self.resolve_machines()
        ranges = make_date_ranges(start, end, self.max_interval)
        logger.info(
            "Client.predict: %d machines x %d chunks", len(machines), len(ranges)
        )
        frames = asyncio.run(self._predict_async(machines, ranges))
        for forwarder in self.forwarders:
            for machine, frame in frames.items():
                forwarder.forward(machine, frame)
        return frames
