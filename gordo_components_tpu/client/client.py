"""Bulk prediction client.

Reference parity: ``gordo_components/client/client.py`` [UNVERIFIED] —
``Client.predict(start, end)`` resolves machine endpoints, splits the range
into chunks (:func:`make_date_ranges`), fires concurrent HTTP requests with
retry/backoff (aiohttp), assembles per-machine score DataFrames, and hands
them to forwarders. The server does the data fetch + TPU-batched scoring
per chunk (``?start&end`` path — SURVEY.md §4.3).

Data plane (docs/ARCHITECTURE.md §12): chunk fetches negotiate the binary
``application/x-gordo-npz`` wire format — scores arrive as ONE npz blob of
float32 arrays instead of JSON floats — and every request of a ``Client``'s
lifetime shares ONE pooled ``aiohttp.ClientSession`` on a persistent
background event loop, so chunk fetches reuse kept-alive connections
instead of paying a TCP (re)connect per ``predict`` call. Call
:meth:`Client.close` (or use the client as a context manager) to release
the pool; a dropped client is cleaned up best-effort.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import threading
import time
from datetime import datetime
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from .. import wire
from ..analysis import lockcheck
from ..observability import flightrec, spans, tracing
from ..observability.registry import REGISTRY
from ..resilience import deadline, qos
from ..resilience.admission import DRAINING_HEADER
from ..resilience.breaker import BreakerBoard
from .forwarders import PredictionForwarder
from .utils import make_date_ranges

logger = logging.getLogger(__name__)

_M_RETRIES = REGISTRY.counter(
    "gordo_client_retries_total",
    "Client request retries, by cause (timeout / connection / http_5xx / "
    "bad_body) — the client-side flakiness signal",
    labels=("reason",),
)
_M_REQUESTS = REGISTRY.counter(
    "gordo_client_requests_total",
    "Client requests by terminal outcome (ok / permanent_4xx / exhausted "
    "/ circuit_open / budget_exhausted / quota_blocked / quota_exhausted)",
    labels=("outcome",),
)


class ClientError(RuntimeError):
    """A request failed permanently (4xx, or retries exhausted)."""


class QuotaExceeded(ClientError):
    """The server answered 429: THIS tenant's token bucket is empty. The
    transport is healthy — a 429 never counts against the circuit
    breaker — so the remedy is to slow down (``retry_after`` seconds)
    or raise the tenant's quota, not to fail over."""

    def __init__(
        self,
        message: str,
        retry_after: float = 1.0,
        tenant: str = qos.DEFAULT_TENANT,
    ):
        super().__init__(message)
        self.retry_after = retry_after
        self.tenant = tenant


class Client:
    def __init__(
        self,
        base_url: str,
        project: str = "project",
        machines: Optional[Sequence[str]] = None,
        max_interval: str = "1D",
        parallelism: int = 10,
        retries: int = 3,
        retry_backoff: float = 0.5,
        timeout: float = 60.0,
        retry_budget: Optional[float] = None,
        breaker_recovery: float = 30.0,
        forwarders: Optional[List[PredictionForwarder]] = None,
        tenant: Optional[str] = None,
    ):
        """``retry_budget``: wall-clock cap (seconds) on one call's retries
        + backoff, so a flapping server cannot stretch a call past what the
        caller budgeted (any bound ``resilience.deadline`` tightens it
        further). ``breaker_recovery``: seconds an endpoint's circuit stays
        open after tripping before one probe request tests it again.
        ``tenant``: principal name stamped on every request as
        ``X-Gordo-Tenant`` — the server maps it to a priority class and
        token-bucket quota (ARCHITECTURE §25); None rides as the server's
        default tenant."""
        self.base_url = base_url.rstrip("/")
        self.project = project
        self.machines = list(machines) if machines else None
        self.max_interval = max_interval
        self.parallelism = parallelism
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.timeout = timeout
        self.retry_budget = retry_budget
        # ONE circuit per endpoint, shared by every chunk fetch this client
        # fires: a dead server trips after a few failures and the remaining
        # machine × chunk requests fail in microseconds instead of each
        # paying a full connect/read timeout
        self._breakers = BreakerBoard(recovery_time=breaker_recovery)
        self.tenant = tenant
        # per-TENANT quota backoff, deliberately separate from the
        # per-base-url breaker above: a 429 means the server is healthy
        # and saying no to THIS principal, so it must not open the
        # transport circuit (which would also fail every other tenant
        # sharing this client process). Values are monotonic "clear at"
        # times; plain dict get/set are atomic under the GIL and the
        # worst race is one extra probe request, so no lock.
        self._quota_until: Dict[str, float] = {}
        self.forwarders = forwarders or []
        # ONE pooled aiohttp session for the client's lifetime, living on a
        # persistent background event loop (asyncio.run per predict() call
        # would tear the loop — and with it every kept-alive connection —
        # down between calls); both are created lazily on first use and
        # released by close()
        self._io_lock = lockcheck.named_lock("client.io")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._session = None
        self._session_loop: Optional[asyncio.AbstractEventLoop] = None

    # -- pooled I/O lifecycle ------------------------------------------------
    def _submit(self, coro) -> "asyncio.Future":
        """Schedule ``coro`` on the pooled I/O loop (creating it on first
        use). Loop lookup and submission are ONE critical section with
        close(): a submission therefore either lands on the loop BEFORE
        close()'s cancel sweep (call_soon_threadsafe callbacks run FIFO,
        so the task exists when the sweep cancels everything → the caller
        gets CancelledError) or sees the swapped-out None and builds a
        fresh loop — it can never target a loop that is already stopping,
        which would freeze its future unresolved."""
        with self._io_lock:
            if self._loop is None or self._loop.is_closed():
                self._loop = asyncio.new_event_loop()
                self._loop_thread = threading.Thread(
                    target=self._loop.run_forever,
                    name="gordo-client-io",
                    daemon=True,
                )
                self._loop_thread.start()
            return asyncio.run_coroutine_threadsafe(coro, self._loop)

    async def _ensure_session(self):
        """The pooled session (created on the I/O loop). Keep-alive is
        aiohttp's default — chunk N+1 to the same host reuses chunk N's
        connection instead of re-handshaking. The session is pinned to the
        loop it was created on: a close() racing a predict() can leave a
        session bound to the OLD, dying loop (the predict re-created it
        just before its cancel landed), and reusing that on a fresh loop
        makes aiohttp raise on every request — so a loop mismatch discards
        and rebuilds instead."""
        import aiohttp

        loop = asyncio.get_running_loop()
        if (
            self._session is None
            or self._session.closed
            or self._session_loop is not loop
        ):
            if self._session is not None and not self._session.closed:
                # bound to a defunct loop; closing it needs that loop, so
                # drop the reference (the connector is reclaimed by GC)
                logger.warning(
                    "Discarding pooled session bound to a closed I/O loop"
                )
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.timeout)
            )
            self._session_loop = loop
        return self._session

    def close(self) -> None:
        """Release the pooled session and stop the background I/O loop.
        Idempotent; a closed client can still be used again (the pool is
        recreated lazily), so close() is a resource release, not a
        poison pill."""
        with self._io_lock:
            loop, self._loop = self._loop, None
            thread, self._loop_thread = self._loop_thread, None
            session, self._session = self._session, None
            self._session_loop = None
        if loop is None or loop.is_closed():
            return
        try:
            if session is not None and not session.closed:
                asyncio.run_coroutine_threadsafe(
                    session.close(), loop
                ).result(timeout=10)
        except Exception:
            logger.warning(
                "Pooled session did not close cleanly", exc_info=True
            )
        finally:
            def _shutdown():
                # cancel in-flight work BEFORE stopping: a predict() racing
                # close() must surface CancelledError in its .result(),
                # never block forever on a future whose loop silently
                # exited mid-await. The loop stops only AFTER the
                # cancelled tasks finish unwinding — stopping in the same
                # tick would strand a task mid-cancellation with its
                # future (and the thread joined on it) unresolved.
                tasks = list(asyncio.all_tasks(loop))
                for task in tasks:
                    task.cancel()

                async def _stop_when_unwound():
                    await asyncio.gather(*tasks, return_exceptions=True)
                    loop.stop()

                loop.create_task(_stop_when_unwound())

            loop.call_soon_threadsafe(_shutdown)
            if thread is not None:
                thread.join(timeout=10)
            # only close a loop that actually stopped: if work is still in
            # flight past the join timeout (a predict() racing close()),
            # closing would raise from __exit__ and leave the client
            # half-torn — the daemon thread and its loop are leaked
            # deliberately and noisily instead
            if thread is None or not thread.is_alive():
                loop.close()
            else:
                logger.warning(
                    "Client I/O loop still busy after close(); leaking the "
                    "daemon loop thread rather than closing a running loop"
                )

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()

    def __del__(self):  # best-effort backstop; close() is the contract
        try:
            self.close()
        except Exception:  # lint: allow-swallow(GC-time backstop: __del__ must never raise, and interpreter teardown makes logging unsafe)
            pass

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with ±50% jitter: a fleet of clients whose
        chunks all failed on the same server hiccup must not re-arrive in
        one synchronized wave (the bare ``backoff * 2**(n-1)`` did exactly
        that — every chunk of every machine retried on the same beat)."""
        return self.retry_backoff * 2 ** (attempt - 1) * random.uniform(0.5, 1.5)

    def _breaker(self):
        return self._breakers.get(self.base_url)

    def _budget_left(self, started: float) -> Optional[float]:
        """Seconds of retry budget remaining for a call begun at
        ``started`` — the tighter of the per-call ``retry_budget`` and any
        deadline bound on the calling context. None = unbounded."""
        candidates = []
        if self.retry_budget is not None:
            candidates.append(self.retry_budget - (time.monotonic() - started))
        bound = deadline.remaining()
        if bound is not None:
            candidates.append(bound)
        return min(candidates) if candidates else None

    def _retry_delay(
        self,
        attempt: int,
        started: float,
        retry_after: Optional[float] = None,
    ) -> Optional[float]:
        """How long to sleep before retry ``attempt`` — honoring a server's
        ``Retry-After`` hint when it exceeds our own backoff — or None when
        the remaining budget cannot cover the wait plus one more attempt
        (retrying past the caller's deadline only produces answers nobody
        is waiting for).

        ``retry_after <= 0`` means "retry NOW": the draining-worker shed
        (``X-Gordo-Draining``) sets it — the fleet is mid-rolling-restart
        and the router will route the retry to a live worker, so the full
        shed backoff would only stretch the restart window."""
        delay = self._backoff_delay(attempt)
        if retry_after is not None:
            delay = min(delay, 0.05) if retry_after <= 0 else max(
                delay, retry_after
            )
        left = self._budget_left(started)
        if left is not None and delay >= left:
            return None
        return delay

    @staticmethod
    def _parse_retry_after(value: Optional[str]) -> Optional[float]:
        """``Retry-After`` seconds form only (our server always sends it);
        an HTTP-date or garbage value forfeits the hint, never errors."""
        if not value:
            return None
        try:
            return max(0.0, float(value))
        except (TypeError, ValueError):
            return None

    def _headers(self) -> Dict[str, str]:
        """Per-request headers: trace id always; npz-first content
        negotiation (an old server ignores the Accept and answers JSON —
        the response handlers dispatch on Content-Type, so both work); the
        context deadline's remaining budget rides ``X-Gordo-Deadline`` so
        the server can 504 work we have already given up on; the tenant
        name (when configured) rides ``X-Gordo-Tenant`` so the server can
        class and meter this principal."""
        headers = {
            tracing.TRACE_HEADER: tracing.current_or_new(),
            "Accept": f"{wire.NPZ_CONTENT_TYPE}, application/json",
        }
        budget = deadline.header_value()
        if budget is not None:
            headers[deadline.DEADLINE_HEADER] = budget
        if self.tenant:
            headers[qos.TENANT_HEADER] = self.tenant
        return headers

    # -- per-tenant quota backoff -------------------------------------------
    def _quota_key(self) -> str:
        return self.tenant or qos.DEFAULT_TENANT

    def _quota_blocked(self) -> Optional[float]:
        """Seconds until this tenant's 429 backoff clears, or None when
        clear. Checked once per call (not per retry): a call that starts
        inside the window fails fast with the typed :class:`QuotaExceeded`
        instead of burning its retry budget re-earning the same 429."""
        until = self._quota_until.get(self._quota_key(), 0.0)
        remaining = until - time.monotonic()
        return remaining if remaining > 0 else None

    def _note_quota(self, retry_after: Optional[float]) -> float:
        """Record a 429's Retry-After against this tenant (1s when the
        server sent no usable hint) and return the wait."""
        wait = retry_after if retry_after and retry_after > 0 else 1.0
        key = self._quota_key()
        self._quota_until[key] = max(
            self._quota_until.get(key, 0.0), time.monotonic() + wait
        )
        return wait

    def _check_quota_gate(self, what: str) -> None:
        blocked = self._quota_blocked()
        if blocked is not None:
            _M_REQUESTS.labels("quota_blocked").inc()
            raise QuotaExceeded(
                f"{what}: tenant {self._quota_key()!r} backing off "
                f"{blocked:.2f}s after HTTP 429",
                retry_after=blocked,
                tenant=self._quota_key(),
            )

    def _exhausted_error(
        self,
        message: str,
        last_error: Optional[str],
        retry_after: Optional[float],
    ) -> ClientError:
        """Terminal failure, typed: a retry budget that died on quota
        responses surfaces as :class:`QuotaExceeded` (the caller can back
        off the principal) instead of a generic retries-exhausted."""
        if last_error == "HTTP 429 (quota)":
            return QuotaExceeded(
                message,
                retry_after=retry_after if retry_after else 1.0,
                tenant=self._quota_key(),
            )
        return ClientError(message)

    @staticmethod
    def _refresh_deadline_header(headers: Dict[str, str]) -> None:
        """Retries re-stamp the REMAINING budget (the trace id stays fixed
        for the call): a header frozen at first attempt would overstate
        what the caller still has, and the server would under-504."""
        budget = deadline.header_value()
        if budget is not None:
            headers[deadline.DEADLINE_HEADER] = budget

    # -- endpoint resolution -------------------------------------------------
    def resolve_machines(self) -> List[str]:
        """Explicit machine list, or discovery via the server's /models
        listing (the role watchman's endpoint registry plays upstream)."""
        if self.machines is not None:
            return self.machines
        import requests

        response = requests.get(f"{self.base_url}/models", timeout=self.timeout)
        response.raise_for_status()
        return response.json()["models"]

    # -- async core ----------------------------------------------------------
    async def _fetch_chunk(
        self, session, semaphore, machine: str, start, end,
        ctx: spans.SpanContext = spans.EMPTY_CONTEXT,
    ) -> Dict[str, Any]:
        # the caller's span context arrives EXPLICITLY: this coroutine
        # runs on the pooled I/O loop's thread, whose contextvars know
        # nothing about the predict() caller — binding restores the trace
        # id for this task's log records and routes chunk_fetch/decode
        # spans to the caller's timeline
        with spans.bind(ctx):
            # one trace id per chunk request (adopting any id already
            # bound): the server echoes it and stamps it on its log
            # records, so a slow chunk is grep-able end to end — and
            # binding it HERE (not just in the header) stamps the
            # client-side retry/backoff records of this chunk too
            with tracing.trace(tracing.current_or_new()):
                return await self._fetch_chunk_traced(
                    session, semaphore, machine, start, end
                )

    async def _fetch_chunk_traced(
        self, session, semaphore, machine: str, start, end
    ) -> Dict[str, Any]:
        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        params = {"start": start.isoformat(), "end": end.isoformat()}
        headers = self._headers()
        breaker = self._breaker()
        self._check_quota_gate(f"{machine} [{start}, {end})")
        started = time.monotonic()
        last_error: Optional[str] = None
        retry_after: Optional[float] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self._retry_delay(attempt, started, retry_after)
                if delay is None:
                    _M_REQUESTS.labels("budget_exhausted").inc()
                    raise self._exhausted_error(
                        f"{machine} [{start}, {end}): retry budget "
                        f"exhausted ({last_error})",
                        last_error,
                        retry_after,
                    )
                await asyncio.sleep(delay)
                self._refresh_deadline_header(headers)
            retry_after = None
            if not breaker.allow():
                # every chunk to this base URL shares the circuit: a dead
                # endpoint costs the few calls that tripped it, the rest
                # fail here in microseconds
                _M_REQUESTS.labels("circuit_open").inc()
                spans.event(
                    "circuit_open", base_url=self.base_url, machine=machine
                )
                raise ClientError(
                    f"{machine} [{start}, {end}): circuit open for "
                    f"{self.base_url} ({last_error or 'recent failures'})"
                )
            try:
                async with semaphore:
                    with spans.stage(
                        "chunk_fetch", machine=machine, attempt=attempt
                    ):
                        async with session.post(
                            url, params=params, headers=headers
                        ) as response:
                            if response.status == 429:
                                # quota, not failure: the server is
                                # healthy and saying no to THIS principal
                                # — never trips the transport circuit,
                                # backs off the TENANT instead
                                breaker.record(True)
                                hint = self._parse_retry_after(
                                    response.headers.get("Retry-After")
                                )
                                retry_after = self._note_quota(hint)
                                last_error = "HTTP 429 (quota)"
                                _M_RETRIES.labels("quota").inc()
                                continue
                            if 400 <= response.status < 500:
                                breaker.record(True)  # alive — the REQUEST
                                # is bad
                                body = await response.text()
                                _M_REQUESTS.labels("permanent_4xx").inc()
                                raise ClientError(
                                    f"{machine} [{start}, {end}): "
                                    f"HTTP {response.status}: {body[:500]}"
                                )
                            if response.status >= 500:
                                hint = self._parse_retry_after(
                                    response.headers.get("Retry-After")
                                )
                                if response.status == 503 and (
                                    response.headers.get(DRAINING_HEADER)
                                ):
                                    # a draining worker's shed (rolling
                                    # restart): alive, deliberate, and
                                    # momentary — retry NOW, the router
                                    # re-routes to a live worker
                                    breaker.record(True)
                                    retry_after = 0.0
                                    last_error = "HTTP 503 (draining)"
                                    _M_RETRIES.labels("draining").inc()
                                    continue
                                # flow control from a LIVE server — a 503
                                # shed carrying Retry-After, or a 504 for
                                # OUR expired deadline — must not count
                                # toward tripping the circuit; bare 5xx
                                # (dead proxy, crash) does
                                breaker.record(
                                    response.status == 504
                                    or (response.status == 503
                                        and hint is not None)
                                )
                                retry_after = hint
                                last_error = f"HTTP {response.status}"
                                _M_RETRIES.labels("http_5xx").inc()
                                continue
                            ctype = wire.content_type_of(
                                response.headers.get("Content-Type")
                            )
                            raw = await response.read()
                    if ctype == wire.NPZ_CONTENT_TYPE:
                        with spans.stage("decode", format="npz"):
                            payload = wire.payload_from_npz(raw)
                    else:
                        with spans.stage("decode", format="json"):
                            payload = json.loads(raw)
                    breaker.record(True)
                    _M_REQUESTS.labels("ok").inc()
                    return payload
            except ClientError:
                raise
            except asyncio.TimeoutError as exc:  # distinct: a timing-out
                # server looks healthy to connection-error counters
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
            except Exception as exc:  # connection errors -> retry
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
        outcome = (
            "quota_exhausted"
            if last_error == "HTTP 429 (quota)"
            else "exhausted"
        )
        _M_REQUESTS.labels(outcome).inc()
        raise self._exhausted_error(
            f"{machine} [{start}, {end}): retries exhausted ({last_error})",
            last_error,
            retry_after,
        )

    async def _predict_async(
        self, machines: List[str], ranges,
        ctx: spans.SpanContext = spans.EMPTY_CONTEXT,
    ) -> Dict[str, pd.DataFrame]:
        semaphore = asyncio.Semaphore(self.parallelism)
        # the POOLED session: one per Client (created here on first use),
        # NOT one per predict() call — keep-alive connections survive
        # across chunks and across calls (see close())
        session = await self._ensure_session()
        tasks = {
            (machine, i): asyncio.ensure_future(
                self._fetch_chunk(
                    session, semaphore, machine, start, end, ctx=ctx
                )
            )
            for machine in machines
            for i, (start, end) in enumerate(ranges)
        }
        # return_exceptions: let every chunk finish, then surface the
        # first failure via task.result() below (avoids orphan tasks)
        await asyncio.gather(*tasks.values(), return_exceptions=True)
        frames: Dict[str, pd.DataFrame] = {}
        for machine in machines:
            chunks = [
                self._chunk_frame(tasks[(machine, i)].result())
                for i in range(len(ranges))
            ]
            chunks = [c for c in chunks if c is not None]
            frames[machine] = (
                pd.concat(chunks).sort_index() if chunks else pd.DataFrame()
            )
        return frames

    @staticmethod
    def _chunk_frame(payload: Dict[str, Any]) -> Optional[pd.DataFrame]:
        """One chunk payload → frame. Serves BOTH wire formats: JSON
        payloads carry nested lists, npz payloads carry numpy arrays
        (``wire.payload_from_npz``) — hence ``len()`` emptiness (array
        truthiness raises) and ``np.asarray`` (a no-copy pass-through for
        the arrays)."""
        data = payload.get("data", {})
        total = data.get("total-anomaly-score")
        if total is None or len(total) == 0:
            return None
        scores = np.asarray(data["tag-anomaly-scores"], dtype=np.float64)
        columns = {
            f"tag-anomaly-score-{i}": scores[:, i] for i in range(scores.shape[1])
        }
        columns["total-anomaly-score"] = np.asarray(total, dtype=np.float64)
        index = pd.to_datetime(data["timestamps"]) if "timestamps" in data else None
        return pd.DataFrame(columns, index=index)

    # -- public API ----------------------------------------------------------
    def predict_frame(
        self, machine: str, frame: pd.DataFrame, fmt: str = "parquet"
    ) -> pd.DataFrame:
        """Score a client-held DataFrame directly (no server-side fetch):
        POST it to ``/anomaly/prediction`` as parquet (default — columnar
        and far smaller on the wire than JSON records) or JSON records, and
        return the scored frame (timestamp-indexed when ``frame`` has a
        DatetimeIndex and fmt is parquet)."""
        import requests

        url = (
            f"{self.base_url}/gordo/v0/{self.project}/{machine}"
            f"/anomaly/prediction"
        )
        if fmt == "parquet":
            import io

            buffer = io.BytesIO()
            frame.to_parquet(buffer)
            kwargs: Dict[str, Any] = {
                "data": buffer.getvalue(),
                "headers": {"Content-Type": "application/x-parquet"},
            }
        elif fmt == "json":
            kwargs = {"json": {"X": frame.to_dict(orient="records")}}
        else:
            raise ValueError(f"fmt must be 'parquet' or 'json', got {fmt!r}")

        # same retry contract as the async path (_fetch_chunk): 4xx is
        # permanent, 5xx/connection errors retry with jittered backoff
        # (honoring any Retry-After and the call's retry budget), the
        # endpoint's shared circuit short-circuits a dead server, and
        # every terminal failure surfaces as ClientError
        kwargs.setdefault("headers", {}).update(self._headers())
        breaker = self._breaker()
        self._check_quota_gate(machine)
        started = time.monotonic()
        last_error: Optional[str] = None
        retry_after: Optional[float] = None
        for attempt in range(self.retries + 1):
            if attempt:
                delay = self._retry_delay(attempt, started, retry_after)
                if delay is None:
                    _M_REQUESTS.labels("budget_exhausted").inc()
                    raise self._exhausted_error(
                        f"{machine}: retry budget exhausted ({last_error})",
                        last_error,
                        retry_after,
                    )
                time.sleep(delay)
                self._refresh_deadline_header(kwargs["headers"])
            retry_after = None
            if not breaker.allow():
                _M_REQUESTS.labels("circuit_open").inc()
                spans.event(
                    "circuit_open", base_url=self.base_url, machine=machine
                )
                raise ClientError(
                    f"{machine}: circuit open for {self.base_url} "
                    f"({last_error or 'recent failures'})"
                )
            try:
                with spans.stage(
                    "chunk_fetch", machine=machine, attempt=attempt
                ):
                    response = requests.post(
                        url, timeout=self.timeout, **kwargs
                    )
            except requests.Timeout as exc:
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("timeout").inc()
                continue
            except requests.RequestException as exc:
                breaker.record(False)
                last_error = repr(exc)
                _M_RETRIES.labels("connection").inc()
                continue
            if response.status_code == 429:
                # same quota carve-out as the async path: a healthy
                # server metering THIS principal — success on the
                # breaker, backoff on the tenant
                breaker.record(True)
                hint = self._parse_retry_after(
                    response.headers.get("Retry-After")
                )
                retry_after = self._note_quota(hint)
                last_error = "HTTP 429 (quota)"
                _M_RETRIES.labels("quota").inc()
                continue
            if 400 <= response.status_code < 500:
                breaker.record(True)  # alive — the REQUEST is bad
                _M_REQUESTS.labels("permanent_4xx").inc()
                raise ClientError(
                    f"{machine}: HTTP {response.status_code}: "
                    f"{response.text[:500]}"
                )
            if response.status_code >= 500:
                hint = self._parse_retry_after(
                    response.headers.get("Retry-After")
                )
                if response.status_code == 503 and response.headers.get(
                    DRAINING_HEADER
                ):
                    # same draining carve-out as the async path: retry
                    # promptly, the rolling restart is momentary
                    breaker.record(True)
                    retry_after = 0.0
                    last_error = "HTTP 503 (draining)"
                    _M_RETRIES.labels("draining").inc()
                    continue
                # same live-server carve-outs as the async path: 503+hint
                # and 504 are answers, not deaths
                breaker.record(
                    response.status_code == 504
                    or (response.status_code == 503 and hint is not None)
                )
                retry_after = hint
                last_error = f"HTTP {response.status_code}"
                _M_RETRIES.labels("http_5xx").inc()
                continue
            ctype = wire.content_type_of(response.headers.get("Content-Type"))
            try:
                if ctype == wire.NPZ_CONTENT_TYPE:
                    payload = wire.payload_from_npz(response.content)
                else:
                    payload = response.json()
            except ValueError:  # 2xx with an undecodable body (broken
                # proxy): retryable, and terminal failures stay ClientError
                breaker.record(False)
                last_error = f"2xx response with undecodable body ({ctype})"
                _M_RETRIES.labels("bad_body").inc()
                continue
            breaker.record(True)
            _M_REQUESTS.labels("ok").inc()
            chunk = self._chunk_frame(payload)
            return chunk if chunk is not None else pd.DataFrame()
        outcome = (
            "quota_exhausted"
            if last_error == "HTTP 429 (quota)"
            else "exhausted"
        )
        _M_REQUESTS.labels(outcome).inc()
        raise self._exhausted_error(
            f"{machine}: retries exhausted ({last_error})",
            last_error,
            retry_after,
        )

    def predict(
        self,
        start: Union[str, datetime],
        end: Union[str, datetime],
        machine_names: Optional[Sequence[str]] = None,
    ) -> Dict[str, pd.DataFrame]:
        """Score ``[start, end)`` for every machine; returns
        ``{machine: DataFrame}`` (timestamp-indexed per-tag + total scores)
        and pushes each frame through the configured forwarders."""
        machines = list(machine_names) if machine_names else self.resolve_machines()
        ranges = make_date_ranges(start, end, self.max_interval)
        logger.info(
            "Client.predict: %d machines x %d chunks", len(machines), len(ranges)
        )
        # span context for the fan-out: the chunk coroutines run on the
        # I/O loop's thread, so the caller's trace id / timeline must be
        # captured HERE and handed over explicitly. A caller without a
        # timeline gets one per predict() call (recorded into this
        # process's flight recorder) so client-side chunk_fetch/decode
        # attribution exists even for bare CLI runs.
        ctx = spans.capture()
        own_timeline = own_token = own_trace_token = None
        if ctx.timeline is None and flightrec.RECORDER.enabled:
            trace_id = ctx.trace_id or tracing.new_trace_id()
            if not ctx.trace_id:
                # bind the minted id too, or every chunk would mint its
                # own unrelated one and the recorded timeline's trace id
                # would correlate with nothing server-side
                own_trace_token = tracing.set_trace_id(trace_id)
            own_timeline, own_token = spans.begin(
                trace_id,
                kind="client.predict",
                machines=len(machines),
                chunks=len(ranges),
            )
            ctx = spans.capture()
        try:
            # run on the client's persistent I/O loop (NOT asyncio.run,
            # which would build and tear down a loop — and the pooled
            # session's connections with it — on every call)
            frames = self._submit(
                self._predict_async(machines, ranges, ctx=ctx)
            ).result()
        except BaseException as exc:
            if own_timeline is not None:
                own_timeline.finish(
                    status="error", error=f"{type(exc).__name__}: {exc}"
                )
            raise
        else:
            if own_timeline is not None:
                own_timeline.finish(status="ok")
        finally:
            if own_token is not None:
                spans.end(own_token)
                if own_trace_token is not None:
                    tracing.reset_trace_id(own_trace_token)
                flightrec.RECORDER.record(own_timeline)
        for forwarder in self.forwarders:
            for machine, frame in frames.items():
                forwarder.forward(machine, frame)
        return frames
