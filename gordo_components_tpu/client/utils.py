"""Client-side helpers.

Reference parity: ``gordo_components/client/utils.py`` [UNVERIFIED] —
``make_date_ranges`` splits a prediction range into chunks so bulk
backfills stream as many small requests instead of one giant one.
"""

from __future__ import annotations

from datetime import datetime
from typing import List, Tuple, Union

import pandas as pd


def _parse(value: Union[str, datetime]) -> pd.Timestamp:
    ts = pd.Timestamp(value)
    if ts.tz is None:
        ts = ts.tz_localize("UTC")
    return ts


def make_date_ranges(
    start: Union[str, datetime],
    end: Union[str, datetime],
    max_interval: str = "1D",
) -> List[Tuple[pd.Timestamp, pd.Timestamp]]:
    """Split ``[start, end)`` into consecutive chunks of at most
    ``max_interval`` (pandas offset string)."""
    start_ts, end_ts = _parse(start), _parse(end)
    if end_ts <= start_ts:
        raise ValueError(f"end ({end_ts}) must be after start ({start_ts})")
    delta = pd.Timedelta(max_interval)
    if delta <= pd.Timedelta(0):
        raise ValueError(f"max_interval must be positive, got {max_interval!r}")
    ranges = []
    cursor = start_ts
    while cursor < end_ts:
        nxt = min(cursor + delta, end_ts)
        ranges.append((cursor, nxt))
        cursor = nxt
    return ranges
