"""Prediction forwarders.

Reference parity: ``gordo_components/client/forwarders.py`` [UNVERIFIED] —
``PredictionForwarder`` + ``ForwardPredictionsIntoInflux``. The Influx
forwarder uses the installed ``influxdb`` package when present and
otherwise the in-repo stdlib wire client
(``dataset/data_provider/influx_client.py``), so it works with no
optional dependency; ``CsvForwarder`` provides a file sink for backfills.
"""

from __future__ import annotations

import abc
import logging
import os

import pandas as pd

logger = logging.getLogger(__name__)


class PredictionForwarder(abc.ABC):
    @abc.abstractmethod
    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        """Deliver one machine's score frame to the sink."""


class CsvForwarder(PredictionForwarder):
    """One CSV per machine under ``output_dir`` (append on repeat calls)."""

    def __init__(self, output_dir: str):
        self.output_dir = output_dir

    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        os.makedirs(self.output_dir, exist_ok=True)
        path = os.path.join(self.output_dir, f"{machine}.csv")
        predictions.to_csv(
            path, mode="a", header=not os.path.exists(path), index=True
        )
        logger.info("Forwarded %d rows for %s -> %s", len(predictions), machine, path)


class ForwardPredictionsIntoInflux(PredictionForwarder):
    """Write scores into InfluxDB (measurement per machine), as line
    protocol on the real wire. Client resolution mirrors
    ``InfluxDataProvider``: injected ``client`` > installed ``influxdb``
    package > in-repo stdlib ``MinimalInfluxClient`` (round-trip-tested
    against tests/influx_double.py over real sockets)."""

    def __init__(self, measurement: str = "anomaly", client=None, **influx_config):
        """``client``: a pre-built DataFrame-style client (tests /
        pre-authenticated sessions) — mirrors InfluxDataProvider's
        injection point."""
        self.measurement = measurement
        if client is not None:
            self._client = client
            return
        try:
            import influxdb  # type: ignore

            self._client = influxdb.DataFrameClient(**influx_config)
        except ImportError:
            from ..dataset.data_provider.influx_client import (
                MinimalInfluxClient,
            )

            self._client = MinimalInfluxClient(**influx_config)

    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        self._client.write_points(
            predictions, self.measurement, tags={"machine": machine}
        )
