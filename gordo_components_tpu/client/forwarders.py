"""Prediction forwarders.

Reference parity: ``gordo_components/client/forwarders.py`` [UNVERIFIED] —
``PredictionForwarder`` + ``ForwardPredictionsIntoInflux``. The Influx
forwarder is gated on the optional ``influxdb`` package (absent in this
image); ``CsvForwarder`` provides a dependency-free sink for backfills.
"""

from __future__ import annotations

import abc
import logging
import os

import pandas as pd

logger = logging.getLogger(__name__)


class PredictionForwarder(abc.ABC):
    @abc.abstractmethod
    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        """Deliver one machine's score frame to the sink."""


class CsvForwarder(PredictionForwarder):
    """One CSV per machine under ``output_dir`` (append on repeat calls)."""

    def __init__(self, output_dir: str):
        self.output_dir = output_dir

    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        os.makedirs(self.output_dir, exist_ok=True)
        path = os.path.join(self.output_dir, f"{machine}.csv")
        predictions.to_csv(
            path, mode="a", header=not os.path.exists(path), index=True
        )
        logger.info("Forwarded %d rows for %s -> %s", len(predictions), machine, path)


class ForwardPredictionsIntoInflux(PredictionForwarder):
    """Write scores into InfluxDB (measurement per machine). Requires the
    optional ``influxdb`` client package."""

    def __init__(self, measurement: str = "anomaly", client=None, **influx_config):
        """``client``: a pre-built DataFrame-style client (tests /
        pre-authenticated sessions) — mirrors InfluxDataProvider's
        injection point."""
        self.measurement = measurement
        if client is not None:
            self._client = client
            return
        try:
            import influxdb  # type: ignore
        except ImportError as exc:
            raise RuntimeError(
                "ForwardPredictionsIntoInflux requires the optional "
                "'influxdb' package, which is not installed."
            ) from exc
        self._client = influxdb.DataFrameClient(**influx_config)

    def forward(self, machine: str, predictions: pd.DataFrame) -> None:
        self._client.write_points(
            predictions, self.measurement, tags={"machine": machine}
        )
