"""Phase timing + device profiling.

The reference records only coarse durations in build metadata (SURVEY.md
§6.1: no tracing/profiling integration). Rebuild implication implemented
here: a ``PhaseTimer`` whose records drop straight into build metadata, and
a ``device_trace`` context manager wrapping ``jax.profiler`` so any build
or serving phase can emit a TensorBoard-loadable trace
(``xprof``/perfetto) without code changes at the call sites.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Dict, Iterator, Optional

logger = logging.getLogger(__name__)


class PhaseTimer:
    """Accumulates named phase durations; ``report()`` is JSON-able and is
    merged into build metadata."""

    def __init__(self) -> None:
        self.durations: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        started = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - started
            self.add(name, elapsed)
            logger.debug("phase %s: %.3fs", name, elapsed)

    def add(self, name: str, seconds: float) -> None:
        """Record a duration measured elsewhere (e.g. in a prefetch worker
        thread, where the contextmanager would attribute overlapped time
        to the wrong wall-clock interval)."""
        self.durations[name] = self.durations.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> Dict[str, Any]:
        return {
            name: {"total_s": total, "count": self.counts[name]}
            for name, total in sorted(self.durations.items())
        }

    def publish(self, prefix: str = "gordo_build") -> None:
        """Merge this timer's phase totals into the process-wide metrics
        registry as ``<prefix>_phase_seconds_total{phase}`` (+ a run
        counter), so build-phase accounting survives the build function
        returning and lands in the same ``/metrics`` scrape as serving
        telemetry. Counters (not gauges): repeated builds in one process
        accumulate, mirroring ``add()``'s own accumulation semantics."""
        from ..observability.registry import REGISTRY

        seconds = REGISTRY.counter(
            f"{prefix}_phase_seconds_total",
            "Cumulative wall-clock seconds spent per build phase",
            labels=("phase",),
        )
        runs = REGISTRY.counter(
            f"{prefix}_phase_runs_total",
            "Times each build phase ran",
            labels=("phase",),
        )
        for name, total in self.durations.items():
            seconds.labels(name).inc(total)
            runs.labels(name).inc(self.counts[name])


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Wrap a block in a ``jax.profiler`` trace when ``log_dir`` is set
    (no-op otherwise, so call sites never branch)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("Device trace written to %s", log_dir)
