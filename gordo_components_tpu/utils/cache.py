"""FIFO-bounded program memo shared by the fleet and single-machine paths.

``jax.jit`` keys its trace cache on *function identity*: building a fresh
jit wrapper per call (as a naive ``fit`` does) re-traces and re-compiles
the same program every time. Both training paths therefore memoize their
jitted callables on a value-based config key — the fleet in
:mod:`gordo_components_tpu.parallel.fleet`, the single-machine estimators
in :mod:`gordo_components_tpu.models.models` (VERDICT r2 #5: host-path CV
paid k+1 identical traces per machine without this).
"""

from __future__ import annotations

from typing import Callable, TypeVar

T = TypeVar("T")


def cached(cache: dict, max_size: int, key, build: Callable[[], T]) -> T:
    """FIFO-bounded memo; an unhashable key (exotic config member) just
    builds uncached."""
    try:
        hit = cache.get(key)
    except TypeError:
        return build()
    if hit is not None:
        return hit
    value = build()
    if len(cache) >= max_size:  # FIFO bound — a long-lived process seeing
        # many distinct configs must not pin every compiled artifact forever
        cache.pop(next(iter(cache)))
    cache[key] = value
    return value
