"""Tiny file-per-key registry mapping config hashes → built-model dirs.

Reference parity: ``gordo_components/util/disk_registry.py`` [UNVERIFIED] —
``write_key`` / ``get_value`` / ``delete_key``, one file per key under a
registry dir. This is what makes fleet builds idempotent: an orchestrator
retry finds the key and skips the rebuild (SURVEY.md §6.3).
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

logger = logging.getLogger(__name__)

_KEY_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def _key_path(registry_dir: str, key: str) -> str:
    if not _KEY_RE.match(key):
        raise ValueError(
            f"Registry key {key!r} must match {_KEY_RE.pattern} "
            "(it is used as a filename)"
        )
    return os.path.join(registry_dir, f"{key}.md5")


def write_key(registry_dir: str, key: str, value: str) -> None:
    os.makedirs(registry_dir, exist_ok=True)
    path = _key_path(registry_dir, key)
    # atomic-ish: write sidecar then rename, so readers never see partials
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(value)
    os.replace(tmp, path)
    logger.debug("Registry write %s -> %s", key, value)


def get_value(registry_dir: str, key: str) -> Optional[str]:
    path = _key_path(registry_dir, key)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read()


def delete_key(registry_dir: str, key: str) -> bool:
    path = _key_path(registry_dir, key)
    if os.path.exists(path):
        os.remove(path)
        return True
    return False
