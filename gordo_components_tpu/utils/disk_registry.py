"""Tiny file-per-key registry mapping config hashes → built-model dirs.

Reference parity: ``gordo_components/util/disk_registry.py`` [UNVERIFIED] —
``write_key`` / ``get_value`` / ``delete_key``, one file per key under a
registry dir. This is what makes fleet builds idempotent: an orchestrator
retry finds the key and skips the rebuild (SURVEY.md §6.3).
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

from ..store.atomic import atomic_write_file

logger = logging.getLogger(__name__)

_KEY_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def _key_path(registry_dir: str, key: str) -> str:
    if not _KEY_RE.match(key):
        raise ValueError(
            f"Registry key {key!r} must match {_KEY_RE.pattern} "
            "(it is used as a filename)"
        )
    return os.path.join(registry_dir, f"{key}.md5")


def write_key(registry_dir: str, key: str, value: str) -> None:
    # atomic AND durable (store.atomic.atomic_write_file): a registry
    # entry that evaporates in a power cut would resurrect a completed
    # build as pending on the next orchestrator retry
    os.makedirs(registry_dir, exist_ok=True)
    atomic_write_file(_key_path(registry_dir, key), value)
    logger.debug("Registry write %s -> %s", key, value)


def get_value(registry_dir: str, key: str) -> Optional[str]:
    """The registered model dir for ``key``, or ``None`` — including when
    the entry exists but points at a directory that no longer does (lost
    in a crash, or on storage that came back without it): an orchestrator
    retry must rebuild rather than trust a dangling pointer."""
    path = _key_path(registry_dir, key)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        value = fh.read()
    if not os.path.isdir(value):
        logger.warning(
            "Registry key %s points at missing model dir %r; treating as "
            "unregistered (the next build will re-register it)", key, value,
        )
        return None
    return value


def delete_key(registry_dir: str, key: str) -> bool:
    path = _key_path(registry_dir, key)
    if os.path.exists(path):
        os.remove(path)
        return True
    return False
