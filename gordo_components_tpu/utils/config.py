"""Dotted-path config → class resolution, shared by every ``from_dict``.

The reference resolves ``{"dotted.path.Class": {kwargs}}`` style configs in
its serializer (``gordo_components/serializer/from_definition.py``
[UNVERIFIED]); providers and datasets use a ``type`` key. One resolver
serves both shapes here so the semantics can't drift between subsystems.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, Optional, Type


def resolve_dotted_path(type_path: str) -> Any:
    """Import ``module.attr`` from a dotted path with distinct errors for
    import vs attribute failures."""
    module_path, name = type_path.rsplit(".", 1)
    try:
        module = importlib.import_module(module_path)
    except ImportError as exc:
        raise ValueError(f"Cannot import module {module_path!r}") from exc
    try:
        return getattr(module, name)
    except AttributeError as exc:
        raise ValueError(f"{module_path!r} has no attribute {name!r}") from exc


def resolve_config_class(
    type_path: str,
    base_cls: Type,
    default_module: Optional[str] = None,
) -> Type:
    """Resolve ``type_path`` (dotted path, or a bare name looked up in
    ``default_module``) to a class and verify it subclasses ``base_cls``."""
    if "." in type_path:
        resolved = resolve_dotted_path(type_path)
    elif default_module:
        module = importlib.import_module(default_module)
        try:
            resolved = getattr(module, type_path)
        except AttributeError as exc:
            raise ValueError(
                f"Unknown {base_cls.__name__} short name {type_path!r}"
            ) from exc
    else:
        raise ValueError(f"{type_path!r} is not a dotted path")
    if not (isinstance(resolved, type) and issubclass(resolved, base_cls)):
        raise ValueError(f"{type_path} is not a {base_cls.__name__}")
    return resolved
