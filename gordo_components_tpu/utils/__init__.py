"""Shared utilities: config-class resolution, disk registry, metadata helpers."""

from .config import resolve_config_class

__all__ = ["resolve_config_class"]
