"""Accelerator-backend liveness probing.

JAX backend init can block indefinitely when the accelerator transport is
wedged (observed on tunneled-TPU rigs: ``jax.devices()`` hung >10 min).
Anything that must not inherit that hang — benchmarks, driver entry points
— probes through here: the callable runs on a daemon thread and the caller
gets an answer within ``timeout_s`` either way.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Tuple


FORCED_CPU_ENV = "GORDO_FORCED_CPU"


def require_live_backend(script_name: str, timeout_s: float = 120.0) -> None:
    """Exit fast (code 3, clear stderr message) when JAX backend init hangs
    or fails — the shared guard for driver-run benchmark scripts, which must
    record a failure rather than stall a round on a wedged tunnel."""
    import sys

    import jax

    status, value = call_with_timeout(jax.devices, timeout_s)
    if status == "ok":
        return
    sys.stderr.write(
        f"{script_name}: JAX backend init "
        + (
            f"failed: {value!r}\n"
            if status == "error"
            else f"hung for {timeout_s:.0f}s (accelerator tunnel down?); "
            "aborting instead of hanging\n"
        )
    )
    sys.exit(3)


def pin_cpu_if_forced() -> bool:
    """Call FIRST in a bench ``main()``, before any backend init: when this
    process is the forced-CPU fallback child (:func:`require_live_backend_or_
    cpu_fallback` set :data:`FORCED_CPU_ENV`) or the operator set
    ``BENCH_CPU=1``, pin the platform via ``jax.config`` — the
    ``JAX_PLATFORMS`` env var alone is ignored once an accelerator plugin is
    installed. Returns True when this run is the degraded tunnel-down
    fallback (callers surface that honestly in their JSON output)."""
    import os

    import jax

    forced = os.environ.get(FORCED_CPU_ENV, "0") == "1"
    if forced or os.environ.get("BENCH_CPU", "0") == "1":
        jax.config.update("jax_platforms", "cpu")
    return forced


def require_live_backend_or_cpu_fallback(
    script_name: str, timeout_s: float = 120.0, child_timeout_s: float = 3300.0
) -> None:
    """Like :func:`require_live_backend`, but NEVER fails the round on a
    wedged accelerator tunnel: on a hung/failed probe it re-execs the current
    script in a subprocess pinned to the CPU backend (same argv, env plus
    :data:`FORCED_CPU_ENV`), forwards the child's stdout/stderr, and exits
    with the child's return code. The child's JSON then carries an honest
    ``"device": "cpu"`` — a degraded-but-parseable artifact instead of rc=3
    (VERDICT r2 #1). Returns normally when the backend is live."""
    import os
    import subprocess
    import sys

    import jax

    status, value = call_with_timeout(jax.devices, timeout_s)
    if status == "ok":
        return
    if os.environ.get(FORCED_CPU_ENV, "0") == "1":
        # CPU backend init cannot hang on a tunnel; something else is wrong —
        # fail loudly rather than recurse
        sys.stderr.write(
            f"{script_name}: backend init failed even on the forced-CPU "
            f"fallback: {value!r}\n"
        )
        sys.exit(3)
    sys.stderr.write(
        f"{script_name}: JAX backend init "
        + (
            f"failed ({value!r})"
            if status == "error"
            else f"hung for {timeout_s:.0f}s (accelerator tunnel down?)"
        )
        + "; re-running on the CPU backend so the round still gets an "
        "honest, parseable measurement\n"
    )
    sys.stderr.flush()
    env = dict(os.environ)
    env[FORCED_CPU_ENV] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    try:
        # child inherits stdio: its progress streams live (a CPU bench run
        # can take many minutes) and its JSON line lands on the same stdout
        # the driver parses — no buffering of the whole run in memory
        proc = subprocess.run(
            [sys.executable] + sys.argv, env=env, timeout=child_timeout_s
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(
            f"{script_name}: forced-CPU fallback timed out after "
            f"{child_timeout_s:.0f}s\n"
        )
        sys.exit(3)
    sys.exit(proc.returncode)


def enable_persistent_compile_cache(cache_dir: Optional[str] = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local directory
    (default: ``.jax_compilation_cache/`` next to the package, the same
    layout tests/conftest.py uses) so repeated driver/bench invocations
    reuse compiles instead of re-paying them — on this rig a cold TPU
    compile of a windowed fleet program costs tens of seconds to tens of
    minutes, and the driver's round-end ``bench.py`` run repeats the exact
    programs the operator's runbook just compiled. Safe to call multiple
    times; a no-op if the operator already pinned a cache dir.

    ``GORDO_COMPILE_CACHE`` is the entry-point-wide env knob, with the
    same semantics the CLI flag gives it: a directory pins the cache
    location, ``off`` disables caching entirely (returns "" and clears
    even an env-var-sourced active config, so the cacheless segfault-
    isolation mode holds outside pytest too). An EXPLICIT ``cache_dir``
    argument always beats the env var — a caller that resolved its own
    precedence (click: flag beats envvar) must not be second-guessed."""
    import os

    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("GORDO_COMPILE_CACHE") or None
    if cache_dir == "off":
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        jax.config.update("jax_compilation_cache_dir", None)
        return ""
    if jax.config.jax_compilation_cache_dir:
        return jax.config.jax_compilation_cache_dir
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            ".jax_compilation_cache",
        )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    return cache_dir


def call_with_timeout(
    fn: Callable[[], Any], timeout_s: float = 60.0
) -> Tuple[str, Optional[Any]]:
    """Run ``fn()`` on a daemon thread; returns one of

    - ``("ok", value)`` — completed within the deadline;
    - ``("error", exception)`` — raised within the deadline;
    - ``("timeout", None)`` — still blocked at the deadline (the thread is
      abandoned; it is a daemon, so it cannot keep the process alive).
    """
    result: dict = {}

    def probe():
        try:
            result["value"] = fn()
        except Exception as exc:
            result["error"] = exc

    thread = threading.Thread(target=probe, daemon=True)
    thread.start()
    thread.join(timeout_s)
    if "value" in result:
        return "ok", result["value"]
    if "error" in result:
        return "error", result["error"]
    return "timeout", None
