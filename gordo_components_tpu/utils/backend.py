"""Accelerator-backend liveness probing.

JAX backend init can block indefinitely when the accelerator transport is
wedged (observed on tunneled-TPU rigs: ``jax.devices()`` hung >10 min).
Anything that must not inherit that hang — benchmarks, driver entry points
— probes through here: the callable runs on a daemon thread and the caller
gets an answer within ``timeout_s`` either way.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Tuple


def call_with_timeout(
    fn: Callable[[], Any], timeout_s: float = 60.0
) -> Tuple[str, Optional[Any]]:
    """Run ``fn()`` on a daemon thread; returns one of

    - ``("ok", value)`` — completed within the deadline;
    - ``("error", exception)`` — raised within the deadline;
    - ``("timeout", None)`` — still blocked at the deadline (the thread is
      abandoned; it is a daemon, so it cannot keep the process alive).
    """
    result: dict = {}

    def probe():
        try:
            result["value"] = fn()
        except Exception as exc:
            result["error"] = exc

    thread = threading.Thread(target=probe, daemon=True)
    thread.start()
    thread.join(timeout_s)
    if "value" in result:
        return "ok", result["value"]
    if "error" in result:
        return "error", result["error"]
    return "timeout", None
