"""Accelerator-backend liveness probing.

JAX backend init can block indefinitely when the accelerator transport is
wedged (observed on tunneled-TPU rigs: ``jax.devices()`` hung >10 min).
Anything that must not inherit that hang — benchmarks, driver entry points
— probes through here: the callable runs on a daemon thread and the caller
gets an answer within ``timeout_s`` either way.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Tuple


def require_live_backend(script_name: str, timeout_s: float = 120.0) -> None:
    """Exit fast (code 3, clear stderr message) when JAX backend init hangs
    or fails — the shared guard for driver-run benchmark scripts, which must
    record a failure rather than stall a round on a wedged tunnel."""
    import sys

    import jax

    status, value = call_with_timeout(jax.devices, timeout_s)
    if status == "ok":
        return
    sys.stderr.write(
        f"{script_name}: JAX backend init "
        + (
            f"failed: {value!r}\n"
            if status == "error"
            else f"hung for {timeout_s:.0f}s (accelerator tunnel down?); "
            "aborting instead of hanging\n"
        )
    )
    sys.exit(3)


def call_with_timeout(
    fn: Callable[[], Any], timeout_s: float = 60.0
) -> Tuple[str, Optional[Any]]:
    """Run ``fn()`` on a daemon thread; returns one of

    - ``("ok", value)`` — completed within the deadline;
    - ``("error", exception)`` — raised within the deadline;
    - ``("timeout", None)`` — still blocked at the deadline (the thread is
      abandoned; it is a daemon, so it cannot keep the process alive).
    """
    result: dict = {}

    def probe():
        try:
            result["value"] = fn()
        except Exception as exc:
            result["error"] = exc

    thread = threading.Thread(target=probe, daemon=True)
    thread.start()
    thread.join(timeout_s)
    if "value" in result:
        return "ok", result["value"]
    if "error" in result:
        return "error", result["error"]
    return "timeout", None
