"""Fleet-config normalization.

Reference parity: ``gordo_components/workflow/config_elements/
normalized_config.py`` + ``machine.py`` [UNVERIFIED] — the fleet YAML lists
``machines`` and a ``globals`` section of defaults; ``NormalizedConfig``
merges per-machine config over the globals (machine wins, dict-deep for
dataset/metadata), yielding one fully-specified :class:`Machine` per entry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import yaml


@dataclass
class Machine:
    name: str
    model: Dict[str, Any]
    dataset: Dict[str, Any]
    metadata: Dict[str, Any] = field(default_factory=dict)
    evaluation: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if not self.name:
            raise ValueError("Machine requires a non-empty name")
        if not self.model:
            raise ValueError(f"Machine {self.name!r} has no model config "
                             "(neither per-machine nor in globals)")
        if not self.dataset:
            raise ValueError(f"Machine {self.name!r} has no dataset config")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "model": self.model,
            "dataset": self.dataset,
            "metadata": self.metadata,
            "evaluation": self.evaluation,
        }


def _merged(defaults: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Deep merge: machine wins per KEY, recursively for nested mappings —
    so a machine overriding ``dataset.data_provider.base_dir`` keeps the
    global provider ``type`` (the shape the module docstring promises; a
    shallow update would silently drop sibling keys of any nested
    override). Non-dict values (lists like tag_list included) replace
    wholesale."""
    out = dict(defaults)
    for key, value in (override or {}).items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _merged(out[key], value)
        else:
            out[key] = value
    return out


class NormalizedConfig:
    """``yaml/dict`` fleet config → normalized machines.

    Expected shape::

        project-name: my-project
        machines:
          - name: m1
            dataset: {tag_list: [...], ...}
            model: {...}           # optional if globals.model given
            metadata: {...}
            evaluation: {...}
        globals:
          model: {...}
          dataset: {resolution: 10min, ...}
          evaluation: {n_splits: 3}
    """

    def __init__(self, config: Union[str, Dict[str, Any]]):
        if isinstance(config, str):
            config = yaml.safe_load(config)
        if not isinstance(config, dict):
            raise ValueError(f"Fleet config must be a mapping, got {type(config)}")
        crd_name = None
        # the CRD unwrap requires CRD MARKERS (kind/apiVersion), not just a
        # top-level 'spec' mapping: a plain fleet config that happens to
        # carry a 'spec' key must parse normally instead of being rejected
        # with "no spec.config mapping" (ADVICE r5). A config that declares
        # kind: Gordo (or any apiVersion) and has a spec mapping is
        # unambiguously the wrapper — and a WRONG kind with a spec is
        # rejected loudly rather than misread as a flat config.
        kind = config.get("kind")
        is_crd = isinstance(config.get("spec"), dict) and (
            kind is not None or "apiVersion" in config
        )
        if kind is not None and not is_crd:
            raise ValueError(
                f"Config declares kind: {kind!r} but has no spec mapping; "
                "a CRD-shaped fleet config needs spec.config"
            )
        if is_crd and kind not in (None, "Gordo"):
            raise ValueError(
                f"Unsupported CRD kind {kind!r}; expected 'Gordo'"
            )
        if is_crd:
            # the reference's full CRD wrapper (apiVersion: equinor.com/v1,
            # kind: Gordo): machines/globals live under spec.config and the
            # project name under metadata.name — accepted verbatim so a
            # deployed gordo config ports with zero edits (VERDICT r4 #7)
            metadata = config.get("metadata")
            if metadata is not None and not isinstance(metadata, dict):
                raise ValueError(
                    "CRD-shaped fleet config has a non-mapping metadata "
                    f"({type(metadata).__name__}); expected e.g. "
                    "{name: my-project}"
                )
            crd_name = (metadata or {}).get("name")
            inner = config["spec"].get("config")
            if not isinstance(inner, dict):
                raise ValueError(
                    "CRD-shaped fleet config has no spec.config mapping"
                )
            config = inner
        self.project_name: str = (
            config.get("project-name")
            or config.get("project_name")
            or crd_name
            or "project"
        )
        raw_machines: Optional[List[Dict[str, Any]]] = config.get("machines")
        if not raw_machines:
            raise ValueError("Fleet config has no 'machines' list")
        defaults = config.get("globals", {}) or {}
        default_model = defaults.get("model", {}) or {}
        default_dataset = defaults.get("dataset", {}) or {}
        default_metadata = defaults.get("metadata", {}) or {}
        default_evaluation = defaults.get("evaluation", {}) or {}

        seen: set = set()
        self.machines: List[Machine] = []
        for entry in raw_machines:
            name = entry.get("name")
            if name in seen:
                raise ValueError(f"Duplicate machine name {name!r}")
            seen.add(name)
            self.machines.append(
                Machine(
                    name=name,
                    model=entry.get("model") or default_model,
                    dataset=_merged(default_dataset, entry.get("dataset", {})),
                    metadata=_merged(default_metadata, entry.get("metadata", {})),
                    evaluation=_merged(
                        default_evaluation, entry.get("evaluation", {})
                    ),
                )
            )
