from .config_elements import Machine, NormalizedConfig
from .workflow_generator import generate_argo_workflow, generate_tpu_job

__all__ = [
    "Machine",
    "NormalizedConfig",
    "generate_argo_workflow",
    "generate_tpu_job",
]
