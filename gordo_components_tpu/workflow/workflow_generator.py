"""Workflow emission: fleet config → deployable manifests.

Reference parity: ``gordo_components/workflow/workflow_generator/``
[UNVERIFIED] — Jinja2-expands the normalized machines into an Argo
``Workflow`` (one builder pod per machine, bounded ``parallelism``) plus a
model-server Deployment/Service per machine and a watchman Deployment.
:func:`generate_argo_workflow` keeps that emitter for compatibility with
existing Argo clusters.

:func:`generate_tpu_job` is the TPU-native replacement: because the fleet
engine trains every machine inside one compiled program
(:mod:`gordo_components_tpu.parallel`), the whole fleet needs ONE builder
Job (``gordo-tpu fleet-build``) and ONE multi-model server Deployment —
the pod-per-machine pattern collapses into a 2-resource spec.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import yaml
from jinja2 import Environment, StrictUndefined

from .config_elements import NormalizedConfig

_ENV = Environment(undefined=StrictUndefined, trim_blocks=True, lstrip_blocks=True)

_ARGO_TEMPLATE = _ENV.from_string(
    """\
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata:
  generateName: {{ project }}-
  labels:
    applications.gordo.equinor.com/project-name: {{ project }}
spec:
  entrypoint: build-fleet
  parallelism: {{ parallelism }}
  templates:
    - name: build-fleet
      dag:
        tasks:
{% for machine in machines %}
          - name: build-{{ machine.name }}
            template: model-builder
            arguments:
              parameters:
                - name: machine-name
                  value: "{{ machine.name }}"
                - name: model-config
                  value: {{ machine.model_json }}
                - name: data-config
                  value: {{ machine.data_json }}
{% endfor %}
    - name: model-builder
      inputs:
        parameters:
          - name: machine-name
          - name: model-config
          - name: data-config
      container:
        image: {{ image }}
        command: [python, -m, gordo_components_tpu.cli]
        args: [build, "{{ '{{inputs.parameters.machine-name}}' }}"]
        env:
          - name: MODEL_CONFIG
            value: "{{ '{{inputs.parameters.model-config}}' }}"
          - name: DATA_CONFIG
            value: "{{ '{{inputs.parameters.data-config}}' }}"
          - name: OUTPUT_DIR
            value: {{ output_dir }}/{{ '{{inputs.parameters.machine-name}}' }}
          - name: MODEL_REGISTER_DIR
            value: {{ register_dir }}
"""
)

_SERVER_TEMPLATE = _ENV.from_string(
    """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: gordo-server-{{ machine }}
  labels: {app: gordo-server, machine: {{ machine }}}
spec:
  replicas: 1
  selector:
    matchLabels: {app: gordo-server, machine: {{ machine }}}
  template:
    metadata:
      labels: {app: gordo-server, machine: {{ machine }}}
    spec:
      containers:
        - name: server
          image: {{ image }}
          command: [python, -m, gordo_components_tpu.cli]
          args: [run-server, --model-dir, {{ output_dir }}/{{ machine }},
                 --port, "5555", --project, {{ project }}]
          readinessProbe:
            httpGet: {path: /healthz, port: 5555}
---
apiVersion: v1
kind: Service
metadata:
  name: gordo-server-{{ machine }}
spec:
  selector: {app: gordo-server, machine: {{ machine }}}
  ports: [{port: 5555}]
"""
)

_WATCHMAN_TEMPLATE = _ENV.from_string(
    """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: gordo-watchman
  labels: {app: gordo-watchman, project: {{ project }}}
spec:
  replicas: 1
  selector:
    matchLabels: {app: gordo-watchman}
  template:
    metadata:
      labels: {app: gordo-watchman}
    spec:
      containers:
        - name: watchman
          image: {{ image }}
          command: [python, -m, gordo_components_tpu.cli]
          args: [run-watchman, --project, {{ project }}, --port, "5556",
{% for machine in machines %}
                 --machine, {{ machine }},
{% endfor %}
                 --target-url, http://gordo-server:5555]
"""
)

_TPU_JOB_TEMPLATE = _ENV.from_string(
    """\
{% if hosts > 1 -%}
apiVersion: v1
kind: Service
metadata:
  name: {{ project }}-fleet-coord
  labels: {app: gordo-fleet-builder, project: {{ project }}}
spec:
  # the k8s API's headless marker is the literal STRING "None" (yaml null
  # would mean "unset" and get a ClusterIP allocated, killing the per-pod
  # DNS names the coordinator address depends on)
  clusterIP: "None"
  selector: {app: gordo-fleet-builder, project: {{ project }}}
  ports: [{port: 6000, name: coordinator}]
---
{% endif -%}
apiVersion: batch/v1
kind: Job
metadata:
  name: {{ project }}-fleet-build
  labels: {app: gordo-fleet-builder, project: {{ project }}}
spec:
{% if hosts > 1 %}
  # every wedge/peer-death event costs up to `hosts` pod failures (the
  # victim plus each watchdog-freed survivor), so the budget scales with
  # hosts — and the retryable code 75 is excluded from the count entirely
  # below, or a single event would exhaust a flat limit and permanently
  # fail the Job for exactly the failure mode the watchdog recovers
  backoffLimit: {{ 3 * hosts }}
{% else %}
  backoffLimit: 3
{% endif %}
  # make the exit-code contract real at the Job layer (k8s >= 1.26,
  # requires restartPolicy Never): transient/watchdog exits (75) restart
  # without counting toward backoffLimit; the CLI's permanent config/data/
  # device codes (64/66/70 — 70 is deterministic XLA failure such as HBM
  # OOM) fail the Job immediately instead of burning retries on a build
  # that can never succeed
  podFailurePolicy:
    rules:
      - action: Ignore
        onExitCodes: {containerName: fleet-builder, operator: In, values: [75]}
      - action: FailJob
        onExitCodes: {containerName: fleet-builder, operator: In, values: [64, 66, 70]}
  # global wall-clock bound: because exit 75 is Ignored above, a failure
  # mode that keeps presenting as retryable (e.g. an XLA error the CLI's
  # permanent-marker list doesn't recognise) could otherwise crash-loop on
  # TPU quota forever without ever touching backoffLimit
  activeDeadlineSeconds: {{ active_deadline_s }}
{% if hosts > 1 %}
  # one indexed pod per TPU host: every pod runs the SAME fleet-build
  # command, joins the jax.distributed runtime at pod 0, and trains/writes
  # only its own machine shard (output/registry dirs must be shared
  # storage). Restart semantics match single-host: the per-machine
  # registry resume makes retries idempotent.
  completionMode: Indexed
  completions: {{ hosts }}
  parallelism: {{ hosts }}
{% endif %}
  template:
    metadata:
      labels: {app: gordo-fleet-builder, project: {{ project }}}
    spec:
      restartPolicy: Never
{% if hosts > 1 %}
      subdomain: {{ project }}-fleet-coord
{% endif %}
      containers:
        - name: fleet-builder
          image: {{ image }}
          command: [python, -m, gordo_components_tpu.cli]
          args: [fleet-build, --machine-config, /config/machines.yaml,
                 --output-dir, {{ output_dir }},
                 --model-register-dir, {{ register_dir }}]
{% if hosts > 1 %}
          env:
            - name: GORDO_NUM_PROCESSES
              value: "{{ hosts }}"
            - name: GORDO_PROCESS_ID
              valueFrom:
                fieldRef:
                  fieldPath: "metadata.annotations['batch.kubernetes.io/job-completion-index']"
            - name: GORDO_COORDINATOR
              value: "{{ project }}-fleet-build-0.{{ project }}-fleet-coord:6000"
            # slice liveness watchdog: a pod wedged in a collective (dead
            # peer the transport can't see) exits the retryable code 75
            # after this budget instead of hanging the Job forever; the
            # backoffLimit restart then resumes from registry + slice
            # checkpoints. Size it above the worst healthy slice wall time.
            - name: GORDO_SLICE_TIMEOUT_S
              value: "{{ slice_timeout_s }}"
{% endif %}
          resources:
            limits: {"google.com/tpu": {{ tpu_chips }}}
---
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {{ project }}-model-server
  labels: {app: gordo-server, project: {{ project }}}
spec:
  replicas: 1
  selector:
    matchLabels: {app: gordo-server, project: {{ project }}}
  template:
    metadata:
      labels: {app: gordo-server, project: {{ project }}}
    spec:
      containers:
        - name: server
          image: {{ image }}
          command: [python, -m, gordo_components_tpu.cli]
          args: [run-server, --models-dir, {{ output_dir }},
                 --port, "5555", --project, {{ project }}]
          resources:
            limits: {"google.com/tpu": 1}
          readinessProbe:
            httpGet: {path: /healthz, port: 5555}
"""
)


def generate_argo_workflow(
    config: Union[str, Dict[str, Any], NormalizedConfig],
    image: str = "gordo-components-tpu:latest",
    parallelism: int = 10,
    output_dir: str = "/gordo/models",
    register_dir: str = "/gordo/registry",
) -> str:
    """Reference-compatible emitter: Argo Workflow (builder pod per machine)
    + per-machine server Deployment/Service + watchman."""
    import json

    if not isinstance(config, NormalizedConfig):
        config = NormalizedConfig(config)
    machines = [
        {
            "name": machine.name,
            "model_json": json.dumps(json.dumps(machine.model)),
            "data_json": json.dumps(json.dumps(machine.dataset)),
        }
        for machine in config.machines
    ]
    documents = [
        _ARGO_TEMPLATE.render(
            project=config.project_name,
            machines=machines,
            image=image,
            parallelism=parallelism,
            output_dir=output_dir,
            register_dir=register_dir,
        )
    ]
    for machine in config.machines:
        documents.append(
            _SERVER_TEMPLATE.render(
                machine=machine.name,
                image=image,
                output_dir=output_dir,
                project=config.project_name,
            )
        )
    documents.append(
        _WATCHMAN_TEMPLATE.render(
            project=config.project_name,
            machines=[machine.name for machine in config.machines],
            image=image,
        )
    )
    return "\n---\n".join(documents)


def generate_tpu_job(
    config: Union[str, Dict[str, Any], NormalizedConfig],
    image: str = "gordo-components-tpu:latest",
    output_dir: str = "/gordo/models",
    register_dir: str = "/gordo/registry",
    tpu_chips: int = 16,
    hosts: int = 1,
    slice_timeout_s: int = 1800,
    active_deadline_s: int = 86400,
) -> str:
    """TPU-native emitter: one fleet-build Job + one multi-model server
    Deployment for the entire fleet.

    ``hosts > 1`` emits the multi-host layout: a headless coordinator
    Service plus an Indexed Job (one pod per TPU host) whose pods derive
    ``GORDO_PROCESS_ID`` from their completion index and join the
    jax.distributed runtime at pod 0 — the k8s wiring for
    ``fleet-build --coordinator-address``. Multi-host pods also carry
    ``GORDO_SLICE_TIMEOUT_S`` (``slice_timeout_s``, default 30 min): the
    in-process slice watchdog that turns a wedged collective (a dead peer
    the transport can't see) into the retryable exit 75 the Job's
    backoffLimit can act on, instead of a forever-Running pod no liveness
    probe can tell from slow training."""
    if not isinstance(config, NormalizedConfig):
        config = NormalizedConfig(config)
    if hosts < 1:
        raise ValueError(f"hosts must be >= 1, got {hosts}")
    if active_deadline_s < 1:
        raise ValueError(
            f"active_deadline_s must be >= 1, got {active_deadline_s}: the "
            "deadline is the only bound on retryable (exit 75) crash loops, "
            "which the podFailurePolicy excludes from backoffLimit"
        )
    return _TPU_JOB_TEMPLATE.render(
        project=config.project_name,
        image=image,
        output_dir=output_dir,
        register_dir=register_dir,
        tpu_chips=tpu_chips,
        hosts=hosts,
        slice_timeout_s=slice_timeout_s,
        active_deadline_s=active_deadline_s,
    )


def validate_generated(manifest: str) -> None:
    """Every emitted document must be parseable YAML (golden-test hook)."""
    for document in yaml.safe_load_all(manifest):
        if document is None:
            continue
        if "kind" not in document:
            raise ValueError(f"Document missing 'kind': {document}")
