"""Diff-based anomaly detection.

Reference parity: ``gordo_components/model/anomaly/diff.py`` [UNVERIFIED] —
``DiffBasedAnomalyDetector`` wraps a base pipeline; ``cross_validate`` fits a
per-tag error scaler on out-of-fold absolute residuals ``|y − ŷ|``;
``anomaly(X, y)`` emits per-tag scaled errors (``tag-anomaly-scores``) and
``total-anomaly-score`` = L2 norm across tags, as a DataFrame whose top-level
columns (``model-input``, ``model-output``, ``tag-anomaly-scores``,
``total-anomaly-score``) are the serving payload's field names.

Alignment rule (works for every zoo model): a model emitting ``m`` prediction
rows for ``n`` input rows predicts the LAST ``m`` target rows — dense models
have ``m = n``; LSTM reconstruction ``m = n − L + 1`` (rows ``L−1…n−1``);
forecast ``m = n − L`` (rows ``L…n−1``). Scoring is a pure function of
``(y_aligned, ŷ, scaler_params)`` so the fleet/serving layers jit it batched.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd

from ..metrics import METRICS
from ..pipeline import clone_pipeline
from ..transformers import MinMaxScaler
from .base import AnomalyDetectorBase


def _tail_align(y: np.ndarray, n_pred_rows: int) -> np.ndarray:
    if n_pred_rows > len(y):
        raise ValueError(
            f"Model produced {n_pred_rows} rows for {len(y)} target rows"
        )
    return y[len(y) - n_pred_rows :]


class DiffBasedAnomalyDetector(AnomalyDetectorBase):
    def __init__(
        self,
        base_estimator: Any = None,
        scaler: Any = None,
        require_thresholds: bool = False,
    ):
        if base_estimator is None:
            from ..models import DenseAutoEncoder

            base_estimator = DenseAutoEncoder()
        self.base_estimator = base_estimator
        self.scaler = scaler if scaler is not None else MinMaxScaler()
        self.require_thresholds = require_thresholds
        self.cross_validation_: Dict[str, Any] = {}
        self.tag_thresholds_: Optional[np.ndarray] = None
        self.total_threshold_: Optional[float] = None

    def _reject_joint_horizon(self) -> None:
        """Joint multi-step forecasters emit ``horizon × F`` values per
        window; diff scoring compares one row per timestamp — reject with
        a clear error instead of an obscure broadcast failure downstream
        (the fleet builder and serving engine carry the same gate)."""
        from ..analysis import analyze_model  # lazy: analysis imports diff

        try:
            est = analyze_model(self).estimator
        except ValueError:
            return  # exotic graph the analyzer can't walk — let the host
            # path's own shape errors surface naturally
        if getattr(est, "joint_horizon", False):
            raise ValueError(
                "DiffBasedAnomalyDetector scores one row per timestamp; "
                f"{type(est).__name__} predicts the whole horizon jointly "
                "— use LSTMForecast(horizon=k) (direct k-step) for anomaly "
                "configs"
            )

    # -- estimator API -------------------------------------------------------
    def fit(self, X, y=None, **kwargs) -> "DiffBasedAnomalyDetector":
        self._reject_joint_horizon()
        self.base_estimator.fit(X, y, **kwargs)
        return self

    def predict(self, X) -> np.ndarray:
        return self.base_estimator.predict(X)

    def score(self, X, y=None) -> float:
        return self.base_estimator.score(X, y)

    # -- CV + error-scaler fitting ------------------------------------------
    def cross_validate(
        self, X, y=None, n_splits: int = 3, metrics: Optional[List[str]] = None
    ) -> Dict[str, Any]:
        """Time-ordered k-fold CV (sklearn ``TimeSeriesSplit`` semantics):
        per-split metric scores, then the per-tag error scaler is fitted on
        the pooled out-of-fold residuals — exactly the reference's recipe."""
        from sklearn.model_selection import TimeSeriesSplit

        self._reject_joint_horizon()
        X_arr = np.asarray(getattr(X, "values", X), dtype=np.float32)
        y_arr = X_arr if y is None else np.asarray(
            getattr(y, "values", y), dtype=np.float32
        )
        metrics = metrics or list(METRICS)
        splits = []
        residuals: List[np.ndarray] = []
        for fold, (train_idx, test_idx) in enumerate(
            TimeSeriesSplit(n_splits=n_splits).split(X_arr)
        ):
            started = time.perf_counter()
            model = clone_pipeline(self.base_estimator)
            model.fit(X_arr[train_idx], y_arr[train_idx])
            pred = np.asarray(model.predict(X_arr[test_idx]))
            y_aligned = _tail_align(y_arr[test_idx], len(pred))
            fold_scores = {
                name: METRICS[name](y_aligned, pred) for name in metrics
            }
            splits.append(
                {
                    "fold": fold,
                    "n_train": int(len(train_idx)),
                    "n_test": int(len(test_idx)),
                    "scores": fold_scores,
                    "duration_s": time.perf_counter() - started,
                }
            )
            residuals.append(np.abs(y_aligned - pred))
        pooled = np.concatenate(residuals, axis=0)
        self.scaler.fit(pooled)
        scaled = np.asarray(self.scaler.transform(pooled))
        self.tag_thresholds_ = np.percentile(scaled, 99, axis=0).astype(np.float32)
        self.total_threshold_ = float(
            np.percentile(np.linalg.norm(scaled, axis=1), 99)
        )
        self.cross_validation_ = {
            "n_splits": n_splits,
            "splits": splits,
            "scores": {
                name: float(np.mean([s["scores"][name] for s in splits]))
                for name in metrics
            },
        }
        return self.cross_validation_

    # -- scoring -------------------------------------------------------------
    def anomaly(self, X, y=None) -> pd.DataFrame:
        """Score ``X`` (optionally vs separate targets ``y``); index is taken
        from ``X`` when it is a DataFrame (tail-aligned to prediction rows)."""
        if getattr(self.scaler, "params_", "unset") is None:
            if self.require_thresholds:
                raise ValueError(
                    "Anomaly scaler is not fitted; run cross_validate() first"
                )
        X_vals = np.asarray(getattr(X, "values", X), dtype=np.float32)
        y_input = X if y is None else y
        y_vals = np.asarray(getattr(y_input, "values", y_input), dtype=np.float32)
        pred = np.asarray(self.predict(X_vals))
        y_aligned = _tail_align(y_vals, len(pred))
        error = np.abs(y_aligned - pred)
        if getattr(self.scaler, "params_", "unset") is None:
            # OUR scaler, unfitted (require_thresholds=False): raw errors.
            # Everything else — a fitted scaler, or an external scaler
            # without the params_ attribute — goes through transform, and
            # its errors (width mismatch, sklearn NotFittedError) propagate:
            # swallowing them would silently change the scores' units
            scaled = error
        else:
            scaled = np.asarray(self.scaler.transform(error))
        total = np.linalg.norm(scaled, axis=1)

        in_tags = list(getattr(X, "columns", [])) or [
            f"tag-{i}" for i in range(X_vals.shape[1])
        ]
        out_tags = list(getattr(y_input, "columns", [])) or [
            f"tag-{i}" for i in range(y_aligned.shape[1])
        ]
        index = None
        if hasattr(X, "index"):
            index = X.index[len(X.index) - len(pred) :]
        columns = pd.MultiIndex.from_tuples(
            [("model-input", t) for t in in_tags]
            + [("model-output", t) for t in out_tags]
            + [("tag-anomaly-scores", t) for t in out_tags]
            + [("total-anomaly-score", "")]
        )
        x_aligned = _tail_align(X_vals, len(pred))
        data = np.concatenate(
            [x_aligned, pred, scaled, total[:, None]], axis=1
        )
        frame = pd.DataFrame(data, columns=columns, index=index)
        return frame

    # -- GordoBase -----------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {
            "base_estimator": self.base_estimator,
            "scaler": self.scaler,
            "require_thresholds": self.require_thresholds,
        }

    def get_metadata(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {
            "type": type(self).__name__,
            "base_estimator": (
                self.base_estimator.get_metadata()
                if hasattr(self.base_estimator, "get_metadata")
                else {}
            ),
        }
        if self.cross_validation_:
            meta["cross_validation"] = self.cross_validation_
        if self.tag_thresholds_ is not None:
            meta["tag_thresholds"] = [float(v) for v in self.tag_thresholds_]
            meta["total_threshold"] = self.total_threshold_
        return meta

    def get_state(self) -> Dict[str, Any]:
        state: Dict[str, Any] = {
            "base_estimator": (
                self.base_estimator.get_state()
                if hasattr(self.base_estimator, "get_state")
                else {}
            ),
            "scaler": (
                self.scaler.get_state() if hasattr(self.scaler, "get_state") else {}
            ),
            "cross_validation": self.cross_validation_,
        }
        if self.tag_thresholds_ is not None:
            state["tag_thresholds"] = np.asarray(self.tag_thresholds_)
            state["total_threshold"] = self.total_threshold_
        return state

    def set_state(self, state: Dict[str, Any]) -> "DiffBasedAnomalyDetector":
        if hasattr(self.base_estimator, "set_state"):
            self.base_estimator.set_state(state.get("base_estimator", {}))
        if hasattr(self.scaler, "set_state"):
            self.scaler.set_state(state.get("scaler", {}))
        self.cross_validation_ = state.get("cross_validation", {})
        if "tag_thresholds" in state:
            self.tag_thresholds_ = np.asarray(state["tag_thresholds"])
            self.total_threshold_ = state.get("total_threshold")
        return self
