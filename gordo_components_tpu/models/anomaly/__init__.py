from .base import AnomalyDetectorBase
from .diff import DiffBasedAnomalyDetector

__all__ = ["AnomalyDetectorBase", "DiffBasedAnomalyDetector"]
