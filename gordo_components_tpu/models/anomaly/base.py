"""Anomaly-detector contract.

Reference parity: ``gordo_components/model/anomaly/base.py`` [UNVERIFIED] —
an anomaly detector is an estimator whose ``anomaly(X, y)`` returns a
DataFrame of scores aligned to the input timestamps.
"""

from __future__ import annotations

import abc

import pandas as pd

from ..base import GordoBase


class AnomalyDetectorBase(GordoBase):
    @abc.abstractmethod
    def anomaly(self, X, y=None) -> pd.DataFrame:
        """Per-row anomaly frame: model input/output, per-tag scaled errors,
        and the total anomaly score."""
