"""Composable pipeline: the sklearn ``Pipeline`` surface the reference's
configs are written against (``sklearn.pipeline.Pipeline`` steps with a final
estimator — the serializer aliases that dotted path here).

Unlike sklearn's, every step is expected to expose the pure-state contract
(:meth:`GordoBase.get_state`) so a whole fitted pipeline serializes to
numpy + JSON — and so the fleet engine can lift all steps of all machines
into stacked arrays. Steps that only implement fit/transform still work for
single-machine use.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .base import GordoBase


def _name_steps(
    steps: Sequence[Union[Tuple[str, Any], Any]]
) -> List[Tuple[str, Any]]:
    named: List[Tuple[str, Any]] = []
    seen: Dict[str, int] = {}
    for step in steps:
        if isinstance(step, (tuple, list)) and len(step) == 2 and isinstance(step[0], str):
            name, obj = step
        else:
            obj = step
            base = f"step_{len(named)}_{type(obj).__name__.lower()}"
            name = base
        if name in seen:
            raise ValueError(f"Duplicate step name {name!r}")
        seen[name] = 1
        named.append((name, obj))
    return named


class Pipeline(GordoBase):
    def __init__(self, steps: Sequence[Union[Tuple[str, Any], Any]]):
        self.steps = _name_steps(steps)

    # -- helpers ------------------------------------------------------------
    @property
    def _final(self) -> Any:
        return self.steps[-1][1]

    def _transform_through(self, X, fit: bool = False, y=None):
        for _, step in self.steps[:-1]:
            if not fit:
                X = step.transform(X)
            elif hasattr(step, "fit_transform"):
                X = step.fit_transform(X, y)
            else:
                step.fit(X, y)
                X = step.transform(X)
        return X

    # -- sklearn API --------------------------------------------------------
    def fit(self, X, y=None, **kwargs) -> "Pipeline":
        Xt = self._transform_through(X, fit=True, y=y)
        self._final.fit(Xt, y, **kwargs)
        return self

    def transform(self, X):
        Xt = self._transform_through(X)
        return self._final.transform(Xt)

    def predict(self, X) -> np.ndarray:
        return self._final.predict(self._transform_through(X))

    def score(self, X, y=None) -> float:
        return self._final.score(self._transform_through(X), y)

    def __getitem__(self, key):
        if isinstance(key, slice):
            return Pipeline(self.steps[key])
        if isinstance(key, str):
            return dict(self.steps)[key]
        return self.steps[key][1]

    # -- GordoBase ----------------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {"steps": list(self.steps)}

    def get_metadata(self) -> Dict[str, Any]:
        return {
            "type": "Pipeline",
            "steps": [
                {name: step.get_metadata() if hasattr(step, "get_metadata") else {}}
                for name, step in self.steps
            ],
        }

    def get_state(self) -> Dict[str, Any]:
        # keyed by position, not name: state must load into any equivalent
        # pipeline regardless of how its steps are named
        return {
            f"step_{i}": step.get_state() if hasattr(step, "get_state") else {}
            for i, (_, step) in enumerate(self.steps)
        }

    def set_state(self, state: Dict[str, Any]) -> "Pipeline":
        for i, (_, step) in enumerate(self.steps):
            if hasattr(step, "set_state"):
                step.set_state(state.get(f"step_{i}", {}))
        return self


class FeatureUnion(GordoBase):
    """Concatenate transformer outputs along the feature axis
    (``sklearn.pipeline.FeatureUnion`` surface — reference configs nest it
    inside Pipelines [SURVEY.md §3 serializer row]). ``transformer_list``
    accepts ``[(name, transformer), …]`` or bare transformers;
    ``transformer_weights`` scales each block by name."""

    def __init__(
        self,
        transformer_list: Sequence[Union[Tuple[str, Any], Any]],
        transformer_weights: Optional[Dict[str, float]] = None,
    ):
        self.transformer_list = _name_steps(transformer_list)
        self.transformer_weights = transformer_weights
        if transformer_weights:
            names = {name for name, _ in self.transformer_list}
            unknown = set(transformer_weights) - names
            if unknown:
                # sklearn raises too — a weight that matches no transformer
                # would otherwise be silently ignored
                raise ValueError(
                    f"transformer_weights keys {sorted(unknown)} match no "
                    f"transformer; names are {sorted(names)}"
                )

    def _weight(self, name: str) -> float:
        if not self.transformer_weights:
            return 1.0
        return float(self.transformer_weights.get(name, 1.0))

    def _assemble(self, name: str, block: Any) -> np.ndarray:
        block = np.asarray(block, dtype=np.float32)
        if block.ndim == 1:
            block = block[:, None]
        return block * self._weight(name)

    def fit(self, X, y=None, **_kwargs) -> "FeatureUnion":
        for _, transformer in self.transformer_list:
            transformer.fit(X, y)
        return self

    def transform(self, X) -> np.ndarray:
        return np.concatenate(
            [
                self._assemble(name, transformer.transform(X))
                for name, transformer in self.transformer_list
            ],
            axis=1,
        )

    def fit_transform(self, X, y=None) -> np.ndarray:
        blocks = []
        for name, transformer in self.transformer_list:
            if hasattr(transformer, "fit_transform"):
                block = transformer.fit_transform(X, y)
            else:
                block = transformer.fit(X, y).transform(X)
            blocks.append(self._assemble(name, block))
        return np.concatenate(blocks, axis=1)

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {
            "transformer_list": list(self.transformer_list),
            "transformer_weights": self.transformer_weights,
        }

    def get_metadata(self) -> Dict[str, Any]:
        return {
            "type": "FeatureUnion",
            "transformers": [
                {name: step.get_metadata() if hasattr(step, "get_metadata") else {}}
                for name, step in self.transformer_list
            ],
        }

    def get_state(self) -> Dict[str, Any]:
        return {
            f"transformer_{i}": (
                step.get_state() if hasattr(step, "get_state") else {}
            )
            for i, (_, step) in enumerate(self.transformer_list)
        }

    def set_state(self, state: Dict[str, Any]) -> "FeatureUnion":
        for i, (_, step) in enumerate(self.transformer_list):
            if hasattr(step, "set_state"):
                step.set_state(state.get(f"transformer_{i}", {}))
        return self


class TransformedTargetRegressor(GordoBase):
    """Fit ``regressor`` on ``transformer``-transformed targets; ``predict``
    inverse-transforms back (sklearn.compose.TransformedTargetRegressor
    surface — the reference's configs wrap models in it [VERSION?])."""

    def __init__(self, regressor: Any, transformer: Optional[Any] = None):
        self.regressor = regressor
        self.transformer = transformer

    def fit(self, X, y=None, **kwargs) -> "TransformedTargetRegressor":
        y_arr = X if y is None else y
        if self.transformer is not None:
            y_arr = self.transformer.fit_transform(y_arr)
        self.regressor.fit(X, y_arr, **kwargs)
        return self

    def predict(self, X) -> np.ndarray:
        pred = self.regressor.predict(X)
        if self.transformer is not None:
            pred = self.transformer.inverse_transform(pred)
        return np.asarray(pred)

    def score(self, X, y=None) -> float:
        from .metrics import explained_variance_score

        y_input = X if y is None else y
        y_arr = np.asarray(getattr(y_input, "values", y_input))
        pred = self.predict(X)
        # windowed regressors (LSTM/PatchTST) emit n−L+1−lookahead rows;
        # score against tail-aligned targets, same contract as
        # BaseFlaxEstimator.score
        y_arr = y_arr[len(y_arr) - len(pred) :]
        return explained_variance_score(y_arr, pred)

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {"regressor": self.regressor, "transformer": self.transformer}

    def get_metadata(self) -> Dict[str, Any]:
        return {
            "type": "TransformedTargetRegressor",
            "regressor": (
                self.regressor.get_metadata()
                if hasattr(self.regressor, "get_metadata")
                else {}
            ),
        }

    def get_state(self) -> Dict[str, Any]:
        return {
            "regressor": (
                self.regressor.get_state() if hasattr(self.regressor, "get_state") else {}
            ),
            "transformer": (
                self.transformer.get_state()
                if hasattr(self.transformer, "get_state")
                else {}
            ),
        }

    def set_state(self, state: Dict[str, Any]) -> "TransformedTargetRegressor":
        if hasattr(self.regressor, "set_state"):
            self.regressor.set_state(state.get("regressor", {}))
        if self.transformer is not None and hasattr(self.transformer, "set_state"):
            self.transformer.set_state(state.get("transformer", {}))
        return self


def clone_pipeline(obj):
    """Deep unfitted clone of a pipeline/estimator graph."""
    if isinstance(obj, Pipeline):
        return Pipeline([(name, clone_pipeline(step)) for name, step in obj.steps])
    if isinstance(obj, FeatureUnion):
        return FeatureUnion(
            [(name, clone_pipeline(step)) for name, step in obj.transformer_list],
            transformer_weights=obj.transformer_weights,
        )
    if isinstance(obj, TransformedTargetRegressor):
        return TransformedTargetRegressor(
            regressor=clone_pipeline(obj.regressor),
            transformer=(
                clone_pipeline(obj.transformer) if obj.transformer is not None else None
            ),
        )
    if isinstance(obj, GordoBase):
        params = obj.get_params(deep=False)
        # nested estimators (anomaly wrappers) must be deep-cloned too, or
        # CV folds would share fitted state
        params = {
            k: clone_pipeline(v) if isinstance(v, (GordoBase, Pipeline)) else v
            for k, v in params.items()
        }
        return type(obj)(**params)
    import copy

    return copy.deepcopy(obj)
