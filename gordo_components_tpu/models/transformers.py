"""Pipeline-step transformers.

Reference parity: the reference drops sklearn preprocessing steps
(``MinMaxScaler``, ``StandardScaler``, ``FunctionTransformer``) and its own
helpers (``InfImputer`` [VERSION?], ``transformer_funcs.general.multiply`` —
``gordo_components/model/transformer_funcs/general.py`` [UNVERIFIED]) into
sklearn Pipelines. These re-implementations keep sklearn's fit/transform API
but hold their fitted state as :class:`~gordo_components_tpu.ops.scaling.ScalerParams`
pytrees, so the fleet engine can stack every machine's scaler into one array
and apply it inside the compiled train/score programs. The serializer aliases
the sklearn dotted paths here, so ported configs get these automatically.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np

from ..ops import scaling
from .base import GordoBase


class _BaseScaler(GordoBase):
    """Shared fit/transform plumbing over :mod:`ops.scaling` pure functions."""

    def __init__(self):
        self.params_: Optional[scaling.ScalerParams] = None

    def _fit_params(self, X: np.ndarray) -> scaling.ScalerParams:
        raise NotImplementedError

    def fit(self, X, y=None, **_kwargs):
        X = np.asarray(getattr(X, "values", X), dtype=np.float32)
        self.params_ = self._fit_params(X)
        return self

    def _check_width(self, X: np.ndarray) -> None:
        """sklearn parity: transform validates the feature count against the
        fit-time width. Without this, a 1-wide input silently BROADCASTS
        against the fitted (F,) params — a served model would return
        plausible-looking scores for a malformed payload (found by driving
        ``POST /anomaly/prediction`` with a 1-feature row)."""
        expected = len(np.atleast_1d(self.params_.scale))
        if X.ndim >= 1 and X.shape[-1] != expected:
            raise ValueError(
                f"{type(self).__name__} was fitted with {expected} features "
                f"but got {X.shape[-1]}"
            )

    def transform(self, X) -> np.ndarray:
        if self.params_ is None:
            raise ValueError(f"{type(self).__name__} is not fitted")
        X = np.asarray(getattr(X, "values", X), dtype=np.float32)
        self._check_width(X)
        return np.asarray(scaling.transform(self.params_, X))

    def fit_transform(self, X, y=None) -> np.ndarray:
        return self.fit(X).transform(X)

    def inverse_transform(self, X) -> np.ndarray:
        if self.params_ is None:
            raise ValueError(f"{type(self).__name__} is not fitted")
        X = np.asarray(getattr(X, "values", X), dtype=np.float32)
        self._check_width(X)
        return np.asarray(scaling.inverse_transform(self.params_, X))

    def get_metadata(self) -> Dict[str, Any]:
        return {"type": type(self).__name__, **self.get_params()}

    def get_state(self) -> Dict[str, Any]:
        if self.params_ is None:
            return {}
        return {
            "scale": np.asarray(self.params_.scale),
            "offset": np.asarray(self.params_.offset),
        }

    def set_state(self, state: Dict[str, Any]):
        if state:
            self.params_ = scaling.ScalerParams(
                scale=np.asarray(state["scale"]), offset=np.asarray(state["offset"])
            )
        return self


class MinMaxScaler(_BaseScaler):
    """Per-feature min-max to ``feature_range`` (sklearn semantics)."""

    def __init__(self, feature_range: Tuple[float, float] = (0.0, 1.0)):
        super().__init__()
        self.feature_range = tuple(feature_range)

    def _fit_params(self, X):
        return scaling.fit_minmax(X, feature_range=self.feature_range)

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {"feature_range": list(self.feature_range)}


class StandardScaler(_BaseScaler):
    """Per-feature standardization (sklearn semantics)."""

    def __init__(self, with_mean: bool = True, with_std: bool = True):
        super().__init__()
        self.with_mean = with_mean
        self.with_std = with_std

    def _fit_params(self, X):
        params = scaling.fit_standard(X)
        scale = params.scale if self.with_std else np.ones_like(params.scale)
        mean = (
            -np.asarray(params.offset) / np.asarray(params.scale)
            if self.with_mean
            else np.zeros_like(params.offset)
        )
        return scaling.ScalerParams(scale=scale, offset=-mean * scale)

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {"with_mean": self.with_mean, "with_std": self.with_std}


class InfImputer(GordoBase):
    """Replace ±inf with the per-feature finite extremes seen at fit time
    (reference: ``InfImputer`` [VERSION?]); optionally an explicit fill."""

    def __init__(
        self,
        inf_fill_value: Optional[float] = None,
        neg_inf_fill_value: Optional[float] = None,
    ):
        self.inf_fill_value = inf_fill_value
        self.neg_inf_fill_value = neg_inf_fill_value
        self.pos_fill_: Optional[np.ndarray] = None
        self.neg_fill_: Optional[np.ndarray] = None

    def fit(self, X, y=None, **_kwargs):
        X = np.asarray(getattr(X, "values", X), dtype=np.float32)
        finite = np.where(np.isfinite(X), X, np.nan)
        with np.errstate(all="ignore"):
            self.pos_fill_ = np.nan_to_num(np.nanmax(finite, axis=0), nan=0.0)
            self.neg_fill_ = np.nan_to_num(np.nanmin(finite, axis=0), nan=0.0)
        if self.inf_fill_value is not None:
            self.pos_fill_ = np.full(X.shape[1], self.inf_fill_value, np.float32)
        if self.neg_inf_fill_value is not None:
            self.neg_fill_ = np.full(X.shape[1], self.neg_inf_fill_value, np.float32)
        return self

    def transform(self, X) -> np.ndarray:
        if self.pos_fill_ is None:
            raise ValueError("InfImputer is not fitted")
        X = np.array(getattr(X, "values", X), dtype=np.float32)
        pos = np.isposinf(X)
        neg = np.isneginf(X)
        X[pos] = np.broadcast_to(self.pos_fill_, X.shape)[pos]
        X[neg] = np.broadcast_to(self.neg_fill_, X.shape)[neg]
        return X

    def fit_transform(self, X, y=None) -> np.ndarray:
        return self.fit(X).transform(X)

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {
            "inf_fill_value": self.inf_fill_value,
            "neg_inf_fill_value": self.neg_inf_fill_value,
        }

    def get_metadata(self) -> Dict[str, Any]:
        return {"type": type(self).__name__, **self.get_params()}

    def get_state(self) -> Dict[str, Any]:
        if self.pos_fill_ is None:
            return {}
        return {"pos_fill": self.pos_fill_, "neg_fill": self.neg_fill_}

    def set_state(self, state: Dict[str, Any]):
        if state:
            self.pos_fill_ = np.asarray(state["pos_fill"])
            self.neg_fill_ = np.asarray(state["neg_fill"])
        return self


def multiply(X, factor: float = 1.0):
    """Reference parity: ``transformer_funcs.general.multiply`` — the demo
    function gordo configs pass to FunctionTransformer."""
    return np.asarray(getattr(X, "values", X)) * factor


class FunctionTransformer(GordoBase):
    """Apply a stateless function (dotted path or callable) as a pipeline
    step — sklearn's FunctionTransformer surface, minus validation knobs."""

    def __init__(
        self,
        func: Union[str, Callable, None] = None,
        inverse_func: Union[str, Callable, None] = None,
        kw_args: Optional[Dict[str, Any]] = None,
        inv_kw_args: Optional[Dict[str, Any]] = None,
    ):
        self.func = func
        self.inverse_func = inverse_func
        self.kw_args = kw_args
        self.inv_kw_args = inv_kw_args

    def _resolve(self, func):
        if func is None:
            return lambda X: X
        if isinstance(func, str):
            # alias-aware so reference paths like
            # gordo_components.model.transformer_funcs.general.multiply work.
            # _allow_external_funcs is cleared by the serializer's
            # artifact-load path: a func string from an untrusted
            # definition.json may only name this package's functions
            from ..serializer.from_definition import resolve_class_path

            return resolve_class_path(
                func,
                allow_external=getattr(self, "_allow_external_funcs", True),
            )
        return func

    def fit(self, X, y=None, **_kwargs):
        return self

    def transform(self, X) -> np.ndarray:
        return self._resolve(self.func)(X, **(self.kw_args or {}))

    def fit_transform(self, X, y=None) -> np.ndarray:
        return self.transform(X)

    def inverse_transform(self, X) -> np.ndarray:
        return self._resolve(self.inverse_func)(X, **(self.inv_kw_args or {}))

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {
            "func": self.func if isinstance(self.func, str) else None,
            "inverse_func": (
                self.inverse_func if isinstance(self.inverse_func, str) else None
            ),
            "kw_args": self.kw_args,
            "inv_kw_args": self.inv_kw_args,
        }

    def get_metadata(self) -> Dict[str, Any]:
        return {"type": type(self).__name__, **self.get_params()}
