"""Pure, jittable training loop.

The reference trains with ``keras.Model.fit`` epochs
(``gordo_components/model/models.py`` [UNVERIFIED]). Here the whole fit —
per-epoch shuffling, mini-batch SGD, loss history — is one compiled XLA
program: ``lax.scan`` over epochs, ``lax.scan`` over mini-batches inside,
no host round-trips. Design constraints that matter downstream:

- **Static shapes**: inputs are padded to a whole number of batches with a
  per-row weight vector (pad rows get weight 0), so one compilation covers
  the dataset and the loss is exact.
- **Purity**: ``make_fit_fn`` closes over only the module's apply fn and the
  optax transform; the returned function is (params, X, y, w, key) →
  (params, history). That makes it directly ``vmap``-able over a stacked
  machine axis — the fleet engine reuses this exact function.
- **RNG**: one fold-able key drives shuffling and dropout; per-machine keys
  under vmap give each machine an independent stream.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

_LOSSES = {
    "mse": lambda diff: diff * diff,
    "mean_squared_error": lambda diff: diff * diff,
    "mae": lambda diff: jnp.abs(diff),
    "mean_absolute_error": lambda diff: jnp.abs(diff),
    "huber": lambda diff: optax.huber_loss(diff, jnp.zeros_like(diff)),
}


def make_loss_fn(apply_fn: Callable, loss: str = "mse") -> Callable:
    """Weighted per-sample loss: (params, x, y, w, key) → scalar.

    ``w`` masks padding rows; the mean is over real rows only.
    """
    if loss not in _LOSSES:
        raise ValueError(f"Unknown loss {loss!r}; supported: {sorted(_LOSSES)}")
    elementwise = _LOSSES[loss]

    def loss_fn(params, x, y, w, dropout_key):
        pred = apply_fn(
            {"params": params},
            x,
            deterministic=dropout_key is None,
            rngs=None if dropout_key is None else {"dropout": dropout_key},
        )
        per_sample = jnp.mean(elementwise(pred - y), axis=-1)
        wsum = jnp.maximum(jnp.sum(w), 1.0)
        return jnp.sum(per_sample * w) / wsum

    return loss_fn


class FitResult(NamedTuple):
    params: Any
    loss_history: jnp.ndarray  # (epochs,) weighted mean loss per epoch


def make_batch_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    use_dropout: bool = False,
) -> Callable:
    """One mini-batch SGD step: ``((params, opt_state), (x, y, w, key)) →
    ((params, opt_state), (loss, wsum))`` — the scanned body of
    :func:`make_fit_fn`, exposed so FLOP accounting can compile exactly the
    step the training loop runs (XLA's ``cost_analysis`` counts a scan body
    ONCE regardless of trip count, so whole-program flops undercount
    training loops; see ``parallel.fleet.fleet_flops_accounting``)."""
    loss_fn = make_loss_fn(apply_fn, loss)
    grad_fn = jax.value_and_grad(loss_fn)

    def batch_step(carry, batch):
        params, opt_state = carry
        xi, yi, wi, ki = batch
        batch_loss, grads = grad_fn(
            params, xi, yi, wi, ki if use_dropout else None
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), (batch_loss, jnp.sum(wi))

    return batch_step


def make_fit_fn(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    batch_size: int = 32,
    epochs: int = 1,
    shuffle: bool = True,
    use_dropout: bool = False,
    unroll: int = 1,
) -> Callable:
    """Build the compiled training program.

    Returns ``fit(params, X, y, w, key) -> FitResult`` where ``X.shape[0]``
    must be a multiple of ``batch_size`` (see :func:`pad_to_batches`).

    ``unroll`` inlines that many mini-batch steps per loop iteration of the
    inner scan (``lax.scan``'s own knob): tiny fleet models are dominated
    by per-iteration dispatch overhead on TPU, and unrolling lets XLA
    schedule several steps per dispatch. Pure scheduling — the step
    sequence and numerics are unchanged; compile time grows with the
    unrolled body, so memory-/compile-constrained callers keep 1.
    """
    batch_step = make_batch_step(
        apply_fn, optimizer, loss=loss, use_dropout=use_dropout
    )

    def fit(params, X, y, w, key) -> FitResult:
        n = X.shape[0]
        steps = n // batch_size
        opt_state = optimizer.init(params)

        def epoch_step(carry, epoch_key):
            params, opt_state = carry
            perm_key, drop_key = jax.random.split(epoch_key)
            if shuffle:
                perm = jax.random.permutation(perm_key, n)
            else:
                perm = jnp.arange(n)
            Xb = X[perm].reshape(steps, batch_size, *X.shape[1:])
            yb = y[perm].reshape(steps, batch_size, *y.shape[1:])
            wb = w[perm].reshape(steps, batch_size)
            drop_keys = jax.random.split(drop_key, steps)

            (params, opt_state), (batch_losses, batch_wsums) = jax.lax.scan(
                batch_step,
                (params, opt_state),
                (Xb, yb, wb, drop_keys),
                unroll=min(unroll, steps) if steps else 1,
            )
            epoch_loss = jnp.sum(batch_losses * batch_wsums) / jnp.maximum(
                jnp.sum(batch_wsums), 1.0
            )
            return (params, opt_state), epoch_loss

        epoch_keys = jax.random.split(key, epochs)
        (params, _), history = jax.lax.scan(
            epoch_step, (params, opt_state), epoch_keys
        )
        return FitResult(params=params, loss_history=history)

    return fit


def pad_to_batches(
    X: np.ndarray, y: np.ndarray, batch_size: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad ``(X, y)`` with zero rows to a multiple of ``batch_size``; returns
    ``(Xp, yp, w)`` where ``w`` is 1.0 on real rows, 0.0 on padding."""
    n = X.shape[0]
    if n == 0:
        raise ValueError("Cannot fit on an empty dataset")
    steps = max(1, -(-n // batch_size))
    padded = steps * batch_size
    pad = padded - n
    w = np.ones(padded, dtype=np.float32)
    if pad:
        X = np.concatenate([X, np.zeros((pad, *X.shape[1:]), X.dtype)])
        y = np.concatenate([y, np.zeros((pad, *y.shape[1:]), y.dtype)])
        w[n:] = 0.0
    return X, y, w


def make_predict_fn(apply_fn: Callable) -> Callable:
    """Deterministic forward pass: (params, X) → predictions."""

    def predict(params, X):
        return apply_fn({"params": params}, X, deterministic=True)

    return predict
