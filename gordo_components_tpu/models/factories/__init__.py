from .spec import ModelSpec, make_optimizer
from . import feedforward, lstm, transformer  # noqa: F401 — registration side effects

__all__ = ["ModelSpec", "make_optimizer", "feedforward", "lstm", "transformer"]
