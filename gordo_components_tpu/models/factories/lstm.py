"""LSTM autoencoder / forecast factories.

Reference parity: ``gordo_components/model/factories/lstm_autoencoder.py``
[UNVERIFIED] — ``lstm_model`` (explicit dims), ``lstm_symmetric``,
``lstm_hourglass`` (same dims math as the feedforward twins). One graph
serves both ``LSTMAutoEncoder`` and ``LSTMForecast``; the estimator picks
the target contract (reconstruction vs one-step forecast).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from ..modules import LSTMModule
from ..register import register_model_factory
from .feedforward import _broadcast_funcs, _reject_unknown, hourglass_calc_dims
from .spec import ModelSpec, make_optimizer


def _build(
    n_features: int,
    n_features_out: Optional[int],
    lookback_window: int,
    units: Sequence[int],
    funcs,
    dropout: float,
    out_func: str,
    optimizer: str,
    optimizer_kwargs: Optional[Dict[str, Any]],
    loss: str,
    compute_dtype: str,
) -> ModelSpec:
    if lookback_window < 1:
        raise ValueError(f"lookback_window must be >= 1, got {lookback_window}")
    n_features_out = n_features_out or n_features
    resolved_funcs = _broadcast_funcs(funcs, units, "tanh")
    module = LSTMModule(
        units=tuple(units),
        n_features_out=n_features_out,
        funcs=resolved_funcs,
        dropout=dropout,
        out_func=out_func,
        compute_dtype=compute_dtype,
    )
    config = {
        "n_features": n_features,
        "n_features_out": n_features_out,
        "lookback_window": lookback_window,
        "units": list(units),
        "funcs": list(resolved_funcs),
        "dropout": dropout,
        "out_func": out_func,
        "optimizer": optimizer,
        "optimizer_kwargs": dict(optimizer_kwargs or {}),
        "loss": loss,
        "compute_dtype": compute_dtype,
    }
    return ModelSpec(
        module=module,
        optimizer=make_optimizer(optimizer, optimizer_kwargs),
        loss=loss,
        input_kind="window",
        config=config,
    )


@register_model_factory("lstm_model")
def lstm_model(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    units: Sequence[int] = (128, 64, 64, 128),
    funcs=None,
    dropout: float = 0.0,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Explicit per-layer LSTM units — the reference's base LSTM factory."""
    _reject_unknown("lstm_model", unknown)
    return _build(
        n_features,
        n_features_out,
        lookback_window,
        units,
        funcs,
        dropout,
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )


@register_model_factory("lstm_symmetric")
def lstm_symmetric(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    dims: Sequence[int] = (128, 64),
    funcs=None,
    dropout: float = 0.0,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Encoder ``dims`` then mirrored decoder dims."""
    _reject_unknown("lstm_symmetric", unknown)
    if not dims:
        raise ValueError("dims must contain at least one layer size")
    encoding_funcs = _broadcast_funcs(funcs, dims, "tanh")
    return _build(
        n_features,
        n_features_out,
        lookback_window,
        tuple(dims) + tuple(reversed(dims)),
        encoding_funcs + tuple(reversed(encoding_funcs)),
        dropout,
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )


@register_model_factory("lstm_hourglass")
def lstm_hourglass(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 1,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    dropout: float = 0.0,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Hourglass dims (same ``hourglass_calc_dims`` contract as feedforward)
    mirrored into a symmetric LSTM stack."""
    _reject_unknown("lstm_hourglass", unknown)
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return _build(
        n_features,
        n_features_out,
        lookback_window,
        dims + tuple(reversed(dims)),
        func,
        dropout,
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )
