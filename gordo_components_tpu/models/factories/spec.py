"""What a model factory produces.

The reference's factories return *compiled Keras models* (architecture +
optimizer + loss bundled by ``keras.Model.compile`` — see
``gordo_components/model/factories/`` [UNVERIFIED]). The JAX equivalent of
"compiled model" is this spec: a Flax module (pure apply), an optax
gradient transformation, and the loss name — everything the train step
needs, nothing stateful.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import flax.linen as nn
import optax

_OPTIMIZERS = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "rmsprop": optax.rmsprop,
    "adagrad": optax.adagrad,
    "adamax": optax.adamax,
    "nadam": optax.nadam,
}


# Keras kwarg spellings → optax spellings (per-optimizer where they apply)
_KERAS_KWARG_MAP = {
    "lr": "learning_rate",
    "beta_1": "b1",
    "beta_2": "b2",
    "epsilon": "eps",
    "rho": "decay",  # RMSprop's smoothing constant
}


def make_optimizer(
    optimizer: str = "Adam", optimizer_kwargs: Optional[Dict[str, Any]] = None
) -> optax.GradientTransformation:
    """Keras optimizer name + kwargs → optax transform. Keras spellings
    (``lr``, ``beta_1``, ``beta_2``, ``epsilon``, ``momentum``, ``rho``) are
    translated so ported configs run unchanged; Keras' ``decay``
    (learning-rate schedule, no optax equivalent here) is dropped with a
    warning rather than crashing the build.

    Memoized by (name, kwargs): identical configs return the SAME optax
    object, so ``FleetSpec`` equality/hash work by value and the fleet
    program cache hits across ``build_fleet`` invocations (optax
    transforms otherwise compare by closure identity)."""
    key = (optimizer, tuple(sorted((optimizer_kwargs or {}).items())))
    try:
        cached = _OPTIMIZER_CACHE.get(key)
        if cached is not None:
            return cached
    except TypeError:  # unhashable kwarg value — build uncached
        key = None
    import inspect
    import logging

    raw = dict(optimizer_kwargs or {})
    if "decay" in raw:  # Keras lr-decay schedule — no optax equivalent here;
        # must be dropped BEFORE mapping so it can't collide with optax
        # rmsprop's own `decay` (the smoothing constant, Keras' `rho`)
        import logging as _logging

        _logging.getLogger(__name__).warning(
            "Optimizer %s: Keras 'decay' (lr schedule) is not supported; ignored",
            optimizer,
        )
        raw.pop("decay")
    kwargs = {_KERAS_KWARG_MAP.get(k, k): v for k, v in raw.items()}
    kwargs.setdefault("learning_rate", 1e-3)
    name = optimizer.lower()
    if name not in _OPTIMIZERS:
        raise ValueError(
            f"Unknown optimizer {optimizer!r}; supported: {sorted(_OPTIMIZERS)}"
        )
    fn = _OPTIMIZERS[name]
    accepted = set(inspect.signature(fn).parameters)
    dropped = {k: kwargs.pop(k) for k in list(kwargs) if k not in accepted}
    if dropped:
        logging.getLogger(__name__).warning(
            "Optimizer %s ignores unsupported kwargs: %s", optimizer, sorted(dropped)
        )
    transform = fn(**kwargs)
    if key is not None:
        _OPTIMIZER_CACHE[key] = transform
    return transform


_OPTIMIZER_CACHE: Dict[Any, optax.GradientTransformation] = {}


class ModelSpec(NamedTuple):
    """A ready-to-train model: pure module + optimizer + loss.

    ``input_kind`` is ``"flat"`` for ``(batch, F)`` models and ``"window"``
    for ``(batch, L, F)`` models — the estimator wrapper validates it against
    its own windowing behavior so a dense kind can't silently be used where
    an LSTM kind is required.
    """

    module: nn.Module
    optimizer: optax.GradientTransformation
    loss: str
    input_kind: str
    config: Dict[str, Any]  # JSON-able record of the resolved architecture
