"""What a model factory produces.

The reference's factories return *compiled Keras models* (architecture +
optimizer + loss bundled by ``keras.Model.compile`` — see
``gordo_components/model/factories/`` [UNVERIFIED]). The JAX equivalent of
"compiled model" is this spec: a Flax module (pure apply), an optax
gradient transformation, and the loss name — everything the train step
needs, nothing stateful.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import flax.linen as nn
import optax

_OPTIMIZERS = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "rmsprop": optax.rmsprop,
    "adagrad": optax.adagrad,
    "adamax": optax.adamax,
    "nadam": optax.nadam,
}


def make_optimizer(
    optimizer: str = "Adam", optimizer_kwargs: Optional[Dict[str, Any]] = None
) -> optax.GradientTransformation:
    """Keras optimizer name + kwargs → optax transform. Accepts the Keras
    spelling ``lr`` as well as ``learning_rate`` so ported configs run
    unchanged."""
    kwargs = dict(optimizer_kwargs or {})
    if "lr" in kwargs:
        kwargs["learning_rate"] = kwargs.pop("lr")
    kwargs.setdefault("learning_rate", 1e-3)
    name = optimizer.lower()
    if name not in _OPTIMIZERS:
        raise ValueError(
            f"Unknown optimizer {optimizer!r}; supported: {sorted(_OPTIMIZERS)}"
        )
    return _OPTIMIZERS[name](**kwargs)


class ModelSpec(NamedTuple):
    """A ready-to-train model: pure module + optimizer + loss.

    ``input_kind`` is ``"flat"`` for ``(batch, F)`` models and ``"window"``
    for ``(batch, L, F)`` models — the estimator wrapper validates it against
    its own windowing behavior so a dense kind can't silently be used where
    an LSTM kind is required.
    """

    module: nn.Module
    optimizer: optax.GradientTransformation
    loss: str
    input_kind: str
    config: Dict[str, Any]  # JSON-able record of the resolved architecture
