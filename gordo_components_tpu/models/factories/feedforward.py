"""Feedforward autoencoder factories.

Reference parity: ``gordo_components/model/factories/feedforward_autoencoder.py``
[UNVERIFIED] — ``feedforward_model`` (explicit encode/decode dims),
``feedforward_symmetric`` (mirrored dims), ``feedforward_hourglass``
(``compression_factor`` + ``encoding_layers`` via ``hourglass_calc_dims``).
Hyperparameter names match the reference exactly so fleet configs port 1:1.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from ..modules import DenseAutoencoderModule
from ..register import register_model_factory
from .spec import ModelSpec, make_optimizer


def _reject_unknown(kind: str, unknown: dict) -> None:
    """A misspelled hyperparameter must fail the build, not silently train
    the default architecture."""
    if unknown:
        raise ValueError(
            f"Unknown hyperparameters for kind {kind!r}: {sorted(unknown)}"
        )


def _broadcast_funcs(funcs, dims, default: str) -> Tuple[str, ...]:
    if funcs is None:
        return tuple(default for _ in dims)
    if isinstance(funcs, str):
        return tuple(funcs for _ in dims)
    funcs = tuple(funcs)
    if len(funcs) != len(dims):
        raise ValueError(
            f"Got {len(funcs)} activation funcs for {len(dims)} layers"
        )
    return funcs


def hourglass_calc_dims(
    compression_factor: float, encoding_layers: int, n_features: int
) -> Tuple[int, ...]:
    """Linearly interpolated layer dims from ``n_features`` down to
    ``n_features * compression_factor`` over ``encoding_layers`` layers.

    Pinned golden values (tests/test_models.py): ``(0.5, 3, 10) →
    (8, 7, 5)`` — the contract the reference's own unit tests assert.
    """
    if not 0 <= compression_factor <= 1:
        raise ValueError(
            f"compression_factor must be 0..1, got {compression_factor}"
        )
    if encoding_layers < 1:
        raise ValueError(f"encoding_layers must be >= 1, got {encoding_layers}")
    smallest = max(1, n_features * compression_factor)
    slope = (n_features - smallest) / encoding_layers
    dims = tuple(
        int(round(n_features - slope * i)) for i in range(1, encoding_layers + 1)
    )
    return dims


def _build(
    n_features: int,
    n_features_out: Optional[int],
    encoding_dim: Sequence[int],
    encoding_func,
    decoding_dim: Sequence[int],
    decoding_func,
    out_func: str,
    optimizer: str,
    optimizer_kwargs: Optional[Dict[str, Any]],
    loss: str,
    compute_dtype: str,
) -> ModelSpec:
    n_features_out = n_features_out or n_features
    encoding_funcs = _broadcast_funcs(encoding_func, encoding_dim, "tanh")
    decoding_funcs = _broadcast_funcs(decoding_func, decoding_dim, "tanh")
    module = DenseAutoencoderModule(
        encoding_dims=tuple(encoding_dim),
        decoding_dims=tuple(decoding_dim),
        n_features_out=n_features_out,
        encoding_funcs=encoding_funcs,
        decoding_funcs=decoding_funcs,
        out_func=out_func,
        compute_dtype=compute_dtype,
    )
    config = {
        "n_features": n_features,
        "n_features_out": n_features_out,
        "encoding_dim": list(encoding_dim),
        "encoding_func": list(encoding_funcs),
        "decoding_dim": list(decoding_dim),
        "decoding_func": list(decoding_funcs),
        "out_func": out_func,
        "optimizer": optimizer,
        "optimizer_kwargs": dict(optimizer_kwargs or {}),
        "loss": loss,
        "compute_dtype": compute_dtype,
    }
    return ModelSpec(
        module=module,
        optimizer=make_optimizer(optimizer, optimizer_kwargs),
        loss=loss,
        input_kind="flat",
        config=config,
    )


@register_model_factory("feedforward_model")
def feedforward_model(
    n_features: int,
    n_features_out: Optional[int] = None,
    encoding_dim: Sequence[int] = (256, 128, 64),
    encoding_func=None,
    decoding_dim: Sequence[int] = (64, 128, 256),
    decoding_func=None,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Explicit encoder/decoder dims — the reference's base factory."""
    _reject_unknown("feedforward_model", unknown)
    return _build(
        n_features,
        n_features_out,
        encoding_dim,
        encoding_func,
        decoding_dim,
        decoding_func,
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )


@register_model_factory("feedforward_symmetric")
def feedforward_symmetric(
    n_features: int,
    n_features_out: Optional[int] = None,
    dims: Sequence[int] = (256, 128, 64),
    funcs=None,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Encoder ``dims``, decoder mirrored (reversed) automatically."""
    _reject_unknown("feedforward_symmetric", unknown)
    if not dims:
        raise ValueError("dims must contain at least one layer size")
    encoding_funcs = _broadcast_funcs(funcs, dims, "tanh")
    return _build(
        n_features,
        n_features_out,
        tuple(dims),
        encoding_funcs,
        tuple(reversed(dims)),
        tuple(reversed(encoding_funcs)),
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )


@register_model_factory("feedforward_hourglass")
def feedforward_hourglass(
    n_features: int,
    n_features_out: Optional[int] = None,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    """Hourglass: dims interpolate down to ``n_features * compression_factor``
    then mirror back up."""
    _reject_unknown("feedforward_hourglass", unknown)
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return _build(
        n_features,
        n_features_out,
        dims,
        func,
        tuple(reversed(dims)),
        func,
        out_func,
        optimizer,
        optimizer_kwargs,
        loss,
        compute_dtype,
    )
