"""PatchTST transformer factory — the rebuild's new model kind.

No reference counterpart (the reference zoo stops at LSTM); this covers
BASELINE.md config 5 ("Transformer/PatchTST anomaly head on a 10k-tag
plant"). Architecture follows PatchTST (Nie et al., ICLR 2023, public):
channel-independent patching — each tag's lookback window is split into
patches, embedded, and run through a shared transformer encoder; a linear
head per channel emits the reconstruction/forecast. TPU notes: patching is
a static gather; attention over ≤dozens of patches lowers to MXU matmuls
that XLA flash-fuses; for very long windows the sequence axis can shard
over a mesh with :func:`gordo_components_tpu.ops.attention.ring_attention`.

The ``patchtst`` kind plugs into the standard window estimators
(``input_kind="window"``), so ``PatchTSTAutoEncoder`` / ``PatchTSTForecast``
inherit the exact windowing contracts — and the fleet engine buckets
transformer machines like any other kind.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ...ops.attention import dense_attention, ring_attention
from ...ops.flash_attention import flash_attention
from ..modules import activation, resolve_dtype
from ..register import register_model_factory
from .feedforward import _reject_unknown
from .spec import ModelSpec, make_optimizer


class MultiHeadSelfAttention(nn.Module):
    """q/k/v/out projections around a swappable attention core.

    ``attention_impl``:

    - ``"dense"`` — :func:`ops.attention.dense_attention` (XLA fuses it
      well for patch counts in the dozens);
    - ``"flash"`` — :func:`ops.flash_attention.flash_attention`: the
      Pallas blockwise kernel — scores stay in VMEM tiles, never O(P²)
      HBM; the single-device long-window path. Exact; parity pinned by
      tests/test_flash_attention.py;
    - ``"ring"`` — :func:`ops.attention.ring_attention`: the sequence
      (patch) axis shards over a 1-D mesh of all local devices and K/V
      blocks rotate via ICI neighbor hops (SURVEY.md §6.7 long-context
      path). Same parameters, exact same math — pinned by
      tests/test_transformer.py.
    - ``"ring_flash"`` — ring across devices with the Pallas blockwise
      kernel as each hop's local update: per-hop scores stay in VMEM too,
      so the sharded long-context path never materializes scores in HBM
      at any level. Exact; parity pinned alongside ring.

    Attention-weight dropout applies on the dense path (weights are
    materialized there); the flash and ring paths cannot drop weights they
    never materialize, so they train with residual dropout only.
    """

    d_model: int
    n_heads: int
    compute_dtype: Any
    attention_impl: str = "dense"
    ring_axis: str = "seq"
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.d_model % self.n_heads != 0:
            raise ValueError(
                f"d_model ({self.d_model}) must be divisible by n_heads "
                f"({self.n_heads})"
            )
        dtype = resolve_dtype(self.compute_dtype)
        head_dim = self.d_model // self.n_heads
        # fused q/k/v projection: one (d_model -> 3*d_model) matmul instead
        # of three d_model-wide ones — at the zoo's small d_model a single
        # 3x-wide contraction wastes fewer MXU tile lanes and gives XLA one
        # op to schedule. DenseGeneral's kernel init draws per output
        # feature with fan_in = d_model either way, so statistics match the
        # separate projections. DELIBERATE pre-1.0 param-tree change
        # (query/key/value -> qkv): artifacts serialized before this do not
        # load into the new tree — unlike the remat knob below (a runtime
        # toggle that must keep the tree stable), this is a versioned
        # architecture change with no compatibility shim.
        qkv = nn.DenseGeneral(
            (3, self.n_heads, head_dim), dtype=dtype, name="qkv"
        )(x)
        q, k, v = (qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :])
        if self.attention_impl in ("ring", "ring_flash"):
            mesh = Mesh(np.asarray(jax.devices()), (self.ring_axis,))
            out = ring_attention(
                q, k, v, mesh=mesh, axis_name=self.ring_axis,
                block_impl="flash" if self.attention_impl == "ring_flash"
                else "dense",
            )
        elif self.attention_impl == "flash":
            out = flash_attention(q, k, v)
        elif self.attention_impl == "dense":
            if self.dropout_rate > 0.0 and not deterministic:
                # materialized-weights path so dropout can hit the weights
                # (same math as ops.attention.dense_attention)
                scale = head_dim**-0.5
                logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
                weights = jax.nn.softmax(logits, axis=-1)
                weights = nn.Dropout(self.dropout_rate)(
                    weights, deterministic=False
                )
                out = jnp.einsum("...hqk,...khd->...qhd", weights, v)
            else:
                out = dense_attention(q, k, v)
        else:
            raise ValueError(
                f"Unknown attention_impl {self.attention_impl!r}; "
                "use 'dense', 'flash', 'ring', or 'ring_flash'"
            )
        return nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=dtype, name="out"
        )(out)


class TransformerEncoderLayer(nn.Module):
    d_model: int
    n_heads: int
    ff_dim: int
    dropout: float
    compute_dtype: Any
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        dtype = resolve_dtype(self.compute_dtype)
        h = nn.LayerNorm(dtype=dtype)(x)
        h = MultiHeadSelfAttention(
            d_model=self.d_model,
            n_heads=self.n_heads,
            compute_dtype=self.compute_dtype,
            attention_impl=self.attention_impl,
            dropout_rate=self.dropout,
        )(h, deterministic=deterministic)
        x = x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=dtype)(x)
        h = nn.Dense(self.ff_dim, dtype=dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=dtype)(h)
        return x + nn.Dropout(self.dropout)(h, deterministic=deterministic)


class PatchTSTModule(nn.Module):
    """``(batch, L, F) → (batch, F_out)`` channel-independent PatchTST."""

    n_features_out: int
    patch_length: int
    stride: int
    d_model: int
    n_heads: int
    n_layers: int
    ff_dim: int
    dropout: float = 0.0
    out_func: str = "linear"
    compute_dtype: Any = "float32"
    attention_impl: str = "dense"
    # rematerialize encoder layers on the backward pass: activations are
    # recomputed instead of stored, trading ~1 extra forward of FLOPs for
    # O(n_layers) less HBM — the standard lever for plant-scale configs
    # (10k tags x long windows) whose activations otherwise exceed HBM
    remat: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        batch, window, n_features = x.shape
        if window < self.patch_length:
            raise ValueError(
                f"PatchTST input window ({window}) is shorter than "
                f"patch_length ({self.patch_length}); set the estimator's "
                "lookback_window >= patch_length"
            )
        dtype = resolve_dtype(self.compute_dtype)
        channels = jnp.swapaxes(x.astype(dtype), 1, 2)  # (B, F, L)
        starts = np.arange(0, window - self.patch_length + 1, self.stride)
        # patching as P static contiguous slices + stack, not an
        # advanced-index gather: slice/concat is XLA:TPU's fast layout
        # path, while a (P, patch_len) index-matrix gather addresses
        # every element through the scalar core — this runs every
        # training step on the (B, F, L) tensor, so the lowering matters
        patches = jnp.stack(
            [
                jax.lax.slice_in_dim(channels, s, s + self.patch_length, axis=2)
                for s in starts
            ],
            axis=2,
        )  # (B, F, P, patch_len)
        n_patches = len(starts)
        h = patches.reshape(batch * n_features, n_patches, self.patch_length)
        h = nn.Dense(self.d_model, dtype=dtype)(h)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (n_patches, self.d_model),
        )
        h = h + pos.astype(dtype)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        layer_cls = (
            nn.remat(TransformerEncoderLayer, static_argnums=(2,))
            if self.remat
            else TransformerEncoderLayer
        )
        for i in range(self.n_layers):
            # explicit names pin the param tree: nn.remat renames the class
            # (Checkpoint...), and auto-scoping would give remat=True a
            # different tree than remat=False — breaking artifact loads
            # that flip the flag (remat is a memory knob, not a new model)
            h = layer_cls(
                d_model=self.d_model,
                n_heads=self.n_heads,
                ff_dim=self.ff_dim,
                dropout=self.dropout,
                compute_dtype=self.compute_dtype,
                attention_impl=self.attention_impl,
                name=f"TransformerEncoderLayer_{i}",
            )(h, deterministic)
        h = nn.LayerNorm(dtype=dtype)(h)
        flat = h.reshape(batch, n_features, n_patches * self.d_model)
        out = nn.Dense(1, dtype=dtype)(flat)[..., 0]  # per-channel head (B, F)
        if self.n_features_out != n_features:
            out = nn.Dense(self.n_features_out, dtype=dtype)(out)
        return activation(self.out_func)(out).astype(jnp.float32)


@register_model_factory("patchtst")
def patchtst(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 32,
    patch_length: int = 8,
    stride: Optional[int] = None,
    d_model: int = 64,
    n_heads: int = 4,
    n_layers: int = 2,
    ff_dim: Optional[int] = None,
    dropout: float = 0.0,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    attention_impl: str = "dense",
    remat: bool = False,
    **unknown: Any,
) -> ModelSpec:
    _reject_unknown("patchtst", unknown)
    if lookback_window < patch_length:
        raise ValueError(
            f"lookback_window ({lookback_window}) must be >= patch_length "
            f"({patch_length})"
        )
    stride = stride or max(1, patch_length // 2)
    ff_dim = ff_dim or 2 * d_model
    n_features_out = n_features_out or n_features
    if attention_impl not in ("dense", "flash", "ring", "ring_flash"):
        raise ValueError(
            f"Unknown attention_impl {attention_impl!r}; "
            "use 'dense', 'flash', 'ring', or 'ring_flash'"
        )
    if d_model % n_heads != 0:
        raise ValueError(
            f"d_model ({d_model}) must be divisible by n_heads ({n_heads})"
        )
    if attention_impl in ("ring", "ring_flash"):
        n_patches = (lookback_window - patch_length) // stride + 1
        n_devices = jax.device_count()
        if n_patches % n_devices != 0:
            raise ValueError(
                f"attention_impl={attention_impl!r} shards the patch axis "
                f"over {n_devices} device(s), but {n_patches} patches do "
                "not divide evenly; pick lookback_window/patch_length/"
                "stride so (lookback_window - patch_length)//stride + 1 is "
                "a multiple of the device count"
            )
    module = PatchTSTModule(
        n_features_out=n_features_out,
        patch_length=patch_length,
        stride=stride,
        d_model=d_model,
        n_heads=n_heads,
        n_layers=n_layers,
        ff_dim=ff_dim,
        dropout=dropout,
        out_func=out_func,
        compute_dtype=compute_dtype,
        attention_impl=attention_impl,
        remat=remat,
    )
    config = {
        "n_features": n_features,
        "n_features_out": n_features_out,
        "lookback_window": lookback_window,
        "patch_length": patch_length,
        "stride": stride,
        "d_model": d_model,
        "n_heads": n_heads,
        "n_layers": n_layers,
        "ff_dim": ff_dim,
        "dropout": dropout,
        "out_func": out_func,
        "optimizer": optimizer,
        "optimizer_kwargs": dict(optimizer_kwargs or {}),
        "loss": loss,
        "compute_dtype": compute_dtype,
        "attention_impl": attention_impl,
        "remat": remat,
    }
    return ModelSpec(
        module=module,
        optimizer=make_optimizer(optimizer, optimizer_kwargs),
        loss=loss,
        input_kind="window",
        config=config,
    )
