"""PatchTST transformer factory — the rebuild's new model kind.

No reference counterpart (the reference zoo stops at LSTM); this covers
BASELINE.md config 5 ("Transformer/PatchTST anomaly head on a 10k-tag
plant"). Architecture follows PatchTST (Nie et al., ICLR 2023, public):
channel-independent patching — each tag's lookback window is split into
patches, embedded, and run through a shared transformer encoder; a linear
head per channel emits the reconstruction/forecast. TPU notes: patching is
a static gather; attention over ≤dozens of patches lowers to MXU matmuls
that XLA flash-fuses; for very long windows the sequence axis can shard
over a mesh with :func:`gordo_components_tpu.ops.attention.ring_attention`.

The ``patchtst`` kind plugs into the standard window estimators
(``input_kind="window"``), so ``PatchTSTAutoEncoder`` / ``PatchTSTForecast``
inherit the exact windowing contracts — and the fleet engine buckets
transformer machines like any other kind.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..modules import activation, resolve_dtype
from ..register import register_model_factory
from .feedforward import _reject_unknown
from .spec import ModelSpec, make_optimizer


class TransformerEncoderLayer(nn.Module):
    d_model: int
    n_heads: int
    ff_dim: int
    dropout: float
    compute_dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        dtype = resolve_dtype(self.compute_dtype)
        h = nn.LayerNorm(dtype=dtype)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads,
            qkv_features=self.d_model,
            dropout_rate=self.dropout,
            dtype=dtype,
        )(h, h, deterministic=deterministic)
        x = x + nn.Dropout(self.dropout)(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=dtype)(x)
        h = nn.Dense(self.ff_dim, dtype=dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=dtype)(h)
        return x + nn.Dropout(self.dropout)(h, deterministic=deterministic)


class PatchTSTModule(nn.Module):
    """``(batch, L, F) → (batch, F_out)`` channel-independent PatchTST."""

    n_features_out: int
    patch_length: int
    stride: int
    d_model: int
    n_heads: int
    n_layers: int
    ff_dim: int
    dropout: float = 0.0
    out_func: str = "linear"
    compute_dtype: Any = "float32"

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        batch, window, n_features = x.shape
        if window < self.patch_length:
            raise ValueError(
                f"PatchTST input window ({window}) is shorter than "
                f"patch_length ({self.patch_length}); set the estimator's "
                "lookback_window >= patch_length"
            )
        dtype = resolve_dtype(self.compute_dtype)
        channels = jnp.swapaxes(x.astype(dtype), 1, 2)  # (B, F, L)
        starts = np.arange(0, window - self.patch_length + 1, self.stride)
        idx = starts[:, None] + np.arange(self.patch_length)[None, :]
        patches = channels[:, :, idx]  # (B, F, P, patch_len) static gather
        n_patches = len(starts)
        h = patches.reshape(batch * n_features, n_patches, self.patch_length)
        h = nn.Dense(self.d_model, dtype=dtype)(h)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (n_patches, self.d_model),
        )
        h = h + pos.astype(dtype)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        for _ in range(self.n_layers):
            h = TransformerEncoderLayer(
                d_model=self.d_model,
                n_heads=self.n_heads,
                ff_dim=self.ff_dim,
                dropout=self.dropout,
                compute_dtype=self.compute_dtype,
            )(h, deterministic=deterministic)
        h = nn.LayerNorm(dtype=dtype)(h)
        flat = h.reshape(batch, n_features, n_patches * self.d_model)
        out = nn.Dense(1, dtype=dtype)(flat)[..., 0]  # per-channel head (B, F)
        if self.n_features_out != n_features:
            out = nn.Dense(self.n_features_out, dtype=dtype)(out)
        return activation(self.out_func)(out).astype(jnp.float32)


@register_model_factory("patchtst")
def patchtst(
    n_features: int,
    n_features_out: Optional[int] = None,
    lookback_window: int = 32,
    patch_length: int = 8,
    stride: Optional[int] = None,
    d_model: int = 64,
    n_heads: int = 4,
    n_layers: int = 2,
    ff_dim: Optional[int] = None,
    dropout: float = 0.0,
    out_func: str = "linear",
    optimizer: str = "Adam",
    optimizer_kwargs: Optional[Dict[str, Any]] = None,
    loss: str = "mse",
    compute_dtype: str = "float32",
    **unknown: Any,
) -> ModelSpec:
    _reject_unknown("patchtst", unknown)
    if lookback_window < patch_length:
        raise ValueError(
            f"lookback_window ({lookback_window}) must be >= patch_length "
            f"({patch_length})"
        )
    stride = stride or max(1, patch_length // 2)
    ff_dim = ff_dim or 2 * d_model
    n_features_out = n_features_out or n_features
    module = PatchTSTModule(
        n_features_out=n_features_out,
        patch_length=patch_length,
        stride=stride,
        d_model=d_model,
        n_heads=n_heads,
        n_layers=n_layers,
        ff_dim=ff_dim,
        dropout=dropout,
        out_func=out_func,
        compute_dtype=compute_dtype,
    )
    config = {
        "n_features": n_features,
        "n_features_out": n_features_out,
        "lookback_window": lookback_window,
        "patch_length": patch_length,
        "stride": stride,
        "d_model": d_model,
        "n_heads": n_heads,
        "n_layers": n_layers,
        "ff_dim": ff_dim,
        "dropout": dropout,
        "out_func": out_func,
        "optimizer": optimizer,
        "optimizer_kwargs": dict(optimizer_kwargs or {}),
        "loss": loss,
        "compute_dtype": compute_dtype,
    }
    return ModelSpec(
        module=module,
        optimizer=make_optimizer(optimizer, optimizer_kwargs),
        loss=loss,
        input_kind="window",
        config=config,
    )
