"""Model-graph analysis: extract the fleet/serving-relevant skeleton from a
materialized config graph.

The reference's canonical anomaly config (SURVEY.md §3 anomaly row
[UNVERIFIED]) nests ``DiffBasedAnomalyDetector(TransformedTargetRegressor(
Pipeline([scaler, estimator])))``. Both the fleet trainer
(:mod:`gordo_components_tpu.parallel.build_fleet`) and the stacked serving
engine (:mod:`gordo_components_tpu.server.engine`) need the same
decomposition — estimator core, input scaler, target scaler, detector — so
it lives here, below both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from .anomaly.diff import DiffBasedAnomalyDetector
from .models import BaseFlaxEstimator
from .pipeline import Pipeline, TransformedTargetRegressor
from .transformers import MinMaxScaler, StandardScaler


@dataclass
class Analyzed:
    """The fleet-relevant skeleton of a materialized model config."""

    estimator: BaseFlaxEstimator
    input_scaler: Optional[Any]
    target_scaler: Optional[Any]
    detector: Optional[DiffBasedAnomalyDetector]


def analyze_model(model: Any) -> Analyzed:
    """Decompose a supported config graph; raises ``ValueError`` for shapes
    the compiled paths can't lift (callers fall back to the host path)."""
    detector = model if isinstance(model, DiffBasedAnomalyDetector) else None
    core = detector.base_estimator if detector else model
    target_scaler = None
    if isinstance(core, TransformedTargetRegressor):
        target_scaler = core.transformer
        core = core.regressor
    input_scaler = None
    if isinstance(core, Pipeline):
        steps = [step for _, step in core.steps]
        if len(steps) == 2 and isinstance(steps[0], (MinMaxScaler, StandardScaler)):
            input_scaler, core = steps[0], steps[1]
        elif len(steps) == 1:
            core = steps[0]
        else:
            raise ValueError(
                "Compiled paths support Pipeline([scaler, estimator]) or "
                f"Pipeline([estimator]); got {len(steps)} steps"
            )
    if not isinstance(core, BaseFlaxEstimator):
        raise ValueError(
            f"Compiled paths require a zoo estimator at the core; got "
            f"{type(core).__name__}"
        )
    return Analyzed(core, input_scaler, target_scaler, detector)
