"""Factory registry: ``kind`` string → model factory.

Reference parity: ``gordo_components/model/register.py`` [UNVERIFIED] — the
``register_model_builder`` decorator maps a ``kind`` name (e.g.
``"feedforward_hourglass"``) to a function building a compiled Keras model.
Here a factory builds a :class:`~gordo_components_tpu.models.factories.spec.ModelSpec`
(Flax module + optax optimizer + loss), and the registry additionally accepts
dotted import paths as kinds so user-defined factories plug in without
touching this package — the same extension mechanism the reference exposes.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..utils.config import resolve_dotted_path

_REGISTRY: Dict[str, Callable] = {}


def register_model_factory(kind: str) -> Callable:
    """Decorator registering ``factory`` under ``kind``."""

    def decorator(factory: Callable) -> Callable:
        if kind in _REGISTRY and _REGISTRY[kind] is not factory:
            raise ValueError(f"Model kind {kind!r} already registered")
        _REGISTRY[kind] = factory
        return factory

    return decorator


def get_factory(kind: str) -> Callable:
    """Look up ``kind`` in the registry, falling back to a dotted import
    path (``package.module.factory_fn``)."""
    if kind in _REGISTRY:
        return _REGISTRY[kind]
    if "." in kind:
        factory = resolve_dotted_path(kind)
        if not callable(factory):
            raise ValueError(f"Model kind {kind!r} resolved to a non-callable")
        return factory
    raise ValueError(
        f"Unknown model kind {kind!r}; registered kinds: {sorted(_REGISTRY)}"
    )


def list_kinds() -> List[str]:
    return sorted(_REGISTRY)
