"""Evaluation metrics as plain numpy functions.

The reference scores with sklearn metrics (explained variance is
``KerasAutoEncoder.score``'s metric; the builder's CV also records r2 /
MAE / MSE — ``gordo_components/builder/build_model.py`` [UNVERIFIED]).
Implemented here directly so scoring has no sklearn dependency in the hot
path and matches sklearn's multioutput="uniform_average" semantics (pinned
against sklearn in tests/test_models.py).
"""

from __future__ import annotations

import numpy as np


def explained_variance_score(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    num = np.var(y_true - y_pred, axis=0)
    den = np.var(y_true, axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        scores = 1.0 - num / den
    # sklearn: zero-variance outputs score 1.0 if perfectly predicted else 0.0
    scores = np.where(den == 0.0, np.where(num == 0.0, 1.0, 0.0), scores)
    return float(np.mean(scores))


def r2_score(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    num = np.sum((y_true - y_pred) ** 2, axis=0)
    den = np.sum((y_true - np.mean(y_true, axis=0)) ** 2, axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        scores = 1.0 - num / den
    scores = np.where(den == 0.0, np.where(num == 0.0, 1.0, 0.0), scores)
    return float(np.mean(scores))


def mean_squared_error(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    diff = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(diff * diff))


def mean_absolute_error(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    diff = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return float(np.mean(np.abs(diff)))


METRICS = {
    "explained_variance_score": explained_variance_score,
    "r2_score": r2_score,
    "mean_squared_error": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
}
