"""The estimator contract every model in the zoo satisfies.

Reference parity: ``gordo_components/model/base.py`` [UNVERIFIED] defines
``GordoBase`` with ``get_metadata()`` on top of the sklearn estimator API
(``fit``/``predict``/``get_params``/``set_params``/``score``). The rebuild
adds an explicit pure-state contract (:meth:`get_state`/:meth:`set_state`):
every fitted model must round-trip through a dict of numpy arrays + plain
JSON config, because that is what the serializer persists and what the fleet
engine stacks across machines.
"""

from __future__ import annotations

import abc
from typing import Any, Dict


class GordoBase(abc.ABC):
    """Abstract base for all models (and the anomaly wrappers around them)."""

    @abc.abstractmethod
    def fit(self, X, y=None, **kwargs):
        """Fit to ``X`` (and ``y`` when the target tags differ from inputs)."""

    @abc.abstractmethod
    def get_metadata(self) -> Dict[str, Any]:
        """JSON-serializable description of the fitted model: kind, hyper-
        params, loss history, durations — merged into build metadata."""

    @abc.abstractmethod
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        """Constructor kwargs, sufficient to re-create this estimator
        (sklearn ``get_params`` semantics; ``clone`` compatibility)."""

    def set_params(self, **params) -> "GordoBase":
        for key, value in params.items():
            setattr(self, key, value)
        return self

    # -- pure-state persistence contract ------------------------------------
    def get_state(self) -> Dict[str, Any]:
        """Fitted state as {numpy arrays + JSON-able config}. Default: no
        fitted state (stateless transformers override nothing)."""
        return {}

    def set_state(self, state: Dict[str, Any]) -> "GordoBase":
        """Inverse of :meth:`get_state`."""
        return self


def clone_estimator(estimator):
    """Unfitted copy via ``get_params`` — sklearn.clone semantics without
    requiring sklearn introspection of ``**kwargs`` constructors."""
    return type(estimator)(**estimator.get_params(deep=False))
