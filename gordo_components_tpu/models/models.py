"""Estimator wrappers: the reference's Keras estimator API over Flax/optax.

Reference parity: ``gordo_components/model/models.py`` [UNVERIFIED] —
``KerasBaseEstimator`` (kind-dispatched factory, sklearn API, picklable
state), ``KerasAutoEncoder`` (X→X), ``KerasLSTMAutoEncoder`` (window →
window's last row), ``KerasLSTMForecast`` (window → next row). The windowing
off-by-one contract lives in :mod:`gordo_components_tpu.ops.windowing` and is
pinned by golden tests.

TPU notes: ``fit`` compiles one XLA program per (padded-rows, features)
shape; ``predict`` pads row counts up to a shape bucket so a serving process
compiles a handful of programs total instead of one per request size.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import windowing
from ..utils.cache import cached
from .base import GordoBase
from .metrics import explained_variance_score
from .register import get_factory
from .train import make_fit_fn, make_predict_fn, pad_to_batches

# value-keyed memo of jitted fit/predict programs: sklearn-style CV clones
# the estimator per fold, and a fresh ``jax.jit`` wrapper per clone would
# re-trace + re-compile an identical program k+1 times per machine (VERDICT
# r2 #5). Keyed on the estimator's full config + feature widths — the same
# scheme as parallel.fleet's program cache — so clones, refits, and
# unpickled copies all share one compiled program per shape.
_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 64


def _as_float32(X) -> np.ndarray:
    values = getattr(X, "values", X)
    arr = np.asarray(values, dtype=np.float32)
    if arr.ndim == 1:
        arr = arr[:, None]  # sklearn-style 1-D target → single-output column
    return arr


def _round_up_bucket(n: int, minimum: int = 256) -> int:
    """Next power-of-two-ish bucket ≥ n, floored at ``minimum`` — bounds the
    number of distinct predict compilations a long-lived server sees."""
    bucket = minimum
    while bucket < n:
        bucket *= 2
    return bucket


class BaseFlaxEstimator(GordoBase):
    """Common fit/predict machinery; subclasses define the windowing contract
    via ``lookahead`` (None = flat 2-D input, 0 = reconstruction, 1 = one-step
    forecast)."""

    lookahead: Optional[int] = None  # class-level contract

    def __init__(self, kind: str, **kwargs: Any):
        self.kind = kind
        self.batch_size = int(kwargs.pop("batch_size", 32))
        self.epochs = int(kwargs.pop("epochs", 1))
        self.seed = int(kwargs.pop("seed", 0))
        self.factory_kwargs = kwargs
        # fitted state
        self.params_: Any = None
        self._spec = None
        self._predict_jit = None
        self.history_: list = []
        self.n_features_: Optional[int] = None
        self.n_features_out_: Optional[int] = None
        self.fit_duration_: Optional[float] = None

    # -- windowing contract hooks ------------------------------------------
    @property
    def lookback_window(self) -> int:
        if self.lookahead is None:
            return 1
        return int(self.factory_kwargs.get("lookback_window", 1))

    def _prepare_inputs(self, X: np.ndarray) -> np.ndarray:
        if self.lookahead is None:
            return X
        return np.asarray(
            windowing.sliding_windows(X, self.lookback_window, self.lookahead)
        )

    def _prepare_targets(self, y: np.ndarray) -> np.ndarray:
        if self.lookahead is None:
            return y
        if self.lookahead == 0:
            return windowing.reconstruction_targets(y, self.lookback_window)
        return windowing.forecast_targets(
            y, self.lookback_window, self.lookahead
        )

    # -- compiled-program identity -----------------------------------------
    def _program_key(self) -> tuple:
        """Value key for the shared program cache: everything that shapes
        the traced computation (config + feature widths). Two estimators
        with equal keys build structurally identical flax modules and optax
        transforms, so they can share one jitted program."""
        return (
            type(self).__name__,
            self.kind,
            json.dumps(self.factory_kwargs, sort_keys=True, default=repr),
            self.batch_size,
            self.epochs,
            self.lookahead,
            self.n_features_,
            self.n_features_out_,
        )

    # -- spec / module construction ----------------------------------------
    def _make_spec(self, n_features: int, n_features_out: int):
        factory = get_factory(self.kind)
        spec = factory(
            n_features=n_features,
            n_features_out=n_features_out,
            **self.factory_kwargs,
        )
        expected = "flat" if self.lookahead is None else "window"
        if spec.input_kind != expected:
            raise ValueError(
                f"Model kind {self.kind!r} produces {spec.input_kind!r} inputs "
                f"but {type(self).__name__} requires {expected!r} "
                f"(e.g. use an lstm_* kind with LSTM estimators)"
            )
        return spec

    def _sample_input(self, n_features: int) -> jnp.ndarray:
        if self.lookahead is None:
            return jnp.zeros((1, n_features), jnp.float32)
        return jnp.zeros((1, self.lookback_window, n_features), jnp.float32)

    # -- sklearn API --------------------------------------------------------
    def fit(self, X, y=None, **_kwargs) -> "BaseFlaxEstimator":
        started = time.perf_counter()
        X = _as_float32(X)
        y_arr = X if y is None else _as_float32(y)
        if X.ndim != 2:
            raise ValueError(f"Expected 2-D (rows, features) input, got {X.shape}")
        if len(y_arr) != len(X):
            raise ValueError(
                f"X and y row counts differ: {len(X)} vs {len(y_arr)}"
            )
        targets = self._prepare_targets(y_arr)
        self.n_features_ = int(X.shape[1])
        self.n_features_out_ = int(y_arr.shape[1])

        self._spec = self._make_spec(self.n_features_, self.n_features_out_)
        key = jax.random.PRNGKey(self.seed)
        init_key, fit_key = jax.random.split(key)
        variables = self._spec.module.init(
            init_key, self._sample_input(self.n_features_), deterministic=True
        )
        params = variables["params"]

        dropout_rate = float(self._spec.config.get("dropout", 0.0) or 0.0)
        fit_kwargs = dict(
            loss=self._spec.loss,
            batch_size=self.batch_size,
            epochs=self.epochs,
            use_dropout=dropout_rate > 0.0,
        )
        spec = self._spec
        if self.lookahead is None:
            fit_fn = cached(
                _PROGRAM_CACHE,
                _PROGRAM_CACHE_MAX,
                ("fit",) + self._program_key(),
                lambda: jax.jit(
                    make_fit_fn(spec.module.apply, spec.optimizer, **fit_kwargs)
                ),
            )
            Xp, yp, w = pad_to_batches(X, targets, self.batch_size)
            result = fit_fn(
                params, jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(w), fit_key
            )
        else:
            # windowed models train on start INDICES: each batch gathers its
            # (batch, L, F) windows from the row matrix inside the compiled
            # loop, so the device holds (n, F) rows — not the L×-blown-up
            # window tensor — and the per-epoch shuffle permutes indices,
            # not windows (same scheme as the fleet program; numerically
            # identical to materialized windows)
            L, la = self.lookback_window, self.lookahead
            n_samples = windowing.n_windows(len(X), L, la)
            if n_samples <= 0:
                raise ValueError(
                    f"Need at least lookback_window+lookahead={L + la} rows "
                    f"to fit, got {len(X)}"
                )
            apply = spec.module.apply
            optimizer = spec.optimizer

            def fit_windowed(p, rows, starts, y_t, w_t, k):
                def windowed_apply(variables, sb, **kw):
                    return apply(
                        variables, windowing.gather_windows(rows, sb, L), **kw
                    )

                return make_fit_fn(windowed_apply, optimizer, **fit_kwargs)(
                    p, starts, y_t, w_t, k
                )

            fit_fn = cached(
                _PROGRAM_CACHE,
                _PROGRAM_CACHE_MAX,
                ("fit",) + self._program_key(),
                lambda: jax.jit(fit_windowed),
            )
            starts, yp, w = pad_to_batches(
                np.arange(n_samples), targets, self.batch_size
            )
            result = fit_fn(
                params,
                jnp.asarray(X),
                jnp.asarray(starts),
                jnp.asarray(yp),
                jnp.asarray(w),
                fit_key,
            )
        self.params_ = result.params
        self.history_ = [float(v) for v in jax.device_get(result.loss_history)]
        self._predict_jit = self._build_predict_jit()
        self.fit_duration_ = time.perf_counter() - started
        return self

    def _build_predict_jit(self):
        """Shared (cached) jitted predict program — clones and unpickled
        copies with equal configs reuse one trace cache, so a served fleet
        of same-architecture machines compiles each request shape once."""
        spec = self._spec
        return cached(
            _PROGRAM_CACHE,
            _PROGRAM_CACHE_MAX,
            ("predict",) + self._program_key(),
            lambda: jax.jit(make_predict_fn(spec.module.apply)),
        )

    def _check_fitted(self):
        if self.params_ is None:
            raise ValueError(
                f"{type(self).__name__} is not fitted; call fit() first"
            )

    def predict(self, X) -> np.ndarray:
        """Predictions aligned per the windowing contract: flat models return
        one row per input row; windowed models return
        ``n - lookback_window + 1 - lookahead`` rows (see
        :func:`~gordo_components_tpu.ops.windowing.window_output_index`)."""
        self._check_fitted()
        X = _as_float32(X)
        inputs = self._prepare_inputs(X)
        n = inputs.shape[0]
        bucket = _round_up_bucket(n)
        if bucket != n:
            pad = np.zeros((bucket - n, *inputs.shape[1:]), inputs.dtype)
            inputs = np.concatenate([inputs, pad])
        out = self._predict_jit(self.params_, jnp.asarray(inputs))
        return np.asarray(jax.device_get(out))[:n]

    def score(self, X, y=None) -> float:
        """Explained variance of predictions vs the contract-aligned targets
        (reference: ``KerasAutoEncoder.score`` / ``KerasLSTMForecast.score``)."""
        self._check_fitted()
        X = _as_float32(X)
        y_arr = X if y is None else _as_float32(y)
        return explained_variance_score(self._prepare_targets(y_arr), self.predict(X))

    # -- introspection / persistence ----------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "seed": self.seed,
            **self.factory_kwargs,
        }

    def set_params(self, **params) -> "BaseFlaxEstimator":
        """sklearn contract: unknown keys are factory hyperparameters, routed
        into ``factory_kwargs`` so the next ``fit`` actually uses them."""
        for key in ("kind", "batch_size", "epochs", "seed"):
            if key in params:
                setattr(self, key, params.pop(key))
        self.factory_kwargs.update(params)
        return self

    # -- pickling: drop compiled closures, keep pure state -------------------
    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["_spec"] = None
        state["_predict_jit"] = None
        if self.params_ is not None:
            state["params_"] = jax.device_get(self.params_)
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        if self.params_ is not None:
            self._spec = self._make_spec(self.n_features_, self.n_features_out_)
            self.params_ = jax.tree_util.tree_map(jnp.asarray, self.params_)
            self._predict_jit = self._build_predict_jit()

    def get_metadata(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {
            "type": type(self).__name__,
            "kind": self.kind,
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "parameters": dict(self.factory_kwargs),
        }
        if self.params_ is not None:
            meta.update(
                {
                    "history": {"loss": self.history_},
                    "architecture": self._spec.config,
                    "fit_duration_s": self.fit_duration_,
                    "num_parameters": int(
                        sum(p.size for p in jax.tree_util.tree_leaves(self.params_))
                    ),
                }
            )
        return meta

    def get_state(self) -> Dict[str, Any]:
        self._check_fitted()
        return {
            "params": jax.device_get(self.params_),
            "n_features": self.n_features_,
            "n_features_out": self.n_features_out_,
            "history": self.history_,
            "fit_duration": self.fit_duration_,
        }

    def set_state(self, state: Dict[str, Any]) -> "BaseFlaxEstimator":
        self.n_features_ = int(state["n_features"])
        self.n_features_out_ = int(state["n_features_out"])
        self.history_ = list(state.get("history", []))
        self.fit_duration_ = state.get("fit_duration")
        self._spec = self._make_spec(self.n_features_, self.n_features_out_)
        self.params_ = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self._predict_jit = self._build_predict_jit()
        return self


class DenseAutoEncoder(BaseFlaxEstimator):
    """X→X reconstruction with a feedforward kind
    (reference: ``KerasAutoEncoder``)."""

    lookahead = None

    def __init__(self, kind: str = "feedforward_hourglass", **kwargs: Any):
        super().__init__(kind, **kwargs)


class LSTMAutoEncoder(BaseFlaxEstimator):
    """Window → window's own last row (reference: ``KerasLSTMAutoEncoder``).
    ``predict`` row ``j`` corresponds to input row ``j + lookback_window - 1``."""

    lookahead = 0

    def __init__(self, kind: str = "lstm_hourglass", **kwargs: Any):
        super().__init__(kind, **kwargs)


class LSTMForecast(BaseFlaxEstimator):
    """Window → the ``horizon``-th-ahead row (reference:
    ``KerasLSTMForecast`` is the ``horizon=1`` case; ``horizon=k`` is the
    direct multi-step forecast of BASELINE.md config 3). ``predict`` row
    ``j`` corresponds to input row ``j + lookback_window - 1 + horizon``."""

    lookahead = 1

    def __init__(
        self, kind: str = "lstm_symmetric", horizon: int = 1, **kwargs: Any
    ):
        super().__init__(kind, **kwargs)
        if int(horizon) < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        self.horizon = int(horizon)
        self.lookahead = self.horizon  # instance overrides the class contract

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {**super().get_params(deep), "horizon": self.horizon}

    def set_params(self, **params) -> "LSTMForecast":
        if "horizon" in params:
            horizon = int(params.pop("horizon"))
            if horizon < 1:  # same contract as __init__ — horizon=0 would
                # silently flip the estimator into reconstruction mode
                raise ValueError(f"horizon must be >= 1, got {horizon}")
            self.horizon = horizon
            self.lookahead = horizon
        return super().set_params(**params)


class MultiStepForecast(LSTMForecast):
    """JOINT multi-step forecast: window → ALL of rows ``t+1..t+horizon``
    predicted together (the other reading of BASELINE config 3's
    "multi-step horizon"; :class:`LSTMForecast` with ``horizon=k`` is the
    direct k-th-ahead variant). The model head emits ``horizon ×
    n_features`` values per window, trained against
    :func:`~gordo_components_tpu.ops.windowing.multi_step_targets`
    flattened to 2-D, so any zoo kind works unchanged. ``predict`` returns
    the flat ``(count, horizon·F)`` sklearn shape; :meth:`predict_steps`
    reshapes to ``(count, horizon, F)``.

    Standalone estimator (sklearn API): the diff-based anomaly head scores
    one row per timestamp, so it pairs with the direct-horizon forecasters,
    not this joint one — the fleet builder and serving engine reject it
    with a clear error instead of mis-scoring.
    """

    joint_horizon = True  # gates: fleet/_spec_for and the serving engine
    # reject this class with a clear error instead of mis-scoring

    def __init__(
        self, kind: str = "lstm_symmetric", horizon: int = 2, **kwargs: Any
    ):
        super().__init__(kind, horizon=horizon, **kwargs)

    def _prepare_targets(self, y: np.ndarray) -> np.ndarray:
        stacked = np.asarray(
            windowing.multi_step_targets(y, self.lookback_window, self.horizon)
        )  # (count, horizon, F)
        return stacked.reshape(stacked.shape[0], -1)

    def _make_spec(self, n_features: int, n_features_out: int):
        # widen the head: joint horizon = horizon × target width outputs
        return super()._make_spec(n_features, n_features_out * self.horizon)

    def predict_steps(self, X) -> np.ndarray:
        """``(count, horizon, F)`` view of :meth:`predict` — step ``s`` of
        row ``j`` forecasts input row ``j + lookback_window + s``."""
        flat = self.predict(X)
        return flat.reshape(flat.shape[0], self.horizon, -1)


class PatchTSTAutoEncoder(LSTMAutoEncoder):
    """Window → window's own last row via the PatchTST transformer kind —
    the rebuild's new model family (BASELINE.md config 5); same windowing
    contract as :class:`LSTMAutoEncoder`."""

    def __init__(self, kind: str = "patchtst", **kwargs: Any):
        # the estimator's windowing must match the factory's default, or an
        # unspecified lookback_window would window rows of length 1
        kwargs.setdefault("lookback_window", 32)
        super().__init__(kind, **kwargs)


class PatchTSTForecast(LSTMForecast):
    """Window → next row via the PatchTST transformer kind."""

    def __init__(self, kind: str = "patchtst", **kwargs: Any):
        kwargs.setdefault("lookback_window", 32)
        super().__init__(kind, **kwargs)


# Aliases so ported reference configs resolve (the serializer rewrites
# `gordo_components.model.models.X` → this module).
KerasAutoEncoder = DenseAutoEncoder
KerasLSTMAutoEncoder = LSTMAutoEncoder
KerasLSTMForecast = LSTMForecast
