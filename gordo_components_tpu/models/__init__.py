"""Model zoo: the reference's Keras estimators re-designed as Flax modules
trained by pure, jittable optax steps.

Reference parity map (``gordo_components/model/`` [UNVERIFIED — empty
reference mount, path-level citations only]):

- ``KerasAutoEncoder``      → :class:`DenseAutoEncoder`
- ``KerasLSTMAutoEncoder``  → :class:`LSTMAutoEncoder`
- ``KerasLSTMForecast``     → :class:`LSTMForecast`

The original class names are importable aliases so ported fleet configs that
reference ``gordo_components.model.models.KerasAutoEncoder`` resolve after a
single module-path rewrite (the serializer applies it automatically).
"""

from .base import GordoBase
from .register import register_model_factory, get_factory, list_kinds
from .models import (
    BaseFlaxEstimator,
    DenseAutoEncoder,
    LSTMAutoEncoder,
    LSTMForecast,
    MultiStepForecast,
    PatchTSTAutoEncoder,
    PatchTSTForecast,
    KerasAutoEncoder,
    KerasLSTMAutoEncoder,
    KerasLSTMForecast,
)

# import for the registration side effects — every factory registers its kind
from .factories import feedforward, lstm, transformer  # noqa: F401

__all__ = [
    "GordoBase",
    "register_model_factory",
    "get_factory",
    "list_kinds",
    "BaseFlaxEstimator",
    "DenseAutoEncoder",
    "LSTMAutoEncoder",
    "LSTMForecast",
    "MultiStepForecast",
    "PatchTSTAutoEncoder",
    "PatchTSTForecast",
    "KerasAutoEncoder",
    "KerasLSTMAutoEncoder",
    "KerasLSTMForecast",
]
