"""Flax modules for the model zoo.

These replace the Keras graphs the reference's factories build
(``gordo_components/model/factories/feedforward_autoencoder.py`` and
``lstm_autoencoder.py`` [UNVERIFIED]). TPU notes:

- ``compute_dtype`` defaults to float32 but the bench configs flip it to
  bfloat16: params stay float32 (``param_dtype``), activations/matmuls run
  on the MXU in bf16, and the final output is cast back to float32 so losses
  and anomaly scores keep full precision.
- The LSTM stack uses ``nn.RNN`` (``lax.scan`` over time) — sequence lengths
  here are lookback windows of order 10², so the scan is short and every
  per-step matmul is batched across the window batch.
- Everything is shape-static and side-effect free: the same ``apply`` is
  used single-model, ``vmap``-ed across a fleet axis, and ``shard_map``-ed
  over a mesh without change.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

_ACTIVATIONS: dict = {
    "linear": lambda x: x,
    "tanh": nn.tanh,
    "relu": nn.relu,
    "sigmoid": nn.sigmoid,
    "elu": nn.elu,
    "selu": nn.selu,
    "softplus": nn.softplus,
    "softmax": nn.softmax,
    "gelu": nn.gelu,
    "swish": nn.swish,
}


def activation(name: str) -> Callable:
    """Resolve a Keras-style activation name (parity: factory ``*_func``
    hyperparams take the same strings ported configs already use)."""
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; supported: {sorted(_ACTIVATIONS)}"
        ) from None


def resolve_dtype(dtype: Any):
    if isinstance(dtype, str):
        return jnp.dtype(dtype)
    return dtype


class DenseAutoencoderModule(nn.Module):
    """Encoder/decoder MLP: ``(batch, F) → (batch, F_out)``.

    Mirrors the reference's ``feedforward_model`` Keras graph: Dense layers of
    ``encoding_dims`` then ``decoding_dims`` with per-layer activations, and a
    final Dense to ``n_features_out`` with ``out_func``.
    """

    encoding_dims: Sequence[int]
    decoding_dims: Sequence[int]
    n_features_out: int
    encoding_funcs: Sequence[str]
    decoding_funcs: Sequence[str]
    out_func: str = "linear"
    compute_dtype: Any = "float32"

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        dtype = resolve_dtype(self.compute_dtype)
        h = x.astype(dtype)
        for dim, func in zip(self.encoding_dims, self.encoding_funcs):
            h = activation(func)(nn.Dense(dim, dtype=dtype)(h))
        for dim, func in zip(self.decoding_dims, self.decoding_funcs):
            h = activation(func)(nn.Dense(dim, dtype=dtype)(h))
        out = activation(self.out_func)(nn.Dense(self.n_features_out, dtype=dtype)(h))
        return out.astype(jnp.float32)


class LSTMModule(nn.Module):
    """Stacked LSTM over a lookback window: ``(batch, L, F) → (batch, F_out)``.

    Mirrors the reference's ``lstm_model`` Keras graph: LSTM layers of
    ``units`` (full sequences between layers), inter-layer dropout, then a
    Dense head on the final timestep's hidden state with ``out_func`` — the
    same graph serves reconstruction and forecast; only the target differs
    (the off-by-one contract in :mod:`gordo_components_tpu.ops.windowing`).
    """

    units: Sequence[int]
    n_features_out: int
    funcs: Sequence[str]
    dropout: float = 0.0
    recurrent_dropout: float = 0.0  # accepted for config parity; not applied
    out_func: str = "linear"
    compute_dtype: Any = "float32"

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        dtype = resolve_dtype(self.compute_dtype)
        h = x.astype(dtype)
        for i, (n_units, func) in enumerate(zip(self.units, self.funcs)):
            cell = nn.OptimizedLSTMCell(
                n_units, activation_fn=activation(func), dtype=dtype
            )
            h = nn.RNN(cell)(h)
            if self.dropout > 0.0:
                h = nn.Dropout(rate=self.dropout)(h, deterministic=deterministic)
        last = h[:, -1, :]
        out = activation(self.out_func)(
            nn.Dense(self.n_features_out, dtype=dtype)(last)
        )
        return out.astype(jnp.float32)
