"""The compiled fleet-training program.

One machine's ENTIRE build — input/target scaler fit, windowing,
TimeSeriesSplit-style cross-validation, error-scaler fit on out-of-fold
residuals, final fit — is a single pure function of
``(X, y, w, key) → MachineResult``. :func:`train_fleet_arrays` ``vmap``s it
over a stacked machine axis and shards that axis over a mesh: the
reference's N Argo pods become one XLA program (SURVEY.md §2.2, §4.1).

Static-shape strategy (the "hard part" SURVEY.md §8 calls out):

- machines in a bucket share (rows N, features F, targets T, architecture);
  shorter machines are padded with zero-weight rows, and the bucket's
  machine count is padded to a multiple of the mesh size with zero-weight
  machines — masks make padding exact, not approximate;
- CV folds are *weight masks* over the padded row axis, not array slices,
  so one compilation serves every machine regardless of its true row count
  (fold boundaries follow sklearn TimeSeriesSplit on each machine's REAL
  samples — :func:`timeseries_fold_masks` computes them traced from the
  weight vector, so padding never shifts a boundary);
- the per-fold fits reuse the single-machine jittable fit program
  (:func:`gordo_components_tpu.models.train.make_fit_fn`) unchanged — the
  fleet engine is a transform over the single path, not a fork of it.

Residual semantics: the model trains in scaled space; predictions are
inverse-transformed and residuals computed in RAW target units, matching the
reference's canonical ``DiffBasedAnomalyDetector(TransformedTargetRegressor
(Pipeline([scaler, model])), MinMaxScaler())`` configuration.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.train import FitResult, make_fit_fn, make_predict_fn
from ..observability.registry import REGISTRY
from ..ops import windowing
from ..ops.scaling import ScalerParams
from ..utils.cache import cached as _cached  # shared FIFO program memo
from .mesh import fleet_sharding, pad_to_multiple

_EPS = 1e-12
logger = logging.getLogger(__name__)

_M_FLEET_PROGRAMS = REGISTRY.counter(
    "gordo_fleet_programs_built_total",
    "Fleet training programs constructed (jit = traced wrapper, compile "
    "deferred to first call; aot = fleet_executable, compile paid here)",
    labels=("kind",),
)
_M_FLEET_COMPILE_SECONDS = REGISTRY.histogram(
    "gordo_fleet_compile_seconds",
    "AOT lower+compile duration of fleet executables — the dominant "
    "cold-build cost on TPU (tens of seconds per bucket shape)",
    buckets=(0.1, 0.5, 1, 5, 10, 30, 60, 120, 300, 600, float("inf")),
)


class FleetSpec(NamedTuple):
    """Static (compile-time) description of one bucket's machines."""

    module: Any  # flax module — shared architecture
    optimizer: Any  # optax transform
    loss: str
    lookahead: Optional[int]  # None=flat, 0=reconstruction, k>=1=k-step forecast
    lookback_window: int
    scaler: str  # "minmax" | "standard" | "none"
    feature_range: Tuple[float, float]
    batch_size: int
    epochs: int
    n_splits: int  # 0 disables CV (error scaler fits on train residuals)
    use_dropout: bool = False
    # True ⇔ the config wraps the model in a TransformedTargetRegressor:
    # targets train scaled and predictions are inverse-transformed. False
    # (plain Pipeline / bare estimator) ⇔ targets stay raw, matching the
    # single-machine path where Pipeline.fit passes y through untransformed.
    scale_targets: bool = True
    # ("standard" only) (with_mean, with_std)
    scaler_options: Tuple[bool, bool] = (True, True)
    # the TransformedTargetRegressor's own transformer — independent of the
    # input scaler (a config may scale targets but not inputs or vice versa)
    target_scaler: str = "minmax"
    target_feature_range: Tuple[float, float] = (0.0, 1.0)
    target_scaler_options: Tuple[bool, bool] = (True, True)
    # True: the K CV-fold fits and the final fit — independent programs with
    # identical shapes — run as ONE vmapped batched fit instead of a
    # sequential lax.scan, cutting the program's sequential depth by (K+1)×
    # at the price of (K+1)× the training-step activation memory. The right
    # default on a TPU whose per-machine models are tiny (the fleet design
    # point); builders flip it off for memory-constrained configs (remat
    # models at plant scale). Numerically equivalent to the scan path up to
    # XLA reduction-order float noise — parity pinned by
    # tests/test_fleet.py::test_cv_parallel_matches_scan.
    cv_parallel: bool = True
    # mini-batch steps inlined per iteration of the training scan
    # (lax.scan's unroll): tiny fleet models are dispatch-overhead-bound,
    # and unrolling lets XLA schedule several steps per dispatch. Pure
    # scheduling, numerics unchanged; compile time grows with the body, so
    # the default here is the safe 1 and _spec_for opts non-remat flat
    # buckets into 4 — independent of cv_parallel so an explicit override
    # of one never silently drags the other along. Windowed models never
    # unroll: their batch step already carries an inner time scan /
    # attention stack, and inlining copies of it is exactly what XLA:TPU's
    # optimization passes are superlinear in (measured r4: 28.7 s -> ~25
    # min for the 32-machine LSTM fleet compile).
    fit_unroll: int = 1
    # "memory profile is unconstrained" bit, set by _spec_for from the
    # model's remat request: predict-chunk widening keys off it (NOT off
    # the user-overridable cv_parallel, and NOT off fit_unroll, which
    # windowed models keep at 1 for compile-time reasons unrelated to
    # memory). Defaults to the safe narrow mode like fit_unroll — a spec
    # built without _spec_for must opt in, never inherit 4x-wide predict
    # chunks it didn't budget for.
    widen_predict: bool = False


class MachineBatch(NamedTuple):
    """Stacked per-machine data: X (M,N,F) raw, y (M,N,T) raw, w (M,N) row
    weights (0 on padding), keys (M, key_width) uint32 raw PRNG keys —
    ``key_width`` is impl-dependent (threefry 2, rbg 4); build keys with
    ``jax.random.split`` and size avatars via :func:`prng_key_width`."""

    X: jnp.ndarray
    y: jnp.ndarray
    w: jnp.ndarray
    keys: jnp.ndarray


class MachineResult(NamedTuple):
    params: Any  # model params (stacked under vmap)
    input_scaler: ScalerParams  # (F,)
    target_scaler: ScalerParams  # (T,)
    error_scaler: ScalerParams  # (T,) minmax over |raw residuals|
    loss_history: jnp.ndarray  # (epochs,)
    # (n_splits, len(FLEET_CV_METRICS)) masked fold metrics (or (0, 4))
    cv_scores: jnp.ndarray
    tag_thresholds: jnp.ndarray  # (T,) 99th pct of scaled residuals
    total_threshold: jnp.ndarray  # () 99th pct of residual L2 norms


FleetResult = MachineResult  # stacked variant returned by train_fleet_arrays


def _masked_minmax(x, w, feature_range) -> ScalerParams:
    lo, hi = feature_range
    mask = (w > 0)[:, None]
    xmin = jnp.min(jnp.where(mask, x, jnp.inf), axis=0)
    xmax = jnp.max(jnp.where(mask, x, -jnp.inf), axis=0)
    # all-padding safety: no real rows → identity scaler
    xmin = jnp.where(jnp.isfinite(xmin), xmin, 0.0)
    xmax = jnp.where(jnp.isfinite(xmax), xmax, 1.0)
    span = xmax - xmin
    scale = (hi - lo) / jnp.where(span < _EPS, 1.0, span)
    return ScalerParams(scale=scale, offset=lo - xmin * scale)


def _masked_standard(x, w, with_mean: bool = True, with_std: bool = True) -> ScalerParams:
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    mean = jnp.sum(x * w[:, None], axis=0) / wsum
    var = jnp.sum((x - mean) ** 2 * w[:, None], axis=0) / wsum
    std = jnp.sqrt(var)
    scale = (
        1.0 / jnp.where(std < _EPS, 1.0, std)
        if with_std
        else jnp.ones_like(std)
    )
    offset = -mean * scale if with_mean else jnp.zeros_like(mean)
    return ScalerParams(scale=scale, offset=offset)


def _fit_scaler(kind: str, options, feature_range, x, w) -> ScalerParams:
    if kind == "minmax":
        return _masked_minmax(x, w, feature_range)
    if kind == "standard":
        with_mean, with_std = options
        return _masked_standard(x, w, with_mean, with_std)
    if kind == "none":
        n = x.shape[1]
        return ScalerParams(scale=jnp.ones(n), offset=jnp.zeros(n))
    raise ValueError(f"Unknown scaler kind {kind!r}")


# column order of the per-fold metric vector the compiled program emits —
# the same four metrics (sklearn ``uniform_average`` semantics) the
# single-machine builder records via models.metrics.METRICS, so fleet and
# single builds expose identical CV metadata keys
FLEET_CV_METRICS = (
    "explained_variance_score",
    "r2_score",
    "mean_squared_error",
    "mean_absolute_error",
)


def _masked_metrics(y, pred, w) -> jnp.ndarray:
    """Weighted fold metrics in :data:`FLEET_CV_METRICS` order, NaN when the
    fold has no real rows (empty folds report as missing, never as a fake
    perfect score). Per-output scores average uniformly across outputs,
    matching sklearn ``multioutput="uniform_average"`` (pinned against
    sklearn by tests/test_fleet_parity.py)."""
    w_total = jnp.sum(w)
    wsum = jnp.maximum(w_total, 1.0)
    wcol = w[:, None]
    diff = y - pred
    # explained variance: 1 - Var(residual)/Var(y)
    dmean = jnp.sum(diff * wcol, axis=0) / wsum
    dvar = jnp.sum((diff - dmean) ** 2 * wcol, axis=0) / wsum
    ymean = jnp.sum(y * wcol, axis=0) / wsum
    yvar = jnp.sum((y - ymean) ** 2 * wcol, axis=0) / wsum
    ev = 1.0 - dvar / jnp.where(yvar < _EPS, 1.0, yvar)
    ev = jnp.mean(jnp.where(yvar < _EPS, jnp.where(dvar < _EPS, 1.0, 0.0), ev))
    # r2: 1 - SS_res/SS_tot (not mean-adjusted residuals)
    ss_res = jnp.sum(diff**2 * wcol, axis=0) / wsum
    r2 = 1.0 - ss_res / jnp.where(yvar < _EPS, 1.0, yvar)
    r2 = jnp.mean(jnp.where(yvar < _EPS, jnp.where(ss_res < _EPS, 1.0, 0.0), r2))
    mse = jnp.mean(jnp.sum(diff**2 * wcol, axis=0) / wsum)
    mae = jnp.mean(jnp.sum(jnp.abs(diff) * wcol, axis=0) / wsum)
    scores = jnp.stack([ev, r2, mse, mae])
    return jnp.where(w_total > 0, scores, jnp.nan)


def timeseries_fold_masks(wt: jnp.ndarray, n_splits: int):
    """sklearn ``TimeSeriesSplit`` fold masks computed per machine on its
    REAL samples (``wt > 0``), traced — one compilation serves machines of
    any true length inside a padded bucket.

    sklearn's rule for ``n`` samples and ``k`` splits: ``test_size = n //
    (k+1)``; split ``i`` tests ranks ``[n-(k-i)*ts, n-(k-i-1)*ts)`` and
    trains on every earlier rank (``sklearn.model_selection.TimeSeriesSplit``
    semantics — parity pinned by tests/test_fleet_parity.py). Masks are in
    rank space over real samples, so padding anywhere on the axis (leading
    row alignment, trailing batch fill) never shifts fold boundaries."""
    real = (wt > 0).astype(jnp.float32)
    n_real = jnp.sum(real).astype(jnp.int32)
    rank = jnp.cumsum(real) - real  # 0-based rank among real samples
    test_size = n_real // (n_splits + 1)
    masks = []
    for i in range(n_splits):
        test_start = n_real - (n_splits - i) * test_size
        test_end = n_real - (n_splits - i - 1) * test_size
        train_mask = real * (rank < test_start)
        test_mask = real * (rank >= test_start) * (rank < test_end)
        masks.append((train_mask, test_mask))
    return masks


def make_machine_program(
    spec: FleetSpec, n_rows: int, n_features: int, n_targets: int
) -> Callable:
    """Pure fn ``(X (N,F), y (N,T), w (N,), key) → MachineResult`` — the
    whole per-machine build as one traceable program."""

    apply_fn = spec.module.apply
    fit_unroll = spec.fit_unroll
    fit_fn = make_fit_fn(
        apply_fn,
        spec.optimizer,
        loss=spec.loss,
        batch_size=spec.batch_size,
        epochs=spec.epochs,
        use_dropout=spec.use_dropout,
        unroll=fit_unroll,
    )
    predict_fn = make_predict_fn(apply_fn)

    L = spec.lookback_window
    la = spec.lookahead
    if la is None:
        n_samples = n_rows
    else:
        n_samples = n_rows - L + 1 - la
        if n_samples < spec.batch_size:
            raise ValueError(
                f"Bucket rows {n_rows} give {n_samples} windows "
                f"(< batch_size {spec.batch_size})"
            )
    padded = pad_to_multiple(n_samples, spec.batch_size)

    def prepare(Xs, ys, w):
        """Scaled rows → (inputs, targets, sample weights) padded to a whole
        number of batches. Windowing/targets delegate to
        :mod:`gordo_components_tpu.ops.windowing` — the off-by-one contract
        lives there, pinned by its golden tests, not re-derived here.

        For windowed models ``inputs`` is the window START INDEX vector,
        not materialized windows: batches gather their ``(batch, L, F)``
        windows from the scaled rows on the fly (see ``windowed_apply``
        below), so HBM holds ``(n_rows, F)`` instead of the L×-blown-up
        ``(n_windows, L, F)`` tensor — the enabler for plant-scale buckets
        (10k tags × L=32 windows would be ~1 GB per machine materialized).

        Row padding may sit ANYWHERE in the row axis (fold boundaries are
        computed on real-sample ranks, so placement is free): a window's
        weight is the MIN of its rows' weights times its target row's
        weight, so any window touching padding is masked out exactly.
        """
        if la is None:
            inputs, targets, wt = Xs, ys, w
        else:
            inputs = jnp.arange(n_samples)
            targets = (
                windowing.reconstruction_targets(ys, L)
                if la == 0
                else windowing.forecast_targets(ys, L, la)
            )
            target_idx = windowing.window_output_index(n_rows, L, la)
            window_w = windowing.sliding_windows(w[:, None], L, la)[:, :, 0]
            wt = jnp.min(window_w, axis=1) * w[target_idx]
        pad = padded - inputs.shape[0]
        if pad:
            inputs = jnp.pad(inputs, ((0, pad),) + ((0, 0),) * (inputs.ndim - 1))
            targets = jnp.pad(targets, ((0, pad), (0, 0)))
            wt = jnp.pad(wt, (0, pad))
        return inputs, targets, wt

    sample_shape = (1, n_features) if la is None else (1, L, n_features)

    def program(X, y, w, key) -> MachineResult:
        sx = _fit_scaler(spec.scaler, spec.scaler_options, spec.feature_range, X, w)
        if spec.scale_targets:
            # the TransformedTargetRegressor's transformer — its own kind,
            # independent of the input scaler
            sy = _fit_scaler(
                spec.target_scaler,
                spec.target_scaler_options,
                spec.target_feature_range,
                y,
                w,
            )
        else:
            # no TransformedTargetRegressor in the config: the model trains
            # against raw targets (Pipeline.fit passes y through untouched)
            sy = ScalerParams(
                scale=jnp.ones(n_targets), offset=jnp.zeros(n_targets)
            )
        Xs = X * sx.scale + sx.offset
        ys = y * sy.scale + sy.offset
        inputs, targets, wt = prepare(Xs, ys, w)
        raw_targets = (targets - sy.offset) / sy.scale

        if la is None:
            fit_local = fit_fn
            predict_all = lambda params: predict_fn(params, inputs)  # noqa: E731
        else:

            def windowed_apply(variables, starts, **kwargs):
                # (batch,) start indices → gather (batch, L, F) from the
                # scaled rows; grads flow only into params, so this is pure
                # data movement XLA fuses into the model's first op
                return apply_fn(
                    variables, windowing.gather_windows(Xs, starts, L), **kwargs
                )

            fit_local = make_fit_fn(
                windowed_apply,
                spec.optimizer,
                loss=spec.loss,
                batch_size=spec.batch_size,
                epochs=spec.epochs,
                use_dropout=spec.use_dropout,
                unroll=fit_unroll,
            )
            windowed_predict = make_predict_fn(windowed_apply)

            # prediction has no optimizer state or backward pass, so its
            # chunks can be wider than the training batch: fold up to 4
            # training batches into one forward call (largest factor of the
            # step count), cutting the predict pass's sequential ticks by
            # that factor. The bound is RELATIVE to the training step, not
            # absolute, because predict_all runs under the same vmaps
            # (machines, and K+1 fits in cv_parallel mode) as the training
            # step: a NON-remat training step holds ~3x its forward
            # activations (fwd + bwd + grads), so a 4x-wide forward-only
            # chunk peaks at ~4/3 of the training step's memory under ANY
            # vmap multiplication. That argument does NOT hold for remat
            # buckets (their step peak is deliberately small), so the
            # widening keys off spec.widen_predict — the bit _spec_for
            # sets from the model's memory profile — NOT off the
            # user-overridable cv_parallel, and not off fit_unroll (which
            # windowed models keep at 1 for XLA:TPU compile-time reasons
            # unrelated to memory). Values are unchanged — prediction is
            # per-window.
            steps = padded // spec.batch_size
            if spec.widen_predict:
                predict_width = spec.batch_size * next(
                    k for k in range(min(4, steps), 0, -1) if steps % k == 0
                )
            else:
                predict_width = spec.batch_size

            def predict_all(params):
                # bounded-memory full prediction: sequential widened chunks,
                # so peak HBM per machine stays one (width, L, F) gather
                chunks = inputs.reshape(-1, predict_width)
                preds = jax.lax.map(
                    lambda sb: windowed_predict(params, sb), chunks
                )
                return preds.reshape(padded, n_targets)

        keys = jax.random.split(key, spec.n_splits + 2)
        init_key, fit_key, fold_keys = keys[0], keys[1], keys[2:]
        params0 = spec.module.init(
            init_key, jnp.zeros(sample_shape, jnp.float32), deterministic=True
        )["params"]

        emin = jnp.full((n_targets,), jnp.inf)
        emax = jnp.full((n_targets,), -jnp.inf)
        n_points = raw_targets.shape[0]
        fold_masks = timeseries_fold_masks(wt, spec.n_splits)
        if spec.n_splits > 0:
            train_masks = jnp.stack([m[0] for m in fold_masks])
            test_masks = jnp.stack([m[1] for m in fold_masks])
        if spec.n_splits > 0 and spec.cv_parallel:
            # parallel CV: the K fold fits and the final fit are independent
            # programs with identical shapes, so ONE vmapped fit of K+1
            # weight vectors replaces K+1 sequential fits — sequential depth
            # drops to a single fit's epochs×batches at (K+1)× step memory
            # (see FleetSpec.cv_parallel). Per-fit keys match the scan path
            # exactly, so both modes train identical models.
            all_w = jnp.concatenate([train_masks * wt[None, :], wt[None, :]])
            all_keys = jnp.concatenate([fold_keys, fit_key[None]])
            fits = jax.vmap(
                lambda wv, kv: fit_local(params0, inputs, targets, wv, kv)
            )(all_w, all_keys)
            preds = jax.vmap(predict_all)(fits.params)  # (K+1, P, T)
            preds_raw = (preds - sy.offset) / sy.scale
            errs_all = jnp.abs(raw_targets[None] - preds_raw)
            fold_errors, err_final = errs_all[:-1], errs_all[-1]
            # rank-space folds guarantee a nonempty train region whenever a
            # test region is nonempty; machines too short for any fold
            # (n_real < n_splits+1) get empty test masks here and fall back
            # to final-model residuals below
            fold_test_masks = test_masks * wt[None, :]
            fmask = (fold_test_masks > 0)[:, :, None]
            emin = jnp.min(
                jnp.where(fmask, fold_errors, jnp.inf), axis=(0, 1)
            )
            emax = jnp.max(
                jnp.where(fmask, fold_errors, -jnp.inf), axis=(0, 1)
            )
            cv_scores = jax.vmap(_masked_metrics, in_axes=(None, 0, 0))(
                raw_targets, preds_raw[:-1], fold_test_masks
            )
            final = FitResult(
                params=jax.tree_util.tree_map(lambda a: a[-1], fits.params),
                loss_history=fits.loss_history[-1],
            )
        else:
            if spec.n_splits > 0:
                # sequential CV: ONE fold fit in the compiled graph, scanned
                # over the stacked masks (folds share every shape) — an
                # unrolled Python loop would inline n_splits copies of the
                # whole training program and multiply XLA compile time
                # accordingly; vs cv_parallel this holds step memory at 1×,
                # the right trade for plant-scale remat configs

                def fold_step(carry, xs):
                    emin, emax = carry
                    train_mask, test_mask, fold_key = xs
                    res = fit_local(
                        params0, inputs, targets, wt * train_mask, fold_key
                    )
                    pred = predict_all(res.params)
                    pred_raw = (pred - sy.offset) / sy.scale
                    err = jnp.abs(raw_targets - pred_raw)
                    # rank-space folds guarantee a nonempty train region
                    # whenever a test region is nonempty; machines too short
                    # for any fold (n_real < n_splits+1) get empty test masks
                    # here and fall back to final-model residuals below
                    wtest = wt * test_mask
                    mask = (wtest > 0)[:, None]
                    emin = jnp.minimum(
                        emin, jnp.min(jnp.where(mask, err, jnp.inf), axis=0)
                    )
                    emax = jnp.maximum(
                        emax, jnp.max(jnp.where(mask, err, -jnp.inf), axis=0)
                    )
                    scores = _masked_metrics(raw_targets, pred_raw, wtest)
                    return (emin, emax), (scores, err, wtest)

                (emin, emax), (cv_scores, fold_errors, fold_test_masks) = (
                    jax.lax.scan(
                        fold_step,
                        (emin, emax),
                        (train_masks, test_masks, fold_keys),
                    )
                )
            else:
                cv_scores = jnp.zeros((0, len(FLEET_CV_METRICS)))
                fold_errors = jnp.zeros((0, n_points, n_targets))
                fold_test_masks = jnp.zeros((0, n_points))

            final = fit_local(params0, inputs, targets, wt, fit_key)

            # final-model residuals over all real rows: the error-scaler
            # source when CV is off, and the per-machine fallback when no CV
            # fold covered this machine's data (short machine in a tall
            # bucket)
            pred_final = predict_all(final.params)
            pred_final_raw = (pred_final - sy.offset) / sy.scale
            err_final = jnp.abs(raw_targets - pred_final_raw)
        mask_final = (wt > 0)[:, None]
        fmin = jnp.min(jnp.where(mask_final, err_final, jnp.inf), axis=0)
        fmax = jnp.max(jnp.where(mask_final, err_final, -jnp.inf), axis=0)

        use_cv = jnp.sum(fold_test_masks) > 0
        emin = jnp.where(use_cv, emin, fmin)
        emax = jnp.where(use_cv, emax, fmax)
        emin = jnp.where(jnp.isfinite(emin), emin, 0.0)
        emax = jnp.where(jnp.isfinite(emax), emax, 1.0)
        span = emax - emin
        e_scale = 1.0 / jnp.where(span < _EPS, 1.0, span)
        error_scaler = ScalerParams(scale=e_scale, offset=-emin * e_scale)

        # thresholds: 99th percentile of scaled residuals — out-of-fold when
        # CV covered this machine, final-model residuals otherwise
        errs = jnp.concatenate([fold_errors, err_final[None]])  # (K+1, P, T)
        fallback_mask = wt * jnp.where(use_cv, 0.0, 1.0)
        masks = jnp.concatenate(
            [fold_test_masks, fallback_mask[None]]
        )  # (K+1, P)
        scaled = errs * error_scaler.scale + error_scaler.offset
        scaled = jnp.where((masks > 0)[:, :, None], scaled, jnp.nan)
        tag_thresholds = jnp.nan_to_num(
            jnp.nanpercentile(scaled.reshape(-1, n_targets), 99, axis=0)
        )
        norms = jnp.linalg.norm(
            jnp.nan_to_num(scaled), axis=-1
        ) + jnp.where(masks > 0, 0.0, jnp.nan)
        total_threshold = jnp.nan_to_num(jnp.nanpercentile(norms, 99))

        return MachineResult(
            params=final.params,
            input_scaler=sx,
            target_scaler=sy,
            error_scaler=error_scaler,
            loss_history=final.loss_history,
            cv_scores=cv_scores,
            tag_thresholds=tag_thresholds,
            total_threshold=total_threshold,
        )

    return program


_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 128  # distinct (spec, shape, mesh) programs kept live


def fleet_program(
    spec: FleetSpec,
    n_rows: int,
    n_features: int,
    n_targets: int,
    mesh=None,
    donate: bool = False,
):
    """The jitted vmap-over-machines program for one bucket shape, cached so
    repeated calls with the same spec/shape reuse the traced+compiled
    executable (``jax.jit`` keys on function identity — without this cache
    every ``train_fleet_arrays`` call would re-trace).

    ``donate=True`` donates the batch buffers to the executable: XLA may
    reuse their HBM for intermediates, roughly halving peak memory for
    plant-scale buckets whose ``(M, N, F)`` data approaches the chip limit.
    The inputs are consumed — callers must not touch them after the call
    (the builder's slice loop never does; benchmarks re-execute on the same
    buffers and must keep the default)."""

    def build():
        _M_FLEET_PROGRAMS.labels("jit").inc()
        program = jax.vmap(
            make_machine_program(spec, n_rows, n_features, n_targets)
        )
        donate_argnums = (0, 1, 2, 3) if donate else ()
        if mesh is None:
            return jax.jit(program, donate_argnums=donate_argnums)
        shard = fleet_sharding(mesh)
        return jax.jit(
            program,
            in_shardings=(shard, shard, shard, shard),
            out_shardings=shard,
            donate_argnums=donate_argnums,
        )

    key = (spec, n_rows, n_features, n_targets, mesh, donate)
    return _cached(_PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build)


_EXEC_CACHE: dict = {}
_EXEC_CACHE_MAX = 64


def prng_key_width() -> int:
    """Trailing uint32 width of a raw PRNG key under the active impl
    (threefry: 2, rbg: 4). AOT avatars must advertise the width
    ``jax.random.split`` actually produces, or the strict executable
    rejects every batch under a non-default ``jax_default_prng_impl``
    (ADVICE r2)."""
    return int(jax.eval_shape(jax.random.PRNGKey, 0).shape[-1])


def fleet_executable(
    spec: FleetSpec,
    n_machines: int,
    n_rows: int,
    n_features: int,
    n_targets: int,
    mesh=None,
    donate: bool = False,
):
    """AOT-compiled fleet executable + its input formats, cached by
    (spec, shape, mesh).

    Why AOT: ``compiled.input_formats`` exposes the exact device layouts
    (tiling) the executable expects, so callers can ``jax.device_put``
    ingest data straight into the right layout. Feeding plain host arrays
    or default-layout device arrays instead makes EVERY execution pay a
    device-side relayout — measured at ~200 ms for an 18 MB batch on v5e
    vs 0.7 ms program execution, i.e. the relayout would dominate the
    fleet hot loop ~300×.

    Returns ``(compiled, formats)``; ``formats`` is ``None`` when the
    backend has no layout API (the call path then falls back to plain
    ``device_put``).
    """
    def build():
        program = fleet_program(
            spec, n_rows, n_features, n_targets, mesh=mesh, donate=donate
        )
        avatars = (
            jax.ShapeDtypeStruct((n_machines, n_rows, n_features), jnp.float32),
            jax.ShapeDtypeStruct((n_machines, n_rows, n_targets), jnp.float32),
            jax.ShapeDtypeStruct((n_machines, n_rows), jnp.float32),
            jax.ShapeDtypeStruct((n_machines, prng_key_width()), jnp.uint32),
        )
        compile_started = time.perf_counter()
        compiled = program.lower(*avatars).compile()
        _M_FLEET_PROGRAMS.labels("aot").inc()
        _M_FLEET_COMPILE_SECONDS.observe(
            time.perf_counter() - compile_started
        )
        try:
            formats = compiled.input_formats[0]
        except (AttributeError, TypeError, IndexError):
            formats = None
        return compiled, formats

    key = (spec, n_machines, n_rows, n_features, n_targets, mesh, donate)
    return _cached(_EXEC_CACHE, _EXEC_CACHE_MAX, key, build)


def peek_fleet_executable(
    spec: FleetSpec,
    n_machines: int,
    n_rows: int,
    n_features: int,
    n_targets: int,
    mesh=None,
    donate: bool = False,
):
    """The cached ``(compiled, formats)`` for this shape, or ``None`` —
    NEVER compiles. For the ingest prefetcher: it places the next slice's
    batch layout-matched only when the program already exists, because a
    worker-side compile would race the unlocked program cache with the
    main thread and contend the (single) device compile slot."""
    key = (spec, n_machines, n_rows, n_features, n_targets, mesh, donate)
    try:
        return _EXEC_CACHE.get(key)
    except TypeError:
        return None


def put_fleet_batch(batch: MachineBatch, formats=None) -> MachineBatch:
    """Device-place a batch, layout-matched when ``formats`` is given (see
    :func:`fleet_executable`). The returned batch's arrays are device
    arrays; transfers are issued immediately so a caller can overlap them
    with an in-flight execution before blocking."""
    keys = batch.keys
    if jax.dtypes.issubdtype(getattr(keys, "dtype", None), jax.dtypes.prng_key):
        keys = jax.random.key_data(keys)  # typed keys → raw uint32 pairs
    args = tuple(
        # host-side cast on mismatch: jnp.asarray would device-place in the
        # DEFAULT layout first, re-paying the relayout this path avoids
        a if getattr(a, "dtype", None) == d else np.asarray(a, d)
        for a, d in zip(
            (batch.X, batch.y, batch.w, keys),
            (jnp.float32, jnp.float32, jnp.float32, jnp.uint32),
        )
    )
    if formats is None:
        placed = [jax.device_put(a) for a in args]
    else:
        placed = [jax.device_put(a, f) for a, f in zip(args, formats)]
    return MachineBatch(*placed)


def compiled_flops(compiled) -> Optional[float]:
    """XLA-reported flops of a compiled executable, or ``None`` on backends
    without cost analysis. The one place that knows ``cost_analysis()``
    sometimes returns a list (its shape has changed across JAX versions) —
    bench.py and the accounting below share it instead of re-guessing."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        return float(analysis["flops"])
    except Exception:  # lint: allow-swallow(XLA cost introspection is optional; None is the documented unknown result)
        return None


def fleet_flops_accounting(
    spec: FleetSpec,
    n_machines: int,
    n_rows: int,
    n_features: int,
    n_targets: int,
) -> Optional[dict]:
    """Trip-count-adjusted FLOP accounting for the fleet program.

    XLA's ``cost_analysis()`` counts a ``lax.scan`` body ONCE regardless of
    trip count, so the whole fleet program's reported flops undercount the
    training loop by roughly ``n_fits × epochs × steps_per_epoch`` — on the
    round-4 TPU bench that made MFU look ~25× smaller than reality. This
    helper compiles the loop bodies standalone — the EXACT mini-batch train
    step (:func:`gordo_components_tpu.models.train.make_batch_step`, the
    same function ``make_fit_fn`` scans) and a batch-size-wide predict
    chunk — reads each one's XLA-reported flops, and multiplies by the
    Python-known trip counts from the program structure (no hand FLOP
    model anywhere). ``predict_chunks`` counts BATCH-SIZE-EQUIVALENT
    chunks, not literal ``lax.map`` iterations: the program may execute
    wider predict chunks (see ``predict_width`` in
    :func:`make_machine_program`), and the total is invariant because
    per-chunk flops are linear in width.

    The total is a slight UNDERcount still: scaler fits, fold masks,
    thresholds, and metrics (all O(rows×tags) elementwise, no matmuls) are
    excluded rather than risk double-counting the one copy the whole-program
    number already includes. Windowed models are probed on materialized
    ``(batch, L, F)`` windows — the production gather adds zero flops.

    Returns ``None`` when the backend exposes no cost analysis, else::

        {"train_step_flops": ..., "train_steps": ...,
         "predict_chunk_flops": ..., "predict_chunks": ..., "total_flops": ...}
    """
    from ..models.train import make_batch_step

    L, la = spec.lookback_window, spec.lookahead
    if la is None:
        n_samples = n_rows
        x_elem = (n_features,)
    else:
        n_samples = n_rows - L + 1 - la
        x_elem = (L, n_features)
    padded = pad_to_multiple(n_samples, spec.batch_size)
    steps_per_epoch = padded // spec.batch_size
    n_fits = spec.n_splits + 1
    train_steps = n_fits * spec.epochs * steps_per_epoch
    predict_chunks = n_fits * steps_per_epoch

    try:
        apply_fn = spec.module.apply
        sample = jnp.zeros((1, *x_elem), jnp.float32)
        params_sd = jax.eval_shape(
            lambda k: spec.module.init(k, sample, deterministic=True)[
                "params"
            ],
            jax.random.PRNGKey(0),
        )
        opt_sd = jax.eval_shape(spec.optimizer.init, params_sd)

        def stack(tree):
            return jax.tree_util.tree_map(
                lambda s: jax.ShapeDtypeStruct(
                    (n_machines, *s.shape), s.dtype
                ),
                tree,
            )

        x_sd = jax.ShapeDtypeStruct(
            (n_machines, spec.batch_size, *x_elem), jnp.float32
        )
        y_sd = jax.ShapeDtypeStruct(
            (n_machines, spec.batch_size, n_targets), jnp.float32
        )
        w_sd = jax.ShapeDtypeStruct((n_machines, spec.batch_size), jnp.float32)
        k_sd = jax.ShapeDtypeStruct((n_machines, prng_key_width()), jnp.uint32)

        step = make_batch_step(
            apply_fn, spec.optimizer, loss=spec.loss,
            use_dropout=spec.use_dropout,
        )

        def machine_step(params, opt_state, x, y, w, key):
            (params, opt_state), _ = step((params, opt_state), (x, y, w, key))
            return params, opt_state

        train_compiled = (
            jax.jit(jax.vmap(machine_step))
            .lower(stack(params_sd), stack(opt_sd), x_sd, y_sd, w_sd, k_sd)
            .compile()
        )
        train_step_flops = compiled_flops(train_compiled)

        def machine_predict(params, x):
            return apply_fn({"params": params}, x, deterministic=True)

        predict_compiled = (
            jax.jit(jax.vmap(machine_predict))
            .lower(stack(params_sd), x_sd)
            .compile()
        )
        predict_chunk_flops = compiled_flops(predict_compiled)
    except Exception:
        # accounting is a measurement aid and must never fail a bench run —
        # but a silent None here would be indistinguishable from "backend
        # has no cost analysis", hiding real probe bugs until a one-shot
        # TPU run comes back without its MFU number. Log loudly instead.
        logger.warning(
            "fleet_flops_accounting probe failed; MFU will be unreported",
            exc_info=True,
        )
        return None
    if train_step_flops is None or predict_chunk_flops is None:
        return None  # backend without cost analysis (the graceful case)
    return {
        "train_step_flops": train_step_flops,
        "train_steps": train_steps,
        "predict_chunk_flops": predict_chunk_flops,
        "predict_chunks": predict_chunks,
        "total_flops": (
            train_step_flops * train_steps
            + predict_chunk_flops * predict_chunks
        ),
    }


def backend_supports_donation(mesh=None) -> bool:
    """Whether the target backend honors ``donate_argnums``. XLA:CPU does
    not — donated buffers are silently copied and every execution emits a
    ``Some donated buffers were not usable`` warning, drowning real signal
    in a full test run (VERDICT r3 #8) — so callers gate donation here."""
    device = mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
    return device.platform != "cpu"


def train_fleet_arrays(
    spec: FleetSpec,
    batch: MachineBatch,
    mesh=None,
    donate: bool = False,
) -> MachineResult:
    """Train a stacked bucket of machines; returns stacked results.

    With ``mesh``, the machine axis is sharded over it (machine count must
    be a multiple of the mesh size — pad with zero-weight machines) and XLA
    partitions the whole program; without, the vmapped program runs on the
    default device.

    Host arrays are device-placed layout-matched via the AOT executable
    (:func:`fleet_executable`); keys uint32 dtype aside, any float inputs
    are accepted as-is.

    ``donate=True`` lets XLA reuse the device-placed batch's HBM for
    intermediates (the placed copies are consumed; the caller's host
    arrays are untouched) — the peak-memory lever for plant-scale buckets;
    see :func:`fleet_program`. Ignored on backends without donation
    support (:func:`backend_supports_donation`).
    """
    donate = donate and backend_supports_donation(mesh)
    n_machines, n_rows, n_features = batch.X.shape
    n_targets = batch.y.shape[2]
    if mesh is not None and n_machines % mesh.size != 0:
        raise ValueError(
            f"Machine count {n_machines} must divide evenly over mesh size "
            f"{mesh.size}; pad with zero-weight machines "
            "(build_fleet does this automatically)"
        )
    compiled, formats = fleet_executable(
        spec, n_machines, n_rows, n_features, n_targets, mesh=mesh,
        donate=donate,
    )
    placed = put_fleet_batch(batch, formats)
    return compiled(placed.X, placed.y, placed.w, placed.keys)
