"""Fleet builder: N machine configs → one compiled program per bucket →
per-machine artifacts identical to the single-machine builder's.

The reference's workflow generator emits one Argo pod per machine running
``gordo build`` (SURVEY.md §4.4). ``build_fleet`` replaces that fan-out:
machines are grouped into compilation buckets (same model config + data
shape), each bucket trains as one ``vmap``-over-mesh program, and every
machine still gets its own serialized model dir + metadata + registry entry
— so the serving layer and the idempotency cache are shared verbatim with
the single-machine path, and a killed fleet build resumes by skipping
machines whose cache key is already registered (the reference's Argo-retry
semantics, per machine).

Supported model-config shapes (the reference's canonical anomaly configs):

1. ``DiffBasedAnomalyDetector(base_estimator=TransformedTargetRegressor(
   regressor=Pipeline([scaler, estimator]), transformer=scaler))``
2. ``DiffBasedAnomalyDetector(base_estimator=Pipeline([scaler, estimator]))``
3. ``Pipeline([scaler, estimator])`` / bare estimator

The estimator must be a zoo model (``BaseFlaxEstimator``); the scaler
``MinMaxScaler`` / ``StandardScaler`` or absent.
"""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import __version__
from .. import precision as precision_mod
from ..builder.build_model import (
    _dataset_from_config,
    cached_artifact_precision,
    calculate_model_key,
)
from ..models.analysis import Analyzed as _Analyzed
from ..models.analysis import analyze_model as _analyze_model
from ..models.transformers import MinMaxScaler, StandardScaler
from ..observability.registry import REGISTRY
from ..ops.scaling import ScalerParams
from ..resilience import faults
from ..serializer import pipeline_from_definition
from ..serializer.persistence import write_artifact_files
from ..store import (
    StoreError,
    commit_generation,
    resolve_artifact_dir,
    verify_artifact,
)
from ..store import journal as store_journal
from ..utils import disk_registry
from .fleet import (
    FLEET_CV_METRICS,
    FleetSpec,
    MachineBatch,
    backend_supports_donation,
    peek_fleet_executable,
    train_fleet_arrays,
)
from .mesh import pad_to_multiple

logger = logging.getLogger(__name__)

_M_FLEET_MACHINES = REGISTRY.counter(
    "gordo_fleet_machines_total",
    "Fleet-build machines resolved, by outcome (completed / cached / failed)",
    labels=("outcome",),
)
_M_BUILD_FETCH = REGISTRY.counter(
    "gordo_resilience_build_fetch_total",
    "Fleet-build per-machine data-fetch outcomes (ok / retry / failed)",
    labels=("outcome",),
)
_M_MACHINE_BUILD_SECONDS = REGISTRY.gauge(
    "gordo_fleet_machine_build_seconds",
    "Amortized build duration of each machine's latest fleet build "
    "(slice wall-clock / machines in slice)",
    labels=("machine",),
)

# sliced builds round the padded row axis up to a multiple of this, so
# heterogeneous-history slices collapse onto few compiled shapes
_ROW_QUANTUM = 256

MANIFEST_FILE = "fleet_manifest.json"

# exit code for a tripped multi-host watchdog: EX_TEMPFAIL — deliberately
# NOT the permanent-failure codes the CLI maps config/data errors to
# (64/66, which Argo/k8s must NOT retry); anything else is retryable under
# the reference's retry semantics, and 75 is the conventional "transient,
# try again" sysexits value
EXIT_RETRYABLE = 75

# env knob for the per-slice collective watchdog in multi-host builds
SLICE_TIMEOUT_ENV = "GORDO_SLICE_TIMEOUT_S"
_CKPT_SUBDIR = ".slice_checkpoints"

# per-machine data-fetch retry knobs (build-time resilience): transient
# lake hiccups get a few backed-off retries; a machine that STILL fails is
# isolated (built as zero-weight padding, recorded failed in the manifest)
# instead of killing the other N-1 machines' build
FETCH_RETRIES_ENV = "GORDO_BUILD_FETCH_RETRIES"
FETCH_BACKOFF_ENV = "GORDO_BUILD_FETCH_BACKOFF"


def _fetch_machine_data(item: dict, retries: int, backoff: float) -> Optional[str]:
    """Fetch one machine's training data into ``item`` (X/y/metadata),
    retrying transient provider failures with exponential backoff. Returns
    None on success, else the terminal error string — the caller decides
    isolation. Permanently-diagnosable failures (bad config, insufficient
    rows) skip the retry loop: re-reading the lake cannot grow history."""
    from ..dataset.dataset import InsufficientDataError

    name = item["machine"].name
    last_error: Optional[str] = None
    for attempt in range(max(0, retries) + 1):
        if attempt:
            _M_BUILD_FETCH.labels("retry").inc()
            time.sleep(backoff * 2 ** (attempt - 1))
        try:
            # chaos seam: `data-fetch:<machine>:error` stands in for a
            # dead lake / revoked credential for exactly one machine
            faults.inject("data-fetch", name)
            X_frame, y_frame = item["dataset"].get_data()
            item["X"] = np.asarray(
                getattr(X_frame, "values", X_frame), np.float32
            )
            item["y"] = np.asarray(
                getattr(y_frame, "values", y_frame), np.float32
            )
            item["dataset_metadata"] = item["dataset"].get_metadata()
            _M_BUILD_FETCH.labels("ok").inc()
            return None
        except (InsufficientDataError, ValueError) as exc:  # permanent
            last_error = f"{type(exc).__name__}: {exc}"
            break
        except Exception as exc:
            last_error = f"{type(exc).__name__}: {exc}"
            logger.warning(
                "Fleet fetch failed for %r (attempt %d/%d): %s",
                name, attempt + 1, max(0, retries) + 1, last_error,
            )
    _M_BUILD_FETCH.labels("failed").inc()
    return last_error


def _prepare_slice(
    slice_items: List[dict],
    n_padded: int,
    n_features: int,
    n_targets: int,
    quantize_rows: bool,
    span: Optional[Tuple[int, int]] = None,
    place: Optional[Tuple[Any, Any, bool]] = None,
    fetch_retries: int = 2,
    fetch_backoff: float = 0.5,
):
    """Host-side ingest for one slice: provider fetch + padded stacked
    assembly. Runs on the prefetch worker so slice ``s+1``'s data-lake reads
    (the reference's I/O hot spot, SURVEY.md §4.1) overlap slice ``s``'s
    device training + artifact writes. Peak host memory is therefore TWO
    slices' data (double buffer), not one — still bounded and documented at
    the slice_size knob.

    ``span=(lo, hi)``: assemble only machine rows ``[lo, hi)`` of the padded
    slice — the multi-host streaming-ingest path, where each process
    fetches ONLY its own machines' data (the machine axis is sharded over
    processes) and the assembled block becomes this process's shard of the
    global batch. The default covers the whole slice (single-host). NOTE:
    the returned row count is the LOCAL maximum; multi-host callers must
    exchange it for the global maximum before building global arrays (done
    on the main thread — collectives must never run on the prefetch worker,
    or two processes could order them differently and deadlock).

    ``place=(spec, mesh, donate)``: single-host transfer overlap. When the
    bucket's executable for this exact shape is ALREADY compiled
    (:func:`..fleet.peek_fleet_executable` — never compiles from this
    thread), the worker issues the layout-matched ``device_put`` of X/y/w
    here, so the NEXT slice's host→device transfer rides behind the
    current slice's training and artifact writes instead of serializing in
    front of its own training (on a tunnel-attached TPU the transfer costs
    ~3x the 128-machine program's execution). ``jax.device_put`` dispatch
    is async, so the worker never blocks on the wire either. Skipped for
    memory-constrained (remat) buckets — callers pass ``place=None``.
    The peek typically first hits for slice 2 of a row shape: slice 1's
    prepare is submitted before slice 0 triggers the bucket's compile, so
    its peek usually races a still-running compile and stays host-side —
    i.e. a 2-slice bucket may see no overlap at all; the win scales with
    slice count, exactly where ingest wall-time does too. Multi-host
    callers must NOT pass ``place`` (their batch assembly is collective,
    main-thread-only).

    Every shape input is an explicit argument (not a closure over bucket-loop
    locals): the call runs on another thread, and late-bound locals would
    silently go stale if a future ever crossed a bucket boundary (ADVICE r2).
    """
    lo, hi = span if span is not None else (0, n_padded)
    local_items = slice_items[lo:min(hi, len(slice_items))]
    fetch_started = time.perf_counter()

    def fetch_one(item: dict) -> None:
        # per-machine failure isolation: a machine whose fetch fails after
        # retries trains as zero-weight padding (fold masks already handle
        # empty machines) and is reported failed — it must not take the
        # other N-1 machines of the slice down with it
        error = _fetch_machine_data(item, fetch_retries, fetch_backoff)
        if error is not None:
            logger.error(
                "Isolating machine %r from fleet build: %s",
                item["machine"].name, error,
            )
            item["build_error"] = error
            item["X"] = np.zeros((0, n_features), np.float32)
            item["y"] = np.zeros((0, n_targets), np.float32)
            item["dataset_metadata"] = {}

    # items the width probe already fetched are skipped
    to_fetch = [item for item in local_items if "X" not in item]
    if len(to_fetch) > 1:
        # per-machine fetches are independent and (for real providers)
        # I/O-bound — the reference got this parallelism for free from its
        # pod-per-machine fan-out (SURVEY §4.1); a serial loop here would
        # make one slice's ingest wall-time the SUM of its machines' lake
        # reads. Bounded width: the point is overlapping network/disk
        # latency, not saturating the host CPU (this already runs on the
        # prefetch worker, itself overlapped behind device training).
        with ThreadPoolExecutor(
            max_workers=min(8, len(to_fetch)),
            thread_name_prefix="fleet-fetch",
        ) as pool:
            list(pool.map(fetch_one, to_fetch))
    else:
        for item in to_fetch:
            fetch_one(item)

    # max(…, 1): an all-isolated slice (every fetch failed) still needs a
    # nonzero row axis for the padded program
    n_rows = max(max((len(item["X"]) for item in local_items), default=1), 1)
    if quantize_rows:
        # quantize the row axis so slices with slightly different history
        # lengths share one (n_padded, n_rows, F) shape and the bucket
        # reuses a single compiled executable; padded rows are zero-weight
        # and masked everywhere (fold masks run on real-sample ranks)
        n_rows = -(-n_rows // _ROW_QUANTUM) * _ROW_QUANTUM
    X = np.zeros((hi - lo, n_rows, n_features), np.float32)
    y = np.zeros((hi - lo, n_rows, n_targets), np.float32)
    w = np.zeros((hi - lo, n_rows), np.float32)
    for i, item in enumerate(local_items):
        rows = len(item["X"])
        # RIGHT-aligned by convention (rows end at the bucket's latest
        # timestamp). CV correctness does not depend on placement: fold
        # masks are computed on real-sample ranks
        # (fleet.timeseries_fold_masks), invariant to where padding sits
        X[i, n_rows - rows :] = item["X"]
        y[i, n_rows - rows :] = item["y"]
        w[i, n_rows - rows :] = 1.0
    if place is not None and span is None:
        spec, mesh, donate = place
        hit = peek_fleet_executable(
            spec, n_padded, n_rows, n_features, n_targets, mesh=mesh,
            donate=donate,
        )
        if hit is not None:
            formats = hit[1]
            if formats is not None:
                X, y, w = (
                    jax.device_put(a, f)
                    for a, f in zip((X, y, w), formats[:3])
                )
            else:
                # no layout API on this backend: a default-layout put still
                # overlaps the wire behind the previous slice's training —
                # it is the same plain device_put the main thread would
                # otherwise pay serially in front of its own training
                X, y, w = (jax.device_put(a) for a in (X, y, w))
    return X, y, w, n_rows, time.perf_counter() - fetch_started


def _local_machine_span(mesh, n_padded: int) -> Tuple[int, int]:
    """Contiguous ``[lo, hi)`` of machine indices this process's devices own
    under :func:`~gordo_components_tpu.parallel.mesh.fleet_sharding` for a
    padded machine axis of ``n_padded`` — derived from the sharding itself,
    never from assumptions about device ordering."""
    from .mesh import fleet_sharding

    starts, stops = [], []
    for dev, idx in fleet_sharding(mesh).devices_indices_map(
        (n_padded,)
    ).items():
        if dev.process_index != jax.process_index():
            continue
        sl = idx[0]
        starts.append(0 if sl.start is None else sl.start)
        stops.append(n_padded if sl.stop is None else sl.stop)
    if not starts:
        raise ValueError(
            "This process owns no devices in the fleet mesh — every "
            "participating process must contribute devices"
        )
    lo, hi = min(starts), max(stops)
    owned = sum(stop - start for start, stop in zip(starts, stops))
    if owned != hi - lo:
        # interleaved per-process devices (a custom mesh not in
        # jax.devices() order) would make the min/max span cover OTHER
        # processes' machines — fail loudly instead of fetching and
        # assembling the wrong shard
        raise ValueError(
            "This process's fleet-mesh shards are not contiguous "
            f"(owns {owned} of span [{lo}, {hi})); build the mesh with "
            "parallel.distributed.global_fleet_mesh() so each process's "
            "devices are adjacent on the machine axis"
        )
    return lo, hi


def _gather_local_block(result):
    """Pull THIS process's contiguous machine block of a globally-sharded
    stacked result to host numpy (``jax.device_get`` on the whole tree
    would fault on non-addressable shards)."""

    def pull(a):
        if not hasattr(a, "addressable_shards"):
            return np.asarray(a)
        seen = {}
        for s in a.addressable_shards:
            start = s.index[0].start or 0
            if start not in seen:
                seen[start] = np.asarray(s.data)
        return np.concatenate([seen[k] for k in sorted(seen)], axis=0)

    return jax.tree_util.tree_map(pull, result)


def _abstract_result(spec, n_machines, n_rows, n_features, n_targets):
    """Shape/dtype skeleton of a stacked slice result, WITHOUT running the
    program — the restore template for orbax (types round-trip exactly)."""
    import jax.numpy as jnp

    from .fleet import make_machine_program, prng_key_width

    program = jax.vmap(make_machine_program(spec, n_rows, n_features, n_targets))
    return jax.eval_shape(
        program,
        jax.ShapeDtypeStruct((n_machines, n_rows, n_features), jnp.float32),
        jax.ShapeDtypeStruct((n_machines, n_rows, n_targets), jnp.float32),
        jax.ShapeDtypeStruct((n_machines, n_rows), jnp.float32),
        jax.ShapeDtypeStruct((n_machines, prng_key_width()), jnp.uint32),
    )


def _leaf_size(a) -> int:
    """Element count without materializing (np.asarray on a non-addressable
    global array would fail)."""
    size = getattr(a, "size", None)
    return int(size) if size is not None else int(np.asarray(a).size)


class _SliceCheckpointer:
    """Orbax-backed async checkpoint of each slice's stacked training result
    (SURVEY.md §6.4: async checkpoint of the stacked fleet pytree).

    The save overlaps the per-machine artifact loop (device→host transfer is
    already done; orbax writes in a background thread), closing the crash
    window between "training finished" and "every artifact + registry key
    durable": a resume restores the trained pytree instead of retraining the
    slice. Checkpoints are deleted once their slice's artifacts are all
    written — steady state leaves nothing behind.

    **Multi-host** (``mesh`` spanning processes): save/restore are orbax
    COLLECTIVES over the globally-sharded result — every process writes and
    reads its own shards (checkpoint dir on shared storage), the restore
    template carries fleet-axis ``NamedSharding``s, and deletion happens on
    process 0 only after a cross-process barrier confirms every process's
    slice artifacts are durable."""

    def __init__(self, output_dir: str, mesh=None):
        import orbax.checkpoint as ocp

        self._root = os.path.abspath(os.path.join(output_dir, _CKPT_SUBDIR))
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._ocp = ocp
        self._mesh = mesh
        self._multihost = jax.process_count() > 1

    @staticmethod
    def slice_key(slice_items: List[dict]) -> str:
        """Content key for a slice: the machines' cache keys (which already
        hash name + model/data/evaluation configs). Positional (bucket,
        slice) indices would SHIFT across resumes — completed machines
        leave ``pending``, so the survivors re-slice differently, and a
        stale positional checkpoint could silently restore another slice's
        params for the wrong machines."""
        import hashlib

        digest = hashlib.md5(
            json.dumps([item["cache_key"] for item in slice_items]).encode()
        )
        return digest.hexdigest()

    def path(self, key: str) -> str:
        return os.path.join(self._root, f"slice_{key}")

    # orbax refuses zero-size arrays (e.g. cv_scores with CV off); stand in
    # a 1-element placeholder on save and rebuild the empty array on restore
    def _shrink(self, tree):
        if self._multihost and self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self._mesh, PartitionSpec())

            def placeholder(a):
                # a GLOBAL replicated array, not host numpy: the collective
                # save expects every leaf to be a jax.Array whose shards
                # each process can write
                return jax.device_put(np.zeros((1,), a.dtype), repl)

        else:

            def placeholder(a):
                return np.zeros((1,), np.asarray(a).dtype)

        return jax.tree_util.tree_map(
            lambda a: placeholder(a) if _leaf_size(a) == 0 else a, tree
        )

    def _shrink_abstract(self, abstract):
        """Placeholder zero-size leaves, and — multi-host — attach the
        fleet-axis sharding to every real leaf (orbax restores each process's
        shards directly) and replicate the placeholders."""
        if self._mesh is None or not self._multihost:
            return jax.tree_util.tree_map(
                lambda s: (
                    jax.ShapeDtypeStruct((1,), s.dtype) if 0 in s.shape else s
                ),
                abstract,
            )
        from jax.sharding import NamedSharding, PartitionSpec

        from .mesh import FLEET_AXIS

        shard = NamedSharding(self._mesh, PartitionSpec(FLEET_AXIS))
        repl = NamedSharding(self._mesh, PartitionSpec())
        return jax.tree_util.tree_map(
            lambda s: (
                jax.ShapeDtypeStruct((1,), s.dtype, sharding=repl)
                if 0 in s.shape
                else jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shard)
            ),
            abstract,
        )

    @staticmethod
    def _unshrink(abstract, restored):
        return jax.tree_util.tree_map(
            lambda s, r: (
                np.zeros(s.shape, s.dtype) if 0 in s.shape else r
            ),
            abstract,
            restored,
        )

    def try_restore(self, key: str, abstract_fn):
        """``abstract_fn`` is a thunk: building the restore template costs a
        full eval_shape trace of the training program, so it only runs when
        a finalized checkpoint actually exists.

        Multi-host: all processes must take the SAME branch (restore is a
        collective; one process retraining while others restore would
        deadlock the training collectives), so existence is agreed by
        allgather first, and a restore failure then raises instead of
        silently diverging — the job-level retry handles it."""
        path = self.path(key)
        exists = os.path.isdir(path)  # orbax finalizes via atomic rename, so
        # a crashed mid-save leaves only a *-tmp dir, never this path
        if self._multihost:
            from jax.experimental import multihost_utils

            exists = bool(
                multihost_utils.process_allgather(
                    np.asarray([exists])
                ).all()
            )
        if not exists:
            return None
        abstract = abstract_fn()
        try:
            result = self._unshrink(
                abstract,
                self._ckptr.restore(
                    path,
                    args=self._ocp.args.StandardRestore(
                        self._shrink_abstract(abstract)
                    ),
                ),
            )
            logger.info(
                "Restored slice checkpoint %s (skipping retrain)", key
            )
            return result
        except Exception as exc:
            if self._multihost:
                raise  # diverging (one process retrains, others restored)
                # would deadlock the fleet collectives — fail the job loudly
            logger.warning(
                "Slice checkpoint %s unreadable (%s); retraining", path, exc
            )
            return None

    def save_async(self, key: str, result) -> None:
        self._ckptr.save(
            self.path(key),
            args=self._ocp.args.StandardSave(self._shrink(result)),
            force=True,
        )

    def join(self) -> None:
        """Wait for any in-flight async save WITHOUT deleting anything —
        exception-path cleanup, so a failed build neither leaks the saver
        thread nor lets a still-writing save race an in-process resume (a
        REAL kill has no thread left to race; this covers the simulated
        kills tests and chaos runs use). Deferred save errors are logged,
        not raised: the original build exception must propagate, and the
        checkpoint is only a resume accelerator."""
        try:
            self._ckptr.wait_until_finished()
        except Exception:
            logger.warning(
                "Async slice-checkpoint save failed during build abort",
                exc_info=True,
            )

    def finalize(self, key: str) -> None:
        """Wait for the async save, then drop the checkpoint — the slice's
        artifacts are durable now, so the registry is the source of truth.
        Multi-host: a cross-process barrier first (every process's slice
        artifacts must be durable before ANY copy of the checkpoint dies),
        then process 0 alone deletes from the shared dir."""
        import shutil

        self._ckptr.wait_until_finished()
        if self._multihost:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"slice-durable-{key}")
            if jax.process_index() != 0:
                return
        shutil.rmtree(self.path(key), ignore_errors=True)

    def close(self) -> None:
        import shutil

        self._ckptr.wait_until_finished()
        self._ckptr.close()
        if self._multihost and jax.process_index() != 0:
            return
        shutil.rmtree(self._root, ignore_errors=True)


def _write_manifest(
    output_dir: str,
    completed: Dict[str, Dict[str, Any]],
    pending: List[str],
    journal_counts: Optional[Dict[str, int]] = None,
) -> None:
    """Fleet completion bitmap (SURVEY.md §6.4): one JSON file in the output
    dir recording which machines are done, rewritten atomically after every
    slice — a monitor (or a resuming build) reads fleet progress without
    scanning the registry. Multi-host: each non-zero process writes its own
    ``fleet_manifest.p{i}.json`` (its machine shard) so concurrent writers
    on shared storage never clobber each other; a monitor unions the files.

    ``journal_counts``: the resume accounting from the build journal —
    how many machines were skipped because a previous run committed them
    (``resumed``), found torn and redone (``torn``), and actually built
    this run (``rebuilt``)."""
    import os
    import tempfile

    manifest_file = MANIFEST_FILE
    if jax.process_count() > 1 and jax.process_index() != 0:
        stem, ext = os.path.splitext(MANIFEST_FILE)
        manifest_file = f"{stem}.p{jax.process_index()}{ext}"
    os.makedirs(output_dir, exist_ok=True)
    payload = {
        "updated": time.strftime("%Y-%m-%d %H:%M:%S%z"),
        "n_completed": len(completed),
        "n_pending": len(pending),
        "machines": completed,
        "pending": sorted(pending),
    }
    if journal_counts is not None:
        payload["journal"] = dict(journal_counts)
    fd, tmp = tempfile.mkstemp(dir=output_dir, suffix=".manifest")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(payload, fh, indent=2)
        os.replace(tmp, os.path.join(output_dir, manifest_file))
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


@dataclass
class FleetMachineConfig:
    name: str
    model_config: Dict[str, Any]
    data_config: Dict[str, Any]
    metadata: Dict[str, Any] = field(default_factory=dict)
    # per-machine evaluation overrides (the reference's Machine.evaluation):
    # ``n_splits`` here beats build_fleet's global — machines with different
    # CV depths land in different compilation buckets
    evaluation: Dict[str, Any] = field(default_factory=dict)


def _effective_splits(
    machine: "FleetMachineConfig", default: int
) -> Tuple[int, Optional[bool], List[str]]:
    """Resolve the machine's CV depth and fold-execution mode:
    ``evaluation.n_splits`` beats the builder default (``None``/absent means
    "use the default"); ``evaluation.cv_parallel`` (bool, optional) pins the
    fold-execution strategy (:class:`..fleet.FleetSpec.cv_parallel` —
    vmapped vs scanned fold fits; ``None`` lets :func:`_spec_for` derive it
    from the model's memory profile). Returns the keys the fleet builder
    does NOT honor (e.g. ``cv_mode`` — always ``"fleet"`` here) so the
    caller can surface them instead of silently dropping config."""
    evaluation = machine.evaluation or {}
    value = evaluation.get("n_splits")
    if value is None:
        eff = int(default)
    else:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ValueError(
                f"Machine {machine.name!r}: evaluation.n_splits must be an "
                f"integer, got {value!r}"
            )
        if value < 0:
            raise ValueError(
                f"Machine {machine.name!r}: evaluation.n_splits must be >= 0, "
                f"got {value}"
            )
        eff = value
    cv_parallel = evaluation.get("cv_parallel")
    if cv_parallel is not None and not isinstance(cv_parallel, bool):
        raise ValueError(
            f"Machine {machine.name!r}: evaluation.cv_parallel must be a "
            f"boolean, got {cv_parallel!r}"
        )
    honored = {"n_splits", "cv_parallel"}
    ignored = sorted(k for k in evaluation if k not in honored)
    return eff, cv_parallel, ignored


def _derived_cv_parallel(model_config: Dict[str, Any]) -> bool:
    """The fold-execution mode a config derives when ``evaluation.
    cv_parallel`` is absent: sequential scan iff the model asked for remat
    (memory-constrained — see :func:`_spec_for`). Reads the literal
    ``remat`` kwarg off the config dict so bucketing can resolve the mode
    without instantiating the pipeline; no factory defaults ``remat`` on,
    so textual absence means remat is off (pinned against the spec-level
    derivation by tests/test_fleet.py)."""

    def scan(node: Any) -> bool:
        if isinstance(node, dict):
            if node.get("remat"):
                return True
            return any(scan(v) for v in node.values())
        if isinstance(node, (list, tuple)):
            return any(scan(v) for v in node)
        return False

    return not scan(model_config)


def _scaler_kind(
    scaler: Optional[Any],
) -> Tuple[str, Tuple[float, float], Tuple[bool, bool]]:
    if scaler is None:
        return "none", (0.0, 1.0), (True, True)
    if isinstance(scaler, MinMaxScaler):
        return "minmax", tuple(scaler.feature_range), (True, True)
    if isinstance(scaler, StandardScaler):
        return (
            "standard",
            (0.0, 1.0),
            (bool(scaler.with_mean), bool(scaler.with_std)),
        )
    raise ValueError(
        f"Fleet building supports MinMaxScaler/StandardScaler steps; got "
        f"{type(scaler).__name__}"
    )


def _spec_for(
    analyzed: _Analyzed,
    n_features: int,
    n_targets: int,
    n_splits: int,
    cv_parallel: Optional[bool] = None,
) -> FleetSpec:
    est = analyzed.estimator
    if getattr(est, "joint_horizon", False):
        raise ValueError(
            "MultiStepForecast (joint horizon) is single-machine only: the "
            "fleet program's target/weight math assumes one target row per "
            "window — use LSTMForecast(horizon=k) for fleet builds"
        )
    model_spec = est._make_spec(n_features, n_targets)
    kind, feature_range, scaler_options = _scaler_kind(analyzed.input_scaler)
    t_kind, t_range, t_options = _scaler_kind(analyzed.target_scaler)
    if analyzed.detector is not None and not isinstance(
        analyzed.detector.scaler, MinMaxScaler
    ):
        # the compiled program computes minmax error-scaler params; writing
        # them into a different scaler class would silently change scoring
        raise ValueError(
            "Fleet building supports a MinMaxScaler anomaly error scaler; "
            f"got {type(analyzed.detector.scaler).__name__} — use the "
            "single-machine builder for this config"
        )
    dropout = float(model_spec.config.get("dropout", 0.0) or 0.0)
    memory_constrained = bool(model_spec.config.get("remat", False))
    if cv_parallel is None:
        # derive the fold-execution mode from the model's memory profile: a
        # config that asked for remat is trading FLOPs for memory already —
        # multiplying step activations by (K+1) would undo that, so such
        # buckets keep the sequential scan; everything else takes the
        # (K+1)× sequential-depth win (FleetSpec.cv_parallel)
        cv_parallel = not memory_constrained
    return FleetSpec(
        module=model_spec.module,
        optimizer=model_spec.optimizer,
        loss=model_spec.loss,
        lookahead=est.lookahead,
        lookback_window=est.lookback_window,
        scaler=kind,
        feature_range=feature_range,
        batch_size=est.batch_size,
        epochs=est.epochs,
        n_splits=n_splits,
        use_dropout=dropout > 0.0,
        scale_targets=analyzed.target_scaler is not None,
        scaler_options=scaler_options,
        target_scaler=t_kind,
        target_feature_range=t_range,
        target_scaler_options=t_options,
        cv_parallel=cv_parallel,
        # scan unrolling follows the model's step-body size, NOT
        # cv_parallel: an explicit cv_parallel override must not silently
        # change compile-time/footprint behavior too. Only "flat" models
        # (small MLP step bodies) unroll: a windowed model's batch step
        # already contains an inner time scan / attention stack, so
        # inlining 4 copies multiplies exactly the structures XLA:TPU's
        # optimization passes are superlinear in — measured on the live
        # tunnel (r4): the 32-machine LSTM fleet compile went from 28.7 s
        # to ~25 min with unroll=4 (XLA:CPU shows no such blowup, 16-27 s
        # across all knob combinations), while its dispatch-overhead win
        # only ever applied to the tiny dense bodies anyway
        fit_unroll=(
            1
            if (memory_constrained or model_spec.input_kind == "window")
            else 4
        ),
        # predict-chunk widening keys off the memory profile alone: it is
        # a forward-only memory argument (fleet.py) with no XLA:TPU
        # compile-time cost, so windowed non-remat models keep it even
        # though they don't unroll
        widen_predict=not memory_constrained,
    )


def _slice_scaler(stacked: ScalerParams, i: int) -> ScalerParams:
    return ScalerParams(
        scale=np.asarray(stacked.scale[i]), offset=np.asarray(stacked.offset[i])
    )


def _install_result(
    model: Any, result, i: int, n_features: int, n_targets: int, n_splits: int
) -> None:
    """Write machine ``i``'s slice of the stacked bucket result into a fresh
    materialized model graph — producing the same fitted object the
    single-machine path would."""
    analyzed = _analyze_model(model)
    history = [float(v) for v in np.asarray(result.loss_history[i])]
    analyzed.estimator.set_state(
        {
            "params": jax.tree_util.tree_map(
                lambda leaf: np.asarray(leaf[i]), result.params
            ),
            "n_features": n_features,
            "n_features_out": n_targets,
            "history": history,
        }
    )
    if analyzed.input_scaler is not None:
        analyzed.input_scaler.params_ = _slice_scaler(result.input_scaler, i)
    if analyzed.target_scaler is not None:
        analyzed.target_scaler.params_ = _slice_scaler(result.target_scaler, i)
    if analyzed.detector is not None:
        det = analyzed.detector
        det.scaler.params_ = _slice_scaler(result.error_scaler, i)
        det.tag_thresholds_ = np.asarray(result.tag_thresholds[i])
        det.total_threshold_ = float(result.total_threshold[i])
        det.cross_validation_ = _cv_metadata(result, i, n_splits)


def _cv_metadata(result, i: int, n_splits: int) -> Dict[str, Any]:
    """Per-machine CV record with the same metric keys the single-machine
    builder emits (models.metrics.METRICS); NaN fold scores (fold had no
    real rows for this machine) are reported as null, never averaged in."""
    cv_scores = np.asarray(result.cv_scores[i])  # (n_splits, n_metrics)

    def val(s):
        return float(s) if np.isfinite(s) else None

    aggregates = {}
    for m, name in enumerate(FLEET_CV_METRICS):
        col = cv_scores[:, m]
        real = col[np.isfinite(col)]
        aggregates[name] = float(np.mean(real)) if len(real) else None
    return {
        "n_splits": n_splits,
        "splits": [
            {
                "fold": k,
                "scores": {
                    name: val(fold[m])
                    for m, name in enumerate(FLEET_CV_METRICS)
                },
            }
            for k, fold in enumerate(cv_scores)
        ],
        "scores": aggregates,
    }


class _SliceWatchdog:
    """Failure detection for multi-host slices (SURVEY §6.3 translation:
    the reference delegates hung-pod detection to k8s liveness + Argo
    retries; a multi-host ``build_fleet`` needs an in-process equivalent
    because a dead PEER process leaves the survivors blocked inside a
    collective — ``process_allgather``, the collective orbax save/restore,
    or a barrier — which no k8s probe can distinguish from slow training
    from the outside).

    With ``GORDO_SLICE_TIMEOUT_S`` set (CLI: ``fleet-build`` passes the
    env through), each slice iteration must finish inside the budget or
    the process logs CRITICAL and hard-exits :data:`EXIT_RETRYABLE` (75,
    EX_TEMPFAIL — retried under the reference's Argo semantics, unlike
    the permanent 64/66). A hard ``os._exit`` is deliberate: a thread
    blocked in a native collective cannot be interrupted from Python, so
    a cooperative exception would never fire. Restart-all-then-resume is
    exactly the reference's retry model — the re-run resolves finished
    machines from the registry and restores any checkpointed slice
    instead of retraining. Size the budget above the worst healthy slice
    wall time (it is a liveness bound, not a perf target); unset = no
    watchdog (single-host builds never arm it: a lone process cannot be
    stalled by a peer, and killing it would lose the in-flight slice for
    nothing).

    Pinned end-to-end by tests/test_aux.py's asymmetric-failure drill
    (peer killed mid-build -> survivor exits 75 -> rerun resumes).
    """

    def __init__(self, multihost: bool, timeout_s: Optional[float] = None):
        if timeout_s is None:
            raw = os.environ.get(SLICE_TIMEOUT_ENV, "")
            timeout_s = float(raw) if raw else 0.0
        self.armed = bool(multihost and timeout_s > 0)
        self.timeout_s = timeout_s
        self._timer: Optional[Any] = None
        self._where = ""

    def start(self, bucket: int, sl: int) -> None:
        """Arm the timer for one slice iteration (no-op when unarmed)."""
        if not self.armed:
            return
        import threading

        self.stop()
        self._where = f"bucket {bucket} slice {sl}"
        self._timer = threading.Timer(self.timeout_s, self._trip)
        self._timer.daemon = True
        self._timer.start()

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _trip(self) -> None:
        try:
            # best-effort diagnostics only: ANY exception here (e.g. the
            # distributed runtime already torn down when process_index()
            # is evaluated) must still reach os._exit — a dead timer
            # thread would leave the process hung in the native
            # collective, the exact failure this watchdog exists to stop
            logger.critical(
                "Fleet slice watchdog: %s exceeded %.0fs on process %d — "
                "a peer process has likely died mid-collective; exiting "
                "%d (retryable) so the job layer restarts all processes "
                "and the re-run resumes from registry + slice checkpoints",
                self._where,
                self.timeout_s,
                jax.process_index(),
                EXIT_RETRYABLE,
            )
            logging.shutdown()  # the CRITICAL line must hit the stream
            # before os._exit skips every atexit/flush hook
        finally:
            os._exit(EXIT_RETRYABLE)

def build_fleet(
    machines: List[FleetMachineConfig],
    output_dir: str,
    model_register_dir: Optional[str] = None,
    mesh=None,
    seed: int = 0,
    n_splits: int = 3,
    profile_dir: Optional[str] = None,
    slice_size: Optional[int] = 256,
    fetch_retries: Optional[int] = None,
    fetch_backoff: Optional[float] = None,
    precision_default: Optional[str] = None,
    precision_map: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Build every machine; returns ``{name: model_dir}``.

    **Precision ladder (§19)**: ``precision_map`` pins individual
    machines to a rung (f32/bf16/int8); everything else takes
    ``precision_default`` (flag → ``GORDO_PRECISION_DEFAULT`` → f32).
    Training always runs f32 — precision shapes each machine's SERVING
    artifact: the metadata pin, the int8 quantized sidecar, and the
    cache key (so re-precisioning a machine rebuilds its artifact
    rather than resurrecting the old rung's).

    **Per-machine failure isolation**: a machine whose data fetch fails
    (after ``fetch_retries`` backed-off retries — defaults from
    ``GORDO_BUILD_FETCH_RETRIES``/``GORDO_BUILD_FETCH_BACKOFF``, else 2 /
    0.5 s) is built as zero-weight padding and recorded ``failed`` in the
    fleet manifest instead of aborting the other machines' build; it is
    absent from the returned mapping and, being unregistered, retried by
    the next run. (Single-host only for the width-probe path — multi-host
    bucketing must stay process-identical, so probe failures there still
    abort.)

    Machines whose config hash is already registered — or whose build
    journal record says ``committed`` (``store/journal.py``; the WAL is
    the resume source when no registry is configured) — are skipped,
    but only after their artifact passes manifest VERIFICATION; a torn
    one is redone and counted under ``torn`` in the fleet manifest's
    ``journal`` block (alongside ``resumed``/``rebuilt``). Artifacts
    land as atomic ``gen-NNNN`` generations (``store/``), so a kill at
    any point leaves each machine either whole or absent — never torn.
    Remaining machines are bucketed by (model config, data shape)
    and each bucket trains as one compiled program, sharded over ``mesh``.
    ``profile_dir`` wraps the device work in a ``jax.profiler`` trace.

    Buckets larger than ``slice_size`` train in slices: every slice is padded
    to the same machine count (so the compiled executable is reused across
    slices) and its artifacts + registry keys are written the moment it
    finishes — a killed build loses at most one in-flight slice, and the
    resume pass skips everything already registered. ``slice_size=None``
    trains each bucket in a single program call (round-1 behavior).

    **Multi-host** (``jax.process_count() > 1`` with a
    :func:`~gordo_components_tpu.parallel.distributed.global_fleet_mesh`):
    every process runs the same deterministic bucketing, but fetches ONLY
    its own machines' data (the slice prefetcher assembles the process-local
    shard, overlapping the previous slice's training as on one host), the
    shards become one global batch via
    ``jax.make_array_from_process_local_data``, and after training each
    process writes only its own machines' artifacts + registry keys.
    Requires ``output_dir``/``model_register_dir`` on storage shared by all
    processes (the reference's shared-volume assumption) so resume scans
    agree; each process's return value covers cached + its own machines.
    Slice checkpoints are orbax COLLECTIVES over the sharded result (each
    process writes/reads its own shards), layered on the per-machine
    registry resume.
    """
    import os

    from ..utils.profiling import PhaseTimer, device_trace

    if slice_size is not None and slice_size < 1:
        # validated BEFORE any dataset probing or cache scanning, so an
        # invalid value errors even on a fully-cached (no-op) build
        raise ValueError(
            f"slice_size must be a positive integer or None, got {slice_size!r}"
        )
    if fetch_retries is None:
        fetch_retries = int(os.environ.get(FETCH_RETRIES_ENV, "2"))
    if fetch_backoff is None:
        fetch_backoff = float(os.environ.get(FETCH_BACKOFF_ENV, "0.5"))
    # precision resolution: per-machine map beats the fleet default; every
    # value validated HERE (including map entries naming no machine in
    # this fleet — a typo'd name must fail the build, not silently build
    # that machine f32)
    fleet_precision = precision_mod.resolve_default(precision_default)
    precision_map = {
        name: precision_mod.validate(value)
        for name, value in (precision_map or {}).items()
    }
    known = {machine.name for machine in machines}
    unknown = sorted(set(precision_map) - known)
    if unknown:
        raise ValueError(
            f"--precision-map names machines not in this fleet: {unknown}"
        )

    def precision_of(name: str) -> str:
        return precision_map.get(name, fleet_precision)
    multihost = jax.process_count() > 1
    if multihost:
        if mesh is None:
            raise ValueError(
                "multi-host fleet builds need a global mesh "
                "(parallel.distributed.global_fleet_mesh())"
            )
        logger.info(
            "Multi-host fleet build: process %d/%d fetches and writes only "
            "its own machine shard; slice checkpoints are collective",
            jax.process_index(),
            jax.process_count(),
        )

    timer = PhaseTimer()
    started = time.perf_counter()
    results: Dict[str, str] = {}
    pending: List[Tuple[FleetMachineConfig, str, int, Optional[bool]]] = []
    ignored_eval: Dict[str, List[str]] = {}
    # resumable-build WAL: one fsync'd record per machine lifecycle event
    # (started / committed / failed); a re-run replays it (unioned with any
    # multi-host siblings) so committed machines are skipped even when no
    # registry is configured, and torn ones are provably redone
    journal = store_journal.BuildJournal(
        store_journal.journal_path(output_dir, jax.process_index())
    )
    journal_states = store_journal.replay(output_dir)
    journal_counts = {"resumed": 0, "torn": 0, "rebuilt": 0}
    for machine in machines:
        eff_splits, eff_cv_parallel, ignored = _effective_splits(
            machine, n_splits
        )
        if ignored:
            ignored_eval[machine.name] = ignored
        # cv_parallel is deliberately NOT part of the cache key: it is an
        # execution strategy (vmapped vs scanned fold fits), numerically
        # equivalent by tests/test_fleet.py::test_cv_parallel_matches_scan —
        # flipping it must resume from existing artifacts, not retrain. The
        # mode that actually trained an artifact is recorded in its fleet
        # metadata block for provenance.
        evaluation_config = {"n_splits": eff_splits, "cv_mode": "fleet"}
        cache_key = calculate_model_key(
            machine.name,
            machine.model_config,
            machine.data_config,
            evaluation_config=evaluation_config,
            # §19: re-precisioning a machine is a cache miss — a cached
            # f32 artifact must not satisfy an int8 build (and vice
            # versa); f32 keeps every pre-ladder key valid
            precision=precision_of(machine.name),
        )
        cached: Optional[str] = None
        if model_register_dir:
            # dangling pointers already read as None inside get_value
            cached = disk_registry.get_value(model_register_dir, cache_key)
        if cached is None:
            # no registry (or no entry): the journal's committed record is
            # the fallback resume source — but only for the SAME config
            # (cache_key match), else a config change would resurrect a
            # stale artifact
            record = journal_states.get(machine.name)
            if (
                record is not None
                and record.get("event") == store_journal.EVENT_COMMITTED
                and record.get("cache_key") == cache_key
                and os.path.isdir(str(record.get("model_dir", "")))
            ):
                cached = str(record["model_dir"])
        if cached is not None:
            # trust nothing unverified: a registered-but-torn artifact
            # (crash between artifact and registry durability) must
            # rebuild, not serve half a model later. Structural check
            # only (deep=False): a fully-cached thousand-machine resume
            # must stay O(stats) — the serving load() pays the hash pass
            try:
                verify_artifact(resolve_artifact_dir(cached), deep=False)
            except StoreError as exc:
                logger.warning(
                    "Fleet resume: artifact for %r fails verification "
                    "(%s); rebuilding", machine.name, exc,
                )
                journal_counts["torn"] += 1
            else:
                cached_precision = cached_artifact_precision(cached)
                if cached_precision != precision_of(machine.name):
                    # registry/journal values are the machine's SHARED
                    # output dir — a later re-precision build swapped
                    # CURRENT under this key's entry, so a hit alone
                    # must not resurrect the other rung (§19)
                    logger.warning(
                        "Fleet resume: artifact for %r serves precision "
                        "%s but this build pins %s; rebuilding",
                        machine.name, cached_precision,
                        precision_of(machine.name),
                    )
                else:
                    logger.info(
                        "Fleet cache hit for %r -> %s", machine.name, cached
                    )
                    results[machine.name] = cached
                    journal_counts["resumed"] += 1
                    _M_FLEET_MACHINES.labels("cached").inc()
                    continue
        pending.append((machine, cache_key, eff_splits, eff_cv_parallel))
    if ignored_eval:
        sample = dict(list(ignored_eval.items())[:5])
        logger.warning(
            "Fleet builder ignores unsupported evaluation keys on %d "
            "machine(s) (cv_mode is always 'fleet' here): %s%s",
            len(ignored_eval),
            sample,
            " ..." if len(ignored_eval) > 5 else "",
        )

    manifest: Dict[str, Dict[str, Any]] = {
        name: {"status": "cached", "model_dir": path}
        for name, path in results.items()
    }
    _write_manifest(
        output_dir, manifest, [m.name for m, *_ in pending],
        journal_counts=journal_counts,
    )

    # ---- bucket by (model config, feature/target width) BEFORE fetching:
    # widths come from the dataset's declared columns, so peak host memory
    # is one bucket's data, not the whole fleet's ---------------------------
    buckets: Dict[str, List[dict]] = {}
    for machine, cache_key, eff_splits, eff_cv_parallel in pending:
        dataset = _dataset_from_config(machine.data_config)
        item: dict = {
            "machine": machine,
            "cache_key": cache_key,
            "dataset": dataset,
        }
        if hasattr(dataset, "_columns_for"):
            n_features = len(dataset._columns_for(dataset.tag_list))
            n_targets = len(dataset._columns_for(dataset.target_tag_list))
        elif multihost:  # non-TimeSeriesDataset: widths require a fetch —
            # and multi-host bucketing must stay identical on every
            # process, so a probe failure aborts (job-level retry) rather
            # than diverging the collective program
            X_probe, y_probe = dataset.get_data()
            n_features, n_targets = X_probe.shape[1], y_probe.shape[1]
            item["X"] = np.asarray(getattr(X_probe, "values", X_probe), np.float32)
            item["y"] = np.asarray(getattr(y_probe, "values", y_probe), np.float32)
            item["dataset_metadata"] = dataset.get_metadata()
        else:  # single-host width probe: fetch with retry, isolating a
            # terminally-failing machine BEFORE it ever buckets
            error = _fetch_machine_data(item, fetch_retries, fetch_backoff)
            if error is not None:
                logger.error(
                    "Isolating machine %r from fleet build (width probe): %s",
                    machine.name, error,
                )
                manifest[machine.name] = {"status": "failed", "error": error}
                journal.record(
                    machine.name, store_journal.EVENT_FAILED, error=error
                )
                _M_FLEET_MACHINES.labels("failed").inc()
                continue
            n_features, n_targets = item["X"].shape[1], item["y"].shape[1]
        item["F"], item["T"] = n_features, n_targets
        item["n_splits"] = eff_splits
        # resolve the fold-execution mode NOW (None → the remat-derived
        # default, readable straight off the config dict) so a machine whose
        # explicit override merely restates the default still buckets — and
        # batches — with its unannotated twins; different resolved modes are
        # different compiled programs and bucket separately
        item["cv_parallel"] = (
            eff_cv_parallel
            if eff_cv_parallel is not None
            else _derived_cv_parallel(machine.model_config)
        )
        sig = json.dumps(
            {
                "model_config": machine.model_config,
                "F": n_features,
                "T": n_targets,
                "n_splits": item["n_splits"],
                "cv_parallel": item["cv_parallel"],
            },
            sort_keys=True,
            default=str,
        )
        buckets.setdefault(sig, []).append(item)

    if any(
        entry.get("status") == "failed" for entry in manifest.values()
    ):
        # probe-isolated machines must land in the on-disk manifest even
        # when every remaining machine is cached (no slice write follows)
        _write_manifest(
            output_dir, manifest,
            [m.name for m, *_ in pending if m.name not in manifest],
            journal_counts=journal_counts,
        )

    master_key = jax.random.PRNGKey(seed)
    checkpointer = _SliceCheckpointer(output_dir, mesh=mesh)
    watchdog = _SliceWatchdog(multihost)
    # the donate value train_fleet_arrays will resolve to — the prefetch
    # worker must peek the executable cache under the SAME key
    donate_effective = backend_supports_donation(mesh)
    prefetcher = ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="fleet-prefetch"
    )
    try:
        for b, (sig, items) in enumerate(sorted(buckets.items())):
            bucket_started = time.perf_counter()
            model_config = items[0]["machine"].model_config
            probe = pipeline_from_definition(model_config)
            analyzed = _analyze_model(probe)
            n_features = items[0]["F"]
            n_targets = items[0]["T"]
            bucket_splits = items[0]["n_splits"]
            spec = _spec_for(
                analyzed,
                n_features,
                n_targets,
                bucket_splits,
                cv_parallel=items[0]["cv_parallel"],
            )

            # ---- slice the bucket: each slice is an independent failure domain
            # with its own data fetch, train call, and artifact writes. All
            # slices share one padded machine count so the compiled executable
            # is reused (fleet_program caches on spec+shape) --------------------
            n_real = len(items)
            eff = n_real if not slice_size else min(slice_size, n_real)
            n_padded = pad_to_multiple(eff, mesh.size) if mesh is not None else eff
            slices = [items[s : s + eff] for s in range(0, n_real, eff)]
            logger.info(
                "Fleet bucket %d/%d: %d machines in %d slice(s) of %d "
                "(padded %d), F=%d",
                b + 1,
                len(buckets),
                n_real,
                len(slices),
                eff,
                n_padded,
                n_features,
            )
            quantize_rows = len(slices) > 1
            span = _local_machine_span(mesh, n_padded) if multihost else None
            # single-host transfer overlap (see _prepare_slice): the worker
            # device-places a prepared slice when the bucket's executable
            # already exists. Memory-constrained (remat) buckets keep the
            # batch on host until their own turn — their peak-HBM budget
            # has no room for a second slice's buffers
            place = (
                (spec, mesh, donate_effective)
                if (not multihost and spec.widen_predict)
                else None
            )
            prepared = prefetcher.submit(
                _prepare_slice,
                slices[0], n_padded, n_features, n_targets, quantize_rows,
                span, place, fetch_retries, fetch_backoff,
            )
            for s, slice_items in enumerate(slices):
                # armed only multi-host + GORDO_SLICE_TIMEOUT_S: if THIS
                # iteration stalls past the budget (dead peer -> blocked
                # collective), the process exits EXIT_RETRYABLE for the
                # job layer to restart; disarmed at iteration end below
                # and in the outer finally
                watchdog.start(b, s)
                slice_started = time.perf_counter()
                X, y, w, n_rows, fetch_s = prepared.result()
                timer.add("data_fetch", fetch_s)
                if s + 1 < len(slices):
                    prepared = prefetcher.submit(
                        _prepare_slice,
                        slices[s + 1], n_padded, n_features, n_targets,
                        quantize_rows, span, place, fetch_retries,
                        fetch_backoff,
                    )
                keys = jax.random.split(
                    jax.random.fold_in(jax.random.fold_in(master_key, b), s),
                    n_padded,
                )

                if multihost:
                    # main thread only (see _prepare_slice): agree on the
                    # global row width, then lift the process-local shards
                    # into one global batch — ingest stayed process-local
                    # and overlapped, only this assembly is synchronous
                    from jax.experimental import multihost_utils

                    from .mesh import fleet_sharding

                    n_rows_global = int(
                        multihost_utils.process_allgather(
                            np.asarray([n_rows])
                        ).max()
                    )
                    if n_rows_global != n_rows:
                        # leading pad keeps every machine right-aligned
                        pad = ((0, 0), (n_rows_global - n_rows, 0))
                        X = np.pad(X, pad + ((0, 0),))
                        y = np.pad(y, pad + ((0, 0),))
                        w = np.pad(w, pad)
                        n_rows = n_rows_global
                    sharding = fleet_sharding(mesh)
                    lo, hi = span
                    batch = MachineBatch(
                        X=jax.make_array_from_process_local_data(sharding, X),
                        y=jax.make_array_from_process_local_data(sharding, y),
                        w=jax.make_array_from_process_local_data(sharding, w),
                        keys=jax.make_array_from_process_local_data(
                            sharding, np.asarray(keys)[lo:hi]
                        ),
                    )
                else:
                    batch = MachineBatch(X=X, y=y, w=w, keys=keys)

                ckpt_key = checkpointer.slice_key(slice_items)
                result = checkpointer.try_restore(
                    ckpt_key,
                    lambda: _abstract_result(
                        spec, n_padded, n_rows, n_features, n_targets
                    ),
                )
                if result is None:
                    with timer.phase("train"), device_trace(profile_dir):
                        # donate: the placed batch is never reused after the
                        # call, so XLA may overlay intermediates on its HBM —
                        # the peak-memory lever for plant-scale buckets
                        result = train_fleet_arrays(
                            spec, batch, mesh=mesh, donate=True
                        )
                        if not multihost:
                            result = jax.device_get(result)
                    # async: orbax writes in the background while the
                    # artifact loop below runs (multi-host: a COLLECTIVE
                    # save of the sharded result); finalize() joins + deletes
                    checkpointer.save_async(ckpt_key, result)
                if multihost:
                    # restored or trained, the result is globally sharded:
                    # pull only this process's machine block to host
                    result = _gather_local_block(result)
                slice_duration = time.perf_counter() - slice_started

                if multihost:
                    lo, hi = span
                    # this process's machines only; result rows are the
                    # local block, so indices shift by lo
                    indexed_items = [
                        (i - lo, item)
                        for i, item in enumerate(slice_items)
                        if lo <= i < hi
                    ]
                else:
                    indexed_items = list(enumerate(slice_items))

                with timer.phase("artifacts"):
                    # ---- per-machine artifacts (same format as the single path),
                    # written before the next slice trains so a kill loses at most
                    # the in-flight slice ------------------------------------------
                    for i, item in indexed_items:
                        machine = item["machine"]
                        if "build_error" in item:
                            # isolated at fetch: trained as zero-weight
                            # padding; no artifact, no registry key — the
                            # next run retries it from scratch
                            manifest[machine.name] = {
                                "status": "failed",
                                "error": item["build_error"],
                                "bucket": b,
                                "slice": s,
                            }
                            journal.record(
                                machine.name,
                                store_journal.EVENT_FAILED,
                                error=item["build_error"],
                            )
                            _M_FLEET_MACHINES.labels("failed").inc()
                            continue
                        model = pipeline_from_definition(machine.model_config)
                        _install_result(
                            model, result, i, n_features, n_targets, bucket_splits
                        )
                        model_dir = os.path.join(output_dir, machine.name)
                        # same metadata contract as the single-machine builder
                        # (consumers read these keys uniformly off the shared
                        # registry); per-machine durations are the slice's amortized
                        # share
                        amortized = slice_duration / max(len(slice_items), 1)
                        metadata = {
                            "name": machine.name,
                            "gordo_components_tpu_version": __version__,
                            "model": {
                                "model_config": machine.model_config,
                                "model_builder_metadata": (
                                    model.get_metadata()
                                    if hasattr(model, "get_metadata")
                                    else {}
                                ),
                                "cross_validation": _cv_metadata(result, i, bucket_splits),
                                "model_training_duration_s": amortized,
                                "model_creation_date": time.strftime(
                                    "%Y-%m-%d %H:%M:%S%z"
                                ),
                                "cache_key": item["cache_key"],
                                "fleet": {
                                    "bucket": b,
                                    "bucket_size": n_real,
                                    "slice": s,
                                    "slice_size": len(slice_items),
                                    "slice_duration_s": slice_duration,
                                    # fold-execution mode that trained this
                                    # artifact (provenance; not in the cache
                                    # key — see evaluation_config above)
                                    "cv_parallel": bool(spec.cv_parallel),
                                },
                            },
                            "dataset": item["dataset_metadata"],
                            "build_duration_s": amortized,
                            "user_defined": dict(machine.metadata),
                            # §19: the manifest pin the serving layers read
                            "precision": precision_of(machine.name),
                        }
                        # WAL first, then the atomic generation commit,
                        # then registry + committed record: a crash at any
                        # point leaves either no trace (redo) or a whole,
                        # verifiable artifact (skip) — never a torn dir a
                        # resume would trust
                        journal.record(
                            machine.name,
                            store_journal.EVENT_STARTED,
                            cache_key=item["cache_key"],
                            bucket=b,
                            slice=s,
                        )
                        commit_generation(
                            model_dir,
                            lambda staging: write_artifact_files(
                                model, staging, metadata=metadata,
                                precision=precision_of(machine.name),
                            ),
                            name=machine.name,
                        )
                        if model_register_dir:
                            disk_registry.write_key(
                                model_register_dir, item["cache_key"], model_dir
                            )
                        journal.record(
                            machine.name,
                            store_journal.EVENT_COMMITTED,
                            cache_key=item["cache_key"],
                            model_dir=model_dir,
                        )
                        journal_counts["rebuilt"] += 1
                        results[machine.name] = model_dir
                        _M_FLEET_MACHINES.labels("completed").inc()
                        _M_MACHINE_BUILD_SECONDS.labels(machine.name).set(
                            amortized
                        )
                        manifest[machine.name] = {
                            "status": "completed",
                            "model_dir": model_dir,
                            "bucket": b,
                            "slice": s,
                        }
                    _write_manifest(
                        output_dir,
                        manifest,
                        [name for name in (m.name for m, *_ in pending) if name not in manifest],
                        journal_counts=journal_counts,
                    )
                with timer.phase("checkpoint_wait"):
                    # artifacts durable → join the async save, drop the ckpt
                    # (multi-host: barrier, then process 0 deletes)
                    checkpointer.finalize(ckpt_key)
                for item in slice_items:  # free before the next slice fetches
                    item.pop("X", None)
                    item.pop("y", None)
                watchdog.stop()  # this slice made liveness; next start()
                # re-arms with a fresh budget
            bucket_duration = time.perf_counter() - bucket_started
            logger.info(
                "Fleet bucket %d/%d done in %.1fs", b + 1, len(buckets), bucket_duration
            )

    finally:
        watchdog.stop()
        prefetcher.shutdown(wait=True, cancel_futures=True)
        checkpointer.join()
    checkpointer.close()
    # phase totals land in the same registry serving scrapes, under the
    # fleet prefix so single-machine and fleet builds stay distinguishable
    timer.publish(prefix="gordo_fleet_build")
    logger.info(
        "Fleet build: %d machines in %.1fs (%d cached); phases: %s",
        len(machines),
        time.perf_counter() - started,
        len(machines) - len(pending),
        timer.report(),
    )
    return results
