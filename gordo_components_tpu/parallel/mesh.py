"""Device-mesh helpers.

Single place that decides how the fleet axis maps onto hardware. On a TPU
pod slice the mesh covers all chips (ICI-connected); on CPU test runs it
covers the virtual devices created by
``--xla_force_host_platform_device_count``. Everything downstream only sees
``Mesh`` + ``NamedSharding`` — the same code compiles for 1 chip, 8 virtual
CPUs, or a v5e-16.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

FLEET_AXIS = "fleet"


def fleet_mesh(n_devices: Optional[int] = None, axis_name: str = FLEET_AXIS) -> Mesh:
    """1-D mesh over (up to) ``n_devices`` available devices."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} exist"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def fleet_sharding(mesh: Mesh, axis_name: str = FLEET_AXIS) -> NamedSharding:
    """Shard the leading (machine) axis over the mesh; trailing dims are
    implicitly replicated, so one spec serves arrays of any rank."""
    return NamedSharding(mesh, PartitionSpec(axis_name))


def pad_to_multiple(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` ≥ ``n`` (machine-axis padding so the
    fleet divides evenly across mesh devices)."""
    if multiple <= 0:
        raise ValueError(f"multiple must be positive, got {multiple}")
    return ((n + multiple - 1) // multiple) * multiple
