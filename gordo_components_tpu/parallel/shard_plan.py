"""Multi-host serving layout: which shard of the mesh owns which machine.

Mesh-TensorFlow frames batch splitting as one point in a layout space
(PAPERS.md); the serving tier already treats machine→worker placement as
a layout axis one level up (router/placement.py). This module closes the
gap between the two for a fleet whose stacked params span HOSTS: the
consistent-hash ring becomes the MACHINE-AXIS layout rule of an N-process
serving mesh, and the sharding decision is picked from a small declared
policy instead of being hand-threaded through config (Automap, PAPERS.md).

Three layout points exist per bucket (docs/ARCHITECTURE.md §23):

- **replicated** — one host's devices hold the whole stacked tree (the
  default latency mode);
- **host-sharded** — the stacked machine axis shards over one host's
  local devices (``--shard-fleet``, the §4.2 HBM capacity mode);
- **fleet-sharded** — the stacked machine axis partitions across N
  processes by ring position (this module): each shard's host stacks
  ONLY the machines it owns, serves them through the unchanged §12/§15
  pipelined + megabatched engine, and covers every other shard's
  machines through the §22 host-RAM spill tier (the fallback rung).

The plan is a pure function of ``(machine name, n_shards, vnodes)`` —
SHA-1 ring points, the same construction as router placement — so the
router and every worker compute the IDENTICAL layout independently:
nothing is threaded through config, a restarted process re-derives its
slice, and changing the shard count moves ~1/N of the machines (bounded
movement, inherited from the ring). For the true-SPMD path (one
``global_fleet_mesh()`` spanning every process, collectives only inside
jit — drilled by ``tests/multihost_child.py --serve-shard``) the plan
also yields the padded global machine axis (``pad_to_multiple``) and its
contiguous per-shard slices, which tile the ``NamedSharding`` layout a
multi-process mesh would give the same fleet.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis import lockcheck

logger = logging.getLogger(__name__)

# ring points per shard — matches router placement's default so the two
# layout axes have the same distribution quality
SHARD_VNODES = 64

POLICY_SHARDED = "sharded"
POLICY_REPLICATED = "replicated"


def shard_name(shard: int) -> str:
    """The ring-member name of shard ``shard`` — the stable identity the
    layout hashes against (worker names/pids must not move machines)."""
    return f"shard-{int(shard)}"


def worker_shard(worker_id: int, n_shards: int) -> int:
    """Which shard a worker slot serves: round-robin cover, so W workers
    over S shards tile evenly (the common case is W == S) and an elastic
    scale-up lands on the least-covered shard by construction."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return int(worker_id) % int(n_shards)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except (TypeError, ValueError):
        logger.warning("%s=%r is not an int; using %d", name, raw, default)
        return default


def mesh_shards_env() -> int:
    """``GORDO_MESH_SHARDS``: total shard count of the serving mesh; 0
    (the default) means single-host serving, exactly as before."""
    return max(0, _env_int("GORDO_MESH_SHARDS", 0))


def mesh_shard_env() -> Optional[int]:
    """``GORDO_MESH_SHARD``: THIS process's shard id (0-based); unset
    means derive from the worker id (see ``worker_shard``)."""
    raw = os.environ.get("GORDO_MESH_SHARD")
    if raw is None or not raw.strip():
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        logger.warning("GORDO_MESH_SHARD=%r is not an int; ignoring", raw)
        return None


class FleetShardPlan:
    """Deterministic machine→shard layout over an ``n_shards``-process
    serving mesh.

    Shard ids join a consistent-hash ring (``SHARD_VNODES`` SHA-1 points
    each); a machine belongs to the shard owning its ring position. The
    POLICY is declared, not hand-threaded: fleets smaller than
    ``min_shard_machines`` (``GORDO_MESH_MIN_SHARD_MACHINES``, default
    2×shards) stay REPLICATED — every shard owns the whole fleet, because
    below that size the cross-host split costs more than it frees — and
    larger fleets shard by ring position. Instances are immutable after
    construction, so reads (placement's per-request ``shard_of``) need no
    lock."""

    def __init__(
        self,
        n_shards: int,
        min_shard_machines: Optional[int] = None,
        vnodes: int = SHARD_VNODES,
    ):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        # the ring construction is router/placement.py's — the layout
        # axis IS the placement ring, one level down (imported lazily so
        # plain training imports of parallel.* never touch router deps)
        from ..router.placement import HashRing

        self.n_shards = int(n_shards)
        if min_shard_machines is None:
            min_shard_machines = _env_int(
                "GORDO_MESH_MIN_SHARD_MACHINES", 2 * self.n_shards
            )
        self.min_shard_machines = max(0, int(min_shard_machines))
        self.vnodes = int(vnodes)
        self._ring = HashRing(
            (shard_name(i) for i in range(self.n_shards)), vnodes=vnodes
        )

    # -- machine-axis layout -------------------------------------------------
    def shard_of(self, machine: str) -> int:
        """The shard owning ``machine``'s ring position. Pure arithmetic
        (one bisect over an immutable ring) — safe on the router's
        per-request path under its placement lock."""
        owner = self._ring.primary(machine)
        return int(owner.rsplit("-", 1)[1])

    def policy(self, fleet_size: int) -> str:
        """Which layout point the declared policy picks for a fleet of
        ``fleet_size`` machines."""
        if self.n_shards > 1 and fleet_size >= self.min_shard_machines:
            return POLICY_SHARDED
        return POLICY_REPLICATED

    def assign(self, machines: Sequence[str]) -> Dict[str, int]:
        """machine → owning shard for the whole fleet (sharded policy
        view; replicated fleets should call :meth:`owned` instead)."""
        return {name: self.shard_of(name) for name in machines}

    def owned(self, machines: Sequence[str], shard: int) -> List[str]:
        """The machines shard ``shard`` stacks eagerly, policy applied:
        a replicated fleet is owned EVERYWHERE (each host serves any
        machine from its own stacked tree), a sharded fleet partitions
        by ring position."""
        if not 0 <= int(shard) < self.n_shards:
            raise ValueError(
                f"shard {shard} outside the {self.n_shards}-shard mesh"
            )
        if self.policy(len(machines)) == POLICY_REPLICATED:
            return sorted(machines)
        return sorted(m for m in machines if self.shard_of(m) == int(shard))

    def counts(self, machines: Sequence[str]) -> List[int]:
        """Machines per shard under the sharded policy — the balance an
        operator (and the bench) reads."""
        counts = [0] * self.n_shards
        for name in machines:
            counts[self.shard_of(name)] += 1
        return counts

    # -- global-mesh (SPMD) view ---------------------------------------------
    def padded_height(self, n_machines: int) -> int:
        """Global stacked machine-axis length, padded so it divides
        evenly across the shards (``pad_to_multiple`` — padding slots
        repeat a live machine and are never dispatched, same contract as
        the engine's device-mesh padding)."""
        from .mesh import pad_to_multiple

        return pad_to_multiple(max(1, int(n_machines)), self.n_shards)

    def shard_bounds(self, n_machines: int) -> List[Tuple[int, int]]:
        """Contiguous ``[lo, hi)`` slices of the padded global machine
        axis, one per shard — the process-local slices a multi-process
        ``NamedSharding`` over ``global_fleet_mesh()`` materializes."""
        height = self.padded_height(n_machines)
        per = height // self.n_shards
        return [(i * per, (i + 1) * per) for i in range(self.n_shards)]

    def global_sharding(self, mesh):
        """The machine-axis ``NamedSharding`` over a (multi-process)
        fleet mesh — the SPMD twin of the ring partition above."""
        from .mesh import fleet_sharding

        return fleet_sharding(mesh)

    def describe(self) -> Dict[str, Any]:
        return {
            "shards": self.n_shards,
            "vnodes": self.vnodes,
            "min_shard_machines": self.min_shard_machines,
        }


# one plan per (shards, threshold) per process: the ring build hashes
# n_shards x vnodes points, and boot + every reload + the router all
# resolve the same layout — cache it instead of re-deriving per call
_PLAN_LOCK = lockcheck.named_lock("parallel.shard_plan")
_PLAN_CACHE: Dict[Tuple[int, int], FleetShardPlan] = {}


def resolve_plan(
    n_shards: Optional[int] = None,
    min_shard_machines: Optional[int] = None,
) -> Optional[FleetShardPlan]:
    """The process's serving-mesh layout, env-resolved: ``None`` when
    mesh serving is off (``GORDO_MESH_SHARDS`` unset/0), else the cached
    deterministic plan."""
    if n_shards is None:
        n_shards = mesh_shards_env()
    if not n_shards or n_shards < 1:
        return None
    if min_shard_machines is None:
        min_shard_machines = _env_int(
            "GORDO_MESH_MIN_SHARD_MACHINES", 2 * int(n_shards)
        )
    key = (int(n_shards), int(min_shard_machines))
    with _PLAN_LOCK:
        lockcheck.assert_guard("parallel.shard_plan")
        plan = _PLAN_CACHE.get(key)
        if plan is None:
            plan = FleetShardPlan(key[0], key[1])
            _PLAN_CACHE[key] = plan
        return plan
