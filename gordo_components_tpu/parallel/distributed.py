"""Multi-host orchestration.

The reference's "distributed backend" is Kubernetes pod scheduling — no
NCCL/MPI anywhere (SURVEY.md §2.3). The TPU-native equivalent:
``jax.distributed.initialize`` brings N hosts into one JAX runtime over
DCN; inside the runtime, ``global_fleet_mesh`` spans every chip of every
host and the fleet programs' collectives ride ICI within a slice (DCN only
carries the runtime's control plane and cross-slice collectives).

Restart/elasticity parity: the reference leans on k8s pod restarts + the
config-hash cache for idempotent retries. The same holds here — a restarted
multi-host job re-runs ``build_fleet``, which skips every machine already
registered (per-machine resume), so host failure costs at most the
in-flight bucket.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import FLEET_AXIS

logger = logging.getLogger(__name__)


def _already_initialized() -> bool:
    """``jax.distributed.is_initialized`` appeared after 0.4.x; on older
    runtimes probe the private singleton instead (conservatively False if
    even that moved — ``initialize`` then raising is the caller's clear
    signal, rather than silently skipping a required rendezvous)."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except (ImportError, AttributeError):
        # private module moved too: assume not initialized — a double
        # initialize then raises loudly rather than silently skipping a
        # required rendezvous
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this host to the distributed JAX runtime.

    With no arguments, cluster-environment autodetection is used (TPU pod
    metadata / k8s JobSet env vars) — the normal path on Cloud TPU.
    Explicit args support bare-metal setups. No-op if already initialized.

    Must run before anything touches the XLA backend (do NOT query
    ``jax.devices()``/``process_count()`` first — that would pin a
    single-process runtime).
    """
    if _already_initialized():
        logger.info("jax.distributed already initialized")
        return
    explicit = coordinator_address is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as exc:
        if explicit:
            # the caller named a coordinator: failing to join it is an
            # error, not a single-host fallback
            raise
        # autodetection found no cluster (tests, one-host dev) — fine
        logger.info("jax.distributed.initialize skipped: %s", exc)
    logger.info(
        "Distributed runtime: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def global_fleet_mesh(axis_name: str = FLEET_AXIS) -> Mesh:
    """1-D mesh over every device of every host. With
    ``jax.distributed`` initialized, ``jax.devices()`` already spans hosts;
    the fleet axis shards machines across the full pod and XLA keeps each
    machine's collectives on-chip (no cross-machine communication exists in
    the fleet program, so DCN carries nothing in steady state)."""
    return Mesh(np.array(jax.devices()), (axis_name,))
