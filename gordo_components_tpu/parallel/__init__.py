"""Fleet-scale parallelism: the TPU-native replacement for the reference's
orchestration-level fan-out.

The reference trains N machines as N Argo/Kubernetes pods with zero
inter-pod communication (SURVEY.md §2.2 — "embarrassingly-parallel fleet
fan-out", its only parallelism). Here that entire layer moves inside the
compiler: machines with the same architecture are stacked on a leading
``fleet`` axis, the single-machine train program is ``vmap``-ed over that
axis, and the axis is sharded across a ``jax.sharding.Mesh`` so XLA
partitions the fleet over chips (ICI-linked on real TPU topologies). One
compiled program trains the whole fleet; host Python never loops over
machines.
"""

from .mesh import fleet_mesh, fleet_sharding
from .distributed import global_fleet_mesh, initialize_multihost
from .fleet import (
    FleetSpec,
    MachineBatch,
    FleetResult,
    make_machine_program,
    train_fleet_arrays,
)
from .build_fleet import build_fleet, FleetMachineConfig

__all__ = [
    "fleet_mesh",
    "fleet_sharding",
    "global_fleet_mesh",
    "initialize_multihost",
    "FleetSpec",
    "MachineBatch",
    "FleetResult",
    "make_machine_program",
    "train_fleet_arrays",
    "build_fleet",
    "FleetMachineConfig",
]
