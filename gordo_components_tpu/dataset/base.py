"""Abstract dataset contract.

Reference parity: ``gordo_components/dataset/base.py`` [UNVERIFIED] —
``get_data() -> (X, y)``, ``get_metadata()``, and dict round-tripping so
dataset configs embed in fleet YAML and in saved-model metadata.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Tuple

import pandas as pd

from ..utils.config import resolve_config_class


class GordoBaseDataset(abc.ABC):
    @abc.abstractmethod
    def get_data(self) -> Tuple[pd.DataFrame, pd.DataFrame]:
        """Return the feature matrix ``X`` and target ``y`` (both time-indexed)."""

    @abc.abstractmethod
    def get_metadata(self) -> Dict[str, Any]:
        """Stats recorded into build metadata (per-tag counts, resolution, …)."""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": f"{self.__class__.__module__}.{self.__class__.__name__}",
            **getattr(self, "_init_kwargs", {}),
        }

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "GordoBaseDataset":
        config = dict(config)
        type_path = config.pop("type", "TimeSeriesDataset")
        dataset_cls = resolve_config_class(
            type_path,
            GordoBaseDataset,
            default_module="gordo_components_tpu.dataset.dataset",
        )
        return dataset_cls(**config)
