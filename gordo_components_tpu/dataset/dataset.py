"""Time-series dataset assembly: provider series → aligned ``(X, y)``.

Reference parity: ``gordo_components/dataset/datasets.py`` [UNVERIFIED] —
``TimeSeriesDataset`` with per-tag resample/aggregate, inner join on the
timestamp index, optional pandas-query row filtering, and per-tag count
metadata. TPU twist: the joined frames are float32 (the builder re-packs them
contiguously at ``jax.device_put`` time), and the windowing that
the reference did host-side with Keras' TimeseriesGenerator is deferred to
on-device static-shape gathers (:mod:`gordo_components_tpu.ops.windowing`).
"""

from __future__ import annotations

import logging
from datetime import datetime
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np
import pandas as pd

from .base import GordoBaseDataset
from .data_provider.base import GordoBaseDataProvider
from .data_provider.providers import RandomDataProvider
from .sensor_tag import SensorTag, normalize_sensor_tags

logger = logging.getLogger(__name__)


class InsufficientDataError(ValueError):
    """Raised when the assembled dataset has fewer rows than required."""


def _normalize_resolution(resolution: str) -> str:
    """Accept both legacy pandas offsets ("10T", "1H", "30S") and modern
    spellings ("10min", "1h", "30s") — ported gordo configs use the legacy
    uppercase forms, which pandas 3 rejects."""
    legacy = {"T": "min", "H": "h", "S": "s", "L": "ms", "U": "us"}
    for suffix, modern in legacy.items():
        if resolution.endswith(suffix) and (
            resolution[:-1].isdigit() or resolution[:-1] == ""
        ):
            return resolution[:-1] + modern
    return resolution


def _parse_date(value: Union[str, datetime]) -> datetime:
    if isinstance(value, datetime):
        return value
    return pd.Timestamp(value).to_pydatetime()


def join_timeseries(
    series_iterable: Iterable[pd.Series],
    resampling_start: datetime,
    resampling_end: datetime,
    resolution: str,
    aggregation_methods: Union[str, List[str]] = "mean",
    interpolation_method: str = "linear_interpolation",
    interpolation_limit: Optional[str] = "8H",
) -> Tuple[pd.DataFrame, Dict[str, Any]]:
    """Resample each series onto a common grid and inner-join on timestamps.

    Returns the joined frame and per-tag metadata: original / resampled row
    counts and rows dropped by the join — the numbers the reference records
    into build metadata for data-quality debugging.
    """
    resolution = _normalize_resolution(resolution)
    if interpolation_method not in ("linear_interpolation", "ffill", "none"):
        raise ValueError(
            f"interpolation_method must be one of 'linear_interpolation', "
            f"'ffill', 'none'; got {interpolation_method!r}"
        )
    metadata: Dict[str, Any] = {}
    resampled: List[pd.DataFrame] = []

    interpolation_steps = None
    if interpolation_limit is not None:
        step = pd.Timedelta(resolution)
        interpolation_steps = max(
            1, int(pd.Timedelta(_normalize_resolution(interpolation_limit)) / step)
        )

    for series in series_iterable:
        original_count = len(series)
        if original_count == 0:
            raise InsufficientDataError(f"Tag {series.name!r} has no data")
        series = series[~series.index.duplicated(keep="first")].sort_index()
        resampler = series.resample(resolution, origin=pd.Timestamp(resampling_start))
        if isinstance(aggregation_methods, str):
            frame = resampler.agg(aggregation_methods).to_frame(name=series.name)
        else:
            frame = resampler.agg(aggregation_methods)
            frame.columns = [f"{series.name}_{m}" for m in aggregation_methods]
        if interpolation_method == "linear_interpolation":
            frame = frame.interpolate(method="linear", limit=interpolation_steps)
        elif interpolation_method == "ffill":
            frame = frame.ffill(limit=interpolation_steps)
        frame = frame.dropna()
        metadata.setdefault("tags", {})[str(series.name)] = {
            "original_length": original_count,
            "resampled_length": len(frame),
        }
        resampled.append(frame)

    if not resampled:
        raise InsufficientDataError("No series to join (empty tag list?)")
    joined = pd.concat(resampled, axis=1, join="inner").dropna()
    for name in list(metadata.get("tags", {})):
        metadata["tags"][name]["dropped_by_join"] = (
            metadata["tags"][name]["resampled_length"] - len(joined)
        )
    before_slice = len(joined)
    joined = joined[(joined.index >= resampling_start) & (joined.index < resampling_end)]
    metadata["dropped_by_range_slice"] = before_slice - len(joined)
    metadata["joined_length"] = len(joined)
    return joined, metadata


class TimeSeriesDataset(GordoBaseDataset):
    """Assemble per-tag provider series into aligned ``(X, y)`` matrices.

    Parameters mirror the reference's TimeSeriesDataset so fleet configs port
    verbatim: ``train_start_date`` / ``train_end_date`` (half-open range),
    ``tag_list``, optional ``target_tag_list`` (defaults to ``tag_list`` —
    the autoencoder X→X case), ``resolution`` (pandas offset, legacy "10T"
    accepted), ``row_filter`` (pandas query string evaluated on the joined
    frame), ``aggregation_methods``, and ``row_threshold`` (minimum rows
    after join, else :class:`InsufficientDataError`).
    """

    def __init__(
        self,
        train_start_date: Union[str, datetime],
        train_end_date: Union[str, datetime],
        tag_list: List,
        target_tag_list: Optional[List] = None,
        data_provider: Union[GordoBaseDataProvider, Dict[str, Any], None] = None,
        resolution: str = "10min",
        row_filter: Optional[str] = None,
        aggregation_methods: Union[str, List[str]] = "mean",
        row_threshold: int = 0,
        asset: Optional[str] = None,
        interpolation_method: str = "linear_interpolation",
        interpolation_limit: Optional[str] = "8H",
    ):
        self.train_start_date = _parse_date(train_start_date)
        self.train_end_date = _parse_date(train_end_date)
        if self.train_end_date <= self.train_start_date:
            raise ValueError(
                f"train_end_date ({self.train_end_date}) must be after "
                f"train_start_date ({self.train_start_date})"
            )
        self.tag_list = normalize_sensor_tags(tag_list, asset=asset)
        self.target_tag_list = (
            normalize_sensor_tags(target_tag_list, asset=asset)
            if target_tag_list
            else list(self.tag_list)
        )
        if data_provider is None:
            data_provider = RandomDataProvider()
        elif isinstance(data_provider, dict):
            data_provider = GordoBaseDataProvider.from_dict(data_provider)
        self.data_provider = data_provider
        self.resolution = resolution
        self.row_filter = row_filter
        self.aggregation_methods = aggregation_methods
        self.row_threshold = row_threshold
        self.asset = asset
        self.interpolation_method = interpolation_method
        self.interpolation_limit = interpolation_limit
        self._metadata: Dict[str, Any] = {}

        self._init_kwargs = {
            "train_start_date": self.train_start_date.isoformat(),
            "train_end_date": self.train_end_date.isoformat(),
            "tag_list": [t.to_dict() for t in self.tag_list],
            "target_tag_list": [t.to_dict() for t in self.target_tag_list],
            "data_provider": self.data_provider.to_dict(),
            "resolution": resolution,
            "row_filter": row_filter,
            "aggregation_methods": aggregation_methods,
            "row_threshold": row_threshold,
            "asset": asset,
            "interpolation_method": interpolation_method,
            "interpolation_limit": interpolation_limit,
        }

    def _columns_for(self, tags: List[SensorTag]) -> List[str]:
        """Joined-frame column names for ``tags`` under the configured
        aggregation (list aggregation suffixes columns per method)."""
        if isinstance(self.aggregation_methods, str):
            return [t.name for t in tags]
        return [
            f"{t.name}_{m}" for t in tags for m in self.aggregation_methods
        ]

    def get_data(self) -> Tuple[pd.DataFrame, pd.DataFrame]:
        # fetch the union of feature+target tags once, deduped by tag *name*
        # (the column identity); the FIRST spelling wins so a feature tag's
        # asset is never overridden by a colliding target tag
        seen: Dict[str, SensorTag] = {}
        for t in self.tag_list + self.target_tag_list:
            kept = seen.setdefault(t.name, t)
            if kept.asset != t.asset:
                logger.warning(
                    "Tag %r requested with conflicting assets %r and %r; "
                    "loading from %r",
                    t.name,
                    kept.asset,
                    t.asset,
                    kept.asset,
                )
        all_tags: List[SensorTag] = list(seen.values())
        series_iter = self.data_provider.load_series(
            self.train_start_date, self.train_end_date, all_tags
        )
        joined, tag_metadata = join_timeseries(
            series_iter,
            self.train_start_date,
            self.train_end_date,
            self.resolution,
            aggregation_methods=self.aggregation_methods,
            interpolation_method=self.interpolation_method,
            interpolation_limit=self.interpolation_limit,
        )
        filtered_count = 0
        if self.row_filter:
            before = len(joined)
            joined = joined.query(self.row_filter)
            filtered_count = before - len(joined)
        if len(joined) < self.row_threshold:
            raise InsufficientDataError(
                f"Only {len(joined)} rows after join/filter "
                f"(threshold {self.row_threshold})"
            )
        X = joined[self._columns_for(self.tag_list)].astype(np.float32)
        y = joined[self._columns_for(self.target_tag_list)].astype(np.float32)
        self._metadata = {
            "tag_loading_metadata": tag_metadata,
            "rows_filtered": filtered_count,
            "x_shape": list(X.shape),
            "y_shape": list(y.shape),
            "tag_list": [t.name for t in self.tag_list],
            "target_tag_list": [t.name for t in self.target_tag_list],
            "resolution": self.resolution,
            "train_start_date": self.train_start_date.isoformat(),
            "train_end_date": self.train_end_date.isoformat(),
            # full re-creatable config: the server's ?start&end fetch path
            # rebuilds the dataset from this (reference: server-side data
            # fetch via the dataset config embedded in build metadata)
            "dataset_config": self.to_dict(),
        }
        return X, y

    def get_metadata(self) -> Dict[str, Any]:
        return dict(self._metadata)


class RandomDataset(TimeSeriesDataset):
    """TimeSeriesDataset pre-wired to the deterministic RandomDataProvider —
    the reference's test workhorse."""

    def __init__(
        self,
        train_start_date: Union[str, datetime] = "2023-01-01T00:00:00+00:00",
        train_end_date: Union[str, datetime] = "2023-02-01T00:00:00+00:00",
        tag_list: Optional[List] = None,
        **kwargs: Any,
    ):
        if tag_list is None:
            tag_list = ["tag-%d" % i for i in range(4)]
        kwargs.setdefault("data_provider", RandomDataProvider(min_size=600, max_size=900))
        kwargs.setdefault("resolution", "10min")
        super().__init__(
            train_start_date=train_start_date,
            train_end_date=train_end_date,
            tag_list=tag_list,
            **kwargs,
        )
