"""Minimal InfluxDB 1.x HTTP client (stdlib-only) with a
``DataFrameClient``-compatible surface.

Reference parity: the reference's Influx stack (SURVEY.md §3
``dataset/data_provider/providers.py`` + ``client/forwarders.py``
[UNVERIFIED]) depends on the ``influxdb`` PyPI package, which this image
does not ship. Rather than leave the provider/forwarder stubbed behind an
ImportError (round-3 state: "experimental, fake-client-tested only"), this
module speaks the actual InfluxDB 1.x wire protocol with nothing but
``urllib``:

- ``write_points(dataframe, measurement, tags=...)`` serializes the frame
  to line protocol (escaping per the spec) and POSTs ``/write?db=...
  &precision=ns``;
- ``query(q)`` GETs ``/query?db=...&q=...&epoch=ns`` and parses the JSON
  ``results[].series[]`` envelope into ``{measurement: DataFrame}`` with a
  tz-aware UTC ``DatetimeIndex`` — the exact shape
  ``influxdb.DataFrameClient.query`` returns and
  :class:`~gordo_components_tpu.dataset.data_provider.providers.
  InfluxDataProvider` consumes.

:class:`InfluxDataProvider` and :class:`ForwardPredictionsIntoInflux`
fall back to this client when the ``influxdb`` package is absent (the
installed package, when present, stays preferred: it covers UDP, chunked
queries, retries and auth modes this minimal client does not). The wire
behavior is pinned by tests/test_influx.py against an in-repo HTTP double
(tests/influx_double.py) over real sockets.

Scope: HTTP(S) basic-auth + header auth, ns-precision writes, single-
statement InfluxQL queries. Not implemented: UDP, chunked responses,
``GROUP BY`` multi-series tag keys (each returned series must carry a
distinct ``name``).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from base64 import b64encode
from typing import Any, Dict, Optional

import numpy as np
import pandas as pd


class InfluxQueryError(RuntimeError):
    """A non-2xx ``/query`` or ``/write`` response, with the server body."""


def _escape(value: str, *, chars: str) -> str:
    if "\n" in value or "\r" in value:
        # line protocol has NO escape for newlines in identifiers — an
        # embedded one would split the point into a second, malformed line
        # (write-side mirror of the query-side quoting in providers.py)
        raise ValueError(
            f"newline in line-protocol identifier {value!r}; InfluxDB "
            "measurements/tags/field keys cannot contain line breaks"
        )
    out = value.replace("\\", "\\\\")
    for ch in chars:
        out = out.replace(ch, "\\" + ch)
    return out


def _escape_tag(value: str) -> str:
    # tag keys, tag values and field keys share one escape set
    return _escape(value, chars=",= ")


def _escape_measurement(value: str) -> str:
    return _escape(value, chars=", ")


def _field_value(value: Any) -> Optional[str]:
    """Line-protocol field literal, or None for missing values (NaN/None/
    NaT fields are OMITTED from the line — Influx has no null literal)."""
    if value is None:
        return None
    if isinstance(value, (bool, np.bool_)):
        return "true" if value else "false"
    if isinstance(value, (int, np.integer)):
        return f"{int(value)}i"
    if isinstance(value, (float, np.floating)):
        if np.isnan(value):
            return None
        return repr(float(value))
    try:  # pd.NaT and other pandas missing markers in object columns
        if pd.isna(value):
            return None
    except (TypeError, ValueError):  # arrays etc. — fall through to str
        pass
    s = str(value)
    if "\n" in s or "\r" in s:
        # quoted string values have no newline escape either — a raw one
        # splits the batch mid-line (same hazard as identifiers)
        raise ValueError(
            f"newline in string field value {s!r}; line protocol cannot "
            "represent line breaks"
        )
    s = s.replace("\\", "\\\\").replace('"', '\\"')
    return f'"{s}"'


class MinimalInfluxClient:
    """``influxdb.DataFrameClient`` work-alike over stdlib HTTP.

    Constructor kwargs mirror the package's client so provider configs are
    portable between the two; unknown kwargs are accepted and ignored for
    the same reason (e.g. ``pool_size``, ``retries``).
    """

    def __init__(
        self,
        host: str = "localhost",
        port: int = 8086,
        username: Optional[str] = None,
        password: Optional[str] = None,
        database: Optional[str] = None,
        ssl: bool = False,
        timeout: Optional[float] = 30.0,
        headers: Optional[Dict[str, str]] = None,
        **_ignored: Any,
    ):
        # kwargs that select a DIFFERENT transport must not be silently
        # dropped — a config written for the real package would construct
        # fine here and then speak the wrong protocol (plain HTTP instead
        # of UDP, unverified TLS instead of verified). Tuning kwargs
        # (pool_size, retries, ...) are safe to ignore.
        for key in ("use_udp", "udp_port", "proxies", "cert"):
            if _ignored.get(key):
                raise ValueError(
                    f"MinimalInfluxClient does not support {key!r}; install "
                    "the optional 'influxdb' package for that transport"
                )
        if _ignored.get("verify_ssl") is False:
            raise ValueError(
                "MinimalInfluxClient always verifies TLS; install the "
                "optional 'influxdb' package for verify_ssl=False"
            )
        scheme = "https" if ssl else "http"
        self._base = f"{scheme}://{host}:{port}"
        self._database = database
        self._timeout = timeout
        self._headers = dict(headers or {})
        if username is not None:
            cred = b64encode(
                f"{username}:{password or ''}".encode()
            ).decode("ascii")
            self._headers.setdefault("Authorization", f"Basic {cred}")

    # -- wire helpers ----------------------------------------------------
    def _request(
        self, path: str, params: Dict[str, str], body: Optional[bytes] = None
    ) -> bytes:
        url = f"{self._base}{path}?{urllib.parse.urlencode(params)}"
        req = urllib.request.Request(
            url, data=body, headers=self._headers, method="POST" if body is not None else "GET"
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode("utf-8", "replace")
            raise InfluxQueryError(
                f"InfluxDB {path} returned HTTP {exc.code}: {detail[:500]}"
            ) from exc

    # -- DataFrameClient surface -----------------------------------------
    def query(self, q: str, database: Optional[str] = None) -> Dict[str, pd.DataFrame]:
        """Run one InfluxQL statement; returns ``{series_name: DataFrame}``
        (empty dict for empty results), frames indexed by tz-aware UTC
        ``DatetimeIndex``."""
        params = {"q": q, "epoch": "ns"}
        db = database or self._database
        if db:
            params["db"] = db
        payload = json.loads(self._request("/query", params).decode())
        out: Dict[str, pd.DataFrame] = {}
        for result in payload.get("results", []):
            if "error" in result:
                raise InfluxQueryError(result["error"])
            for series in result.get("series", []):
                columns = series["columns"]
                frame = pd.DataFrame(series.get("values", []), columns=columns)
                if "time" in columns:
                    index = pd.to_datetime(frame.pop("time"), unit="ns", utc=True)
                    frame.index = index
                    frame.index.name = "time"
                out[series["name"]] = frame
        return out

    def write_points(
        self,
        dataframe: pd.DataFrame,
        measurement: str,
        tags: Optional[Dict[str, str]] = None,
        database: Optional[str] = None,
        **_ignored: Any,
    ) -> bool:
        """Write a time-indexed frame: columns become fields, ``tags`` apply
        to every point, timestamps are ns-precision."""
        if not isinstance(dataframe.index, pd.DatetimeIndex):
            raise TypeError(
                "write_points needs a DatetimeIndex-ed frame, got "
                f"{type(dataframe.index).__name__}"
            )
        index = dataframe.index
        if index.tz is None:
            index = index.tz_localize("UTC")
        # pandas >= 2 indexes can carry s/ms/us resolution — the int64 view
        # is only ns after an explicit as_unit (else writes land in 1970)
        index = index.as_unit("ns")
        tag_suffix = "".join(
            f",{_escape_tag(str(k))}={_escape_tag(str(v))}"
            for k, v in sorted((tags or {}).items())
        )
        prefix = _escape_measurement(measurement) + tag_suffix
        timestamps = index.view("int64")
        # serialize COLUMN-wise (never DataFrame.iterrows(): its row view
        # upcasts integer columns to float in numeric frames, turning 'Ni'
        # integer fields into floats — a field-type conflict against a
        # server where the field already exists as integer)
        columns = [
            (_escape_tag(str(col)), [_field_value(v) for v in dataframe[col]])
            for col in dataframe.columns
        ]
        lines = []
        for i, ts in enumerate(timestamps):
            fields = ",".join(
                f"{key}={literals[i]}"
                for key, literals in columns
                if literals[i] is not None
            )
            if not fields:  # all-NaN row: no valid line-protocol encoding
                continue
            lines.append(f"{prefix} {fields} {int(ts)}")
        if not lines:
            return True
        params = {"precision": "ns"}
        db = database or self._database
        if db:
            params["db"] = db
        self._request("/write", params, body="\n".join(lines).encode())
        return True

    def close(self) -> None:  # parity no-op: urllib holds no pooled sockets
        pass
