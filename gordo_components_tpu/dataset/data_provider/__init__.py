"""Data providers: fetch raw per-tag series for a time range.

Capability parity with the reference's ``gordo_components/dataset/data_provider/``
[UNVERIFIED — path-level citation]: an abstract provider contract
(``load_series`` / ``can_handle_tag`` / ``to_dict`` / ``from_dict``), a
deterministic synthetic provider (the universal test backend), a
file-system provider (per-tag parquet/CSV, the NcsReader/IrocReader
equivalent), and a gated InfluxDB provider.
"""

from .base import GordoBaseDataProvider
from .ncs_iroc import DataLakeProvider, IrocReader, NcsReader
from .providers import (
    RandomDataProvider,
    FileDataProvider,
    InfluxDataProvider,
    CompositeDataProvider,
    provider_from_dict,
)

__all__ = [
    "GordoBaseDataProvider",
    "RandomDataProvider",
    "FileDataProvider",
    "InfluxDataProvider",
    "CompositeDataProvider",
    "DataLakeProvider",
    "IrocReader",
    "NcsReader",
    "provider_from_dict",
]
