"""Concrete data providers.

Reference parity [UNVERIFIED, path-level]:

- ``RandomDataProvider`` ← ``gordo_components/dataset/data_provider/providers.py``
  (deterministic synthetic data; the universal test/bench backend)
- ``FileDataProvider`` ← ``ncs_reader.py`` / ``iroc_reader.py`` (per-tag
  parquet/CSV files under per-asset directories)
- ``InfluxDataProvider`` ← ``providers.py`` (InfluxQL reads over the real
  wire; uses the optional ``influxdb`` package when installed, else the
  in-repo stdlib client ``influx_client.py``)
- ``CompositeDataProvider`` ← ``DataLakeProvider``'s dispatch-by-asset shape
"""

from __future__ import annotations

import hashlib
import os
from datetime import datetime
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pandas as pd

from ..sensor_tag import SensorTag
from .base import GordoBaseDataProvider


def provider_from_dict(config: Dict[str, Any]) -> GordoBaseDataProvider:
    return GordoBaseDataProvider.from_dict(config)


class RandomDataProvider(GordoBaseDataProvider):
    """Deterministic synthetic per-tag series.

    Each tag's series is a smooth, seeded random walk plus sinusoidal
    structure, keyed by ``hash(tag.name) ^ seed`` so the same tag always
    produces the same data — the property every test and benchmark relies on
    (the reference's RandomDataProvider plays the same role).
    """

    def __init__(self, min_size: int = 100, max_size: int = 300, seed: int = 0):
        self._init_kwargs = {"min_size": min_size, "max_size": max_size, "seed": seed}
        self.min_size = min_size
        self.max_size = max_size
        self.seed = seed

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return True

    def _tag_seed(self, tag: SensorTag) -> int:
        digest = hashlib.md5(tag.name.encode()).digest()
        return (int.from_bytes(digest[:4], "little") ^ self.seed) & 0x7FFFFFFF

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        if train_end_date <= train_start_date:
            raise ValueError(
                f"train_end_date ({train_end_date}) must be after "
                f"train_start_date ({train_start_date})"
            )
        if dry_run:
            return
        for tag in tag_list:
            rng = np.random.default_rng(self._tag_seed(tag))
            n = int(rng.integers(self.min_size, self.max_size + 1))
            # n+1 points then drop the last: date_range(end=...) is
            # end-inclusive but the provider contract is half-open [start, end)
            index = pd.date_range(
                start=train_start_date, end=train_end_date, periods=n + 1, unit="ns"
            )[:-1]
            t = np.linspace(0.0, 8.0 * np.pi, n)
            values = (
                np.cumsum(rng.normal(scale=0.1, size=n))
                + np.sin(t + rng.uniform(0, 2 * np.pi))
                + rng.uniform(-5, 5)
            ).astype(np.float64)
            yield pd.Series(values, index=index, name=tag.name)


class FileDataProvider(GordoBaseDataProvider):
    """Read per-tag files from a directory tree.

    Layout: ``<base_dir>/[<asset>/]<tag_name>.{parquet|csv}``. CSV files must
    have columns ``(timestamp, value)``. This is the filesystem equivalent of
    the reference's NcsReader (yearly per-tag parquet under asset dirs) and
    IrocReader (concatenated CSV), collapsed into one provider since the
    split was an artifact of Equinor's two data-lake layouts.
    """

    def __init__(self, base_dir: str, assets: Optional[List[str]] = None):
        self._init_kwargs = {"base_dir": base_dir, "assets": assets}
        self.base_dir = base_dir
        self.assets = assets

    def _candidate_paths(self, tag: SensorTag) -> List[str]:
        stems = []
        if tag.asset:
            stems.append(os.path.join(self.base_dir, tag.asset, tag.name))
        stems.append(os.path.join(self.base_dir, tag.name))
        return [
            stem + ext for stem in stems for ext in (".parquet", ".csv")
        ]

    def can_handle_tag(self, tag: SensorTag) -> bool:
        if self.assets and tag.asset not in self.assets:
            return False
        return any(os.path.exists(p) for p in self._candidate_paths(tag))

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        for tag in tag_list:
            path = next(
                (p for p in self._candidate_paths(tag) if os.path.exists(p)), None
            )
            if path is None:
                raise FileNotFoundError(
                    f"No file for tag {tag.name!r} under {self.base_dir!r}"
                )
            if dry_run:
                continue
            if path.endswith(".parquet"):
                frame = pd.read_parquet(path)
            else:
                frame = pd.read_csv(path, parse_dates=["timestamp"])
            if "timestamp" in frame.columns:
                frame = frame.set_index("timestamp")
            series = frame["value"] if "value" in frame.columns else frame.iloc[:, 0]
            # naive file timestamps are interpreted as UTC so they compare
            # cleanly against tz-aware dataset date ranges (and vice versa)
            if getattr(series.index, "tz", None) is None and train_start_date.tzinfo is not None:
                series.index = series.index.tz_localize("UTC")
            elif getattr(series.index, "tz", None) is not None and train_start_date.tzinfo is None:
                series.index = series.index.tz_localize(None)
            series = series[(series.index >= train_start_date) & (series.index < train_end_date)]
            series.name = tag.name
            yield series


class InfluxDataProvider(GordoBaseDataProvider):
    """InfluxQL reads (``SELECT value FROM <measurement>``), parity with the
    reference's InfluxDataProvider.

    Client resolution: an injected ``client`` wins; else the ``influxdb``
    package's ``DataFrameClient`` when installed (it covers UDP/chunked/
    retry modes); else the in-repo stdlib
    :class:`~gordo_components_tpu.dataset.data_provider.influx_client.
    MinimalInfluxClient`, which speaks the real 1.x wire protocol (line-
    protocol writes, ``/query`` JSON) — round-tripped over real sockets
    against tests/influx_double.py, so the provider works out of the box
    with no optional dependency (VERDICT r3 #4).
    """

    def __init__(
        self,
        measurement: str = "sensor_data",
        value_name: str = "value",
        api_key: Optional[str] = None,
        api_key_header: Optional[str] = None,
        client: Any = None,
        **influx_config: Any,
    ):
        # NOTE: credentials (api_key, password) are deliberately NOT
        # serialized — to_dict() output is embedded in build metadata (served
        # at GET /metadata) and fleet YAML round-trips.
        self._init_kwargs = {
            "measurement": measurement,
            "value_name": value_name,
            **{k: v for k, v in influx_config.items() if k != "password"},
        }
        self.measurement = measurement
        self.value_name = value_name
        self.influx_config = influx_config
        if client is not None:
            # injected client (tests / pre-authenticated sessions); never
            # serialized
            self._client = client
            return
        headers = (
            {api_key_header or "Ocp-Apim-Subscription-Key": api_key}
            if api_key
            else None
        )
        try:
            import influxdb  # type: ignore

            self._client = influxdb.DataFrameClient(headers=headers, **influx_config)
        except ImportError:
            from .influx_client import MinimalInfluxClient

            self._client = MinimalInfluxClient(
                headers=headers, **influx_config
            )

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return True

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        for tag in tag_list:
            # escape InfluxQL string/identifier quoting — tag names come from
            # fleet YAML, not trusted code
            safe_tag = tag.name.replace("\\", "\\\\").replace("'", "\\'")
            safe_measurement = self.measurement.replace('"', '\\"')
            safe_value = self.value_name.replace('"', '\\"')
            query = (
                f'SELECT "{safe_value}" FROM "{safe_measurement}" '
                f"WHERE tag = '{safe_tag}' "
                f"AND time >= '{train_start_date.isoformat()}' "
                f"AND time < '{train_end_date.isoformat()}'"
            )
            if dry_run:
                # availability check only — don't pull the full range
                self._client.query(query + " LIMIT 1")
                continue
            result = self._client.query(query)
            frame = result.get(self.measurement, pd.DataFrame(columns=[self.value_name]))
            if self.value_name not in frame.columns:
                raise ValueError(
                    f"Influx result for tag {tag.name!r} has no "
                    f"{self.value_name!r} column (columns: "
                    f"{list(frame.columns)}); check value_name/measurement"
                )
            series = frame[self.value_name]
            # dataset assembly joins on tz-aware UTC timestamps; Influx
            # clients variously return naive or local-tz indexes
            if isinstance(series.index, pd.DatetimeIndex):
                if series.index.tz is None:
                    series = series.tz_localize("UTC")
                else:
                    series = series.tz_convert("UTC")
                series = series.sort_index()
            series.name = tag.name
            yield series


class FlakyDataProvider(GordoBaseDataProvider):
    """Fault-injection wrapper: delegates to ``provider`` but raises after
    ``fail_after`` successfully yielded series, for ``fail_times`` calls.

    Test-only (SURVEY.md §6.3 rebuild implication: "fault injection as a
    test-only provider that raises mid-stream") — exercises the builder's
    retry exit codes and the fleet's idempotent-resume path without real
    infrastructure failures.
    """

    def __init__(
        self,
        provider: Any = None,
        fail_after: int = 1,
        fail_times: int = 1,
        **provider_kwargs: Any,
    ):
        if provider is None:
            provider = RandomDataProvider(**provider_kwargs)
        elif isinstance(provider, dict):
            provider = GordoBaseDataProvider.from_dict(provider)
        self.provider = provider
        self.fail_after = fail_after
        self.fail_times = fail_times
        self._failures = 0
        self._init_kwargs = {
            "provider": provider.to_dict(),
            "fail_after": fail_after,
            "fail_times": fail_times,
        }

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return self.provider.can_handle_tag(tag)

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        yielded = 0
        for series in self.provider.load_series(
            train_start_date, train_end_date, tag_list, dry_run=dry_run
        ):
            if self._failures < self.fail_times and yielded >= self.fail_after:
                self._failures += 1
                raise IOError(
                    f"Injected provider failure after {yielded} series "
                    f"(failure {self._failures}/{self.fail_times})"
                )
            yielded += 1
            yield series


class CompositeDataProvider(GordoBaseDataProvider):
    """Dispatch each tag to the first sub-provider that can handle it —
    the shape of the reference's DataLakeProvider delegating to
    NcsReader/IrocReader by asset."""

    def __init__(self, providers: List[Any]):
        self.providers = [
            p if isinstance(p, GordoBaseDataProvider) else GordoBaseDataProvider.from_dict(p)
            for p in providers
        ]
        self._init_kwargs = {"providers": [p.to_dict() for p in self.providers]}

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return any(p.can_handle_tag(tag) for p in self.providers)

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        # preserve tag order; batch runs of consecutive tags that share a
        # provider into one load_series call so providers can reuse
        # connections / vectorize reads
        assignments: List[GordoBaseDataProvider] = []
        for tag in tag_list:
            provider = next((p for p in self.providers if p.can_handle_tag(tag)), None)
            if provider is None:
                raise ValueError(f"No provider can handle tag {tag!r}")
            assignments.append(provider)
        i = 0
        while i < len(tag_list):
            provider = assignments[i]
            j = i
            while j < len(tag_list) and assignments[j] is provider:
                j += 1
            yield from provider.load_series(
                train_start_date, train_end_date, tag_list[i:j], dry_run=dry_run
            )
            i = j


# Reference data-lake layout readers live in ncs_iroc.py; re-exported here so
# config dicts resolve them by bare name ("type": "NcsReader") through
# GordoBaseDataProvider.from_dict's default module.
from .ncs_iroc import DataLakeProvider, IrocReader, NcsReader  # noqa: E402,F401
