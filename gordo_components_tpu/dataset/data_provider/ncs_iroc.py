"""Reference data-lake layout readers: NCS, IROC, and the dispatching
DataLakeProvider.

Reference parity [UNVERIFIED, path-level — the reference mount is empty]:
``gordo_components/dataset/data_provider/ncs_reader.py``, ``iroc_reader.py``,
``azure_utils.py``. The reference reads Equinor's two data-lake layouts from
Azure Data Lake Store; here the "lake" is either a mounted filesystem path
(``base_dir``) or ADL reached through ``azure_utils.create_adl_filesystem``
(``storename`` + credentials) — the auth/dispatch plumbing is real and
test-injectable, and only the SDK import inside the default client factory
refuses in this offline image.

Layouts (reconstructed from SURVEY.md §3's component inventory):

- **NCS** (``NcsReader``): per-tag *yearly* files under per-asset
  directories::

      <base_dir>/<asset>/<tag_name>/<tag_name>_<year>.parquet   (or .csv)

  Parquet files carry a ``value`` column with a datetime index (or
  ``timestamp``/``value`` columns); CSVs carry ``timestamp,value`` rows.
  Missing year files inside the requested range are normal (a tag that
  started mid-history) and are skipped.

- **IROC** (``IrocReader``): *concatenated* CSVs — many tags in one file —
  under the asset directory::

      <base_dir>/<asset>/<anything>.csv   with columns  tag,timestamp,value

  Common reference-era column spellings (``item_name``, ``t``,
  ``average_value``) are normalized.

- **DataLakeProvider**: the auth-owning facade that dispatches each tag by
  asset to the right reader (NCS first — its per-tag directory layout is
  the more specific claim — then IROC), mirroring the reference's
  tag→asset→reader routing.
"""

from __future__ import annotations

import logging
import os
import threading
from datetime import datetime
from typing import Any, Dict, Iterable, List, Optional, Tuple

import pandas as pd

from ..sensor_tag import SensorTag
from .azure_utils import (
    LocalFileSystem,
    create_adl_filesystem,
    parse_dl_service_auth_str,
)
from .base import GordoBaseDataProvider

logger = logging.getLogger(__name__)


def _to_utc(ts: datetime) -> pd.Timestamp:
    stamp = pd.Timestamp(ts)
    return stamp.tz_localize("UTC") if stamp.tzinfo is None else stamp.tz_convert("UTC")


def _normalize_frame(frame: pd.DataFrame, origin: str) -> pd.Series:
    """(timestamp, value) frame/series-like → UTC-indexed float series."""
    columns = {str(c).lower(): c for c in frame.columns}
    if "timestamp" in columns:
        frame = frame.set_index(columns["timestamp"])
    if "value" in columns:
        values = frame[columns["value"]]
    elif frame.shape[1] == 1:
        values = frame.iloc[:, 0]
    else:
        raise ValueError(
            f"{origin}: expected a 'value' column (have {list(frame.columns)})"
        )
    index = pd.DatetimeIndex(pd.to_datetime(values.index, utc=True))
    return pd.Series(values.to_numpy(dtype=float), index=index)


class NcsReader(GordoBaseDataProvider):
    """Yearly per-tag files under per-asset directories (NCS layout).

    ``fs``: a :class:`~.azure_utils.LocalFileSystem`-shaped backend —
    local by default; DataLakeProvider passes an ADL filesystem when the
    lake is reached over Azure instead of a mount."""

    def __init__(
        self, base_dir: str, assets: Optional[List[str]] = None, fs=None
    ):
        self._init_kwargs = {"base_dir": base_dir, "assets": assets}
        self.base_dir = base_dir
        self.assets = assets
        self._fs = fs or LocalFileSystem()
        # POSITIVE resolutions only, bounded: can_handle_tag (dispatch) and
        # load_series both resolve the tag dir — over a remote filesystem
        # that is stat round trips, not free os calls. Misses stay
        # uncached so late-arriving tags are still found.
        self._dir_cache: Dict[Tuple[Optional[str], str], str] = {}

    def _tag_dir(self, tag: SensorTag) -> Optional[str]:
        key = (tag.asset, tag.name)
        cached = self._dir_cache.get(key)
        if cached is not None:
            return cached
        roots = []
        if tag.asset:
            roots.append(os.path.join(self.base_dir, tag.asset, tag.name))
        roots.append(os.path.join(self.base_dir, tag.name))
        found = next((root for root in roots if self._fs.isdir(root)), None)
        if found is not None:
            while len(self._dir_cache) >= 4096:
                self._dir_cache.pop(next(iter(self._dir_cache)))
            self._dir_cache[key] = found
        return found

    def can_handle_tag(self, tag: SensorTag) -> bool:
        if self.assets and tag.asset not in self.assets:
            return False
        return self._tag_dir(tag) is not None

    def _read_year(self, tag_dir: str, tag: SensorTag, year: int) -> Optional[pd.Series]:
        stem = os.path.join(tag_dir, f"{tag.name}_{year}")
        for ext in (".parquet", ".csv"):
            path = stem + ext
            try:  # open directly — an exists() probe first would double
                # the round trips on a remote filesystem
                handle = self._fs.open(path, "rb")
            except FileNotFoundError:
                continue
            with handle:
                if ext == ".parquet":
                    frame = pd.read_parquet(handle)
                else:
                    frame = pd.read_csv(handle)
            return _normalize_frame(frame, path)
        return None

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        start, end = _to_utc(train_start_date), _to_utc(train_end_date)
        for tag in tag_list:
            tag_dir = self._tag_dir(tag)
            if tag_dir is None:
                raise FileNotFoundError(
                    f"No NCS directory for tag {tag.name!r} "
                    f"(asset {tag.asset!r}) under {self.base_dir!r}"
                )
            if dry_run:
                continue
            pieces = []
            for year in range(start.year, end.year + 1):
                piece = self._read_year(tag_dir, tag, year)
                if piece is None:
                    logger.debug(
                        "NCS tag %r has no file for year %d (normal for "
                        "partial histories)",
                        tag.name,
                        year,
                    )
                    continue
                pieces.append(piece)
            if not pieces:
                raise FileNotFoundError(
                    f"NCS tag {tag.name!r}: no yearly files in "
                    f"[{start.year}, {end.year}] under {tag_dir!r}"
                )
            series = pd.concat(pieces).sort_index()
            series = series[(series.index >= start) & (series.index < end)]
            series.name = tag.name
            yield series


class IrocReader(GordoBaseDataProvider):
    """Concatenated many-tags-per-file CSVs under asset directories (IROC
    layout). Files are parsed once per (path, mtime) and cached."""

    _COLUMN_ALIASES = {
        "item_name": "tag",
        "sensor": "tag",
        "t": "timestamp",
        "time": "timestamp",
        "average_value": "value",
        "avg": "value",
    }

    def __init__(
        self, base_dir: str, assets: Optional[List[str]] = None, fs=None
    ):
        self._init_kwargs = {"base_dir": base_dir, "assets": assets}
        self.base_dir = base_dir
        self.assets = assets
        self._fs = fs or LocalFileSystem()
        # positive asset-dir resolutions (see NcsReader._dir_cache)
        self._dir_cache: Dict[str, str] = {}
        self._cache: Dict[Tuple[str, float], pd.DataFrame] = {}
        # concatenated per-asset frame, keyed by the (path, mtime) tuple of
        # its inputs — per-tag dispatch must not redo the concat per tag
        self._asset_cache: Dict[tuple, pd.DataFrame] = {}

    def _asset_dir(self, tag: SensorTag) -> Optional[str]:
        if not tag.asset:
            return None
        cached = self._dir_cache.get(tag.asset)
        if cached is not None:
            return cached
        path = os.path.join(self.base_dir, tag.asset)
        if not self._fs.isdir(path):
            return None
        while len(self._dir_cache) >= 1024:
            self._dir_cache.pop(next(iter(self._dir_cache)))
        self._dir_cache[tag.asset] = path
        return path

    def _asset_frame(self, asset_dir: str) -> pd.DataFrame:
        paths = [
            os.path.join(asset_dir, entry)
            for entry in self._fs.listdir(asset_dir)
            if entry.lower().endswith(".csv")
        ]
        asset_key = tuple((p, self._fs.mtime(p)) for p in paths)
        cached_asset = self._asset_cache.get(asset_key)
        if cached_asset is not None:
            return cached_asset
        frames = []
        for path, mtime in asset_key:
            key = (path, mtime)
            cached = self._cache.get(key)
            if cached is None:
                with self._fs.open(path, "rb") as handle:
                    frame = pd.read_csv(handle)
                frame.columns = [
                    self._COLUMN_ALIASES.get(str(c).lower(), str(c).lower())
                    for c in frame.columns
                ]
                missing = {"tag", "timestamp", "value"} - set(frame.columns)
                if missing:
                    raise ValueError(
                        f"IROC file {path!r} lacks columns {sorted(missing)} "
                        f"(have {list(frame.columns)})"
                    )
                frame["timestamp"] = pd.to_datetime(frame["timestamp"], utc=True)
                # drop stale cache entries for this path (old mtimes)
                for old in [k for k in self._cache if k[0] == path]:
                    del self._cache[old]
                self._cache[key] = frame
                cached = frame
            frames.append(cached)
        if not frames:
            raise FileNotFoundError(f"No IROC CSV files under {asset_dir!r}")
        result = pd.concat(frames, ignore_index=True)
        while len(self._asset_cache) >= 8:  # FIFO bound: interleaved-asset
            # tag lists stay cached; stale mtimes age out
            self._asset_cache.pop(next(iter(self._asset_cache)))
        self._asset_cache[asset_key] = result
        return result

    def can_handle_tag(self, tag: SensorTag) -> bool:
        if self.assets and tag.asset not in self.assets:
            return False
        return self._asset_dir(tag) is not None

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        start, end = _to_utc(train_start_date), _to_utc(train_end_date)
        # one freshness probe (listdir + per-file mtime) per asset per CALL,
        # not per tag — on a remote filesystem _asset_frame's cache-key
        # computation is network round trips, and many tags share an asset
        call_frames: Dict[str, pd.DataFrame] = {}
        for tag in tag_list:
            asset_dir = self._asset_dir(tag)
            if asset_dir is None:
                raise FileNotFoundError(
                    f"No IROC asset directory for tag {tag.name!r} "
                    f"(asset {tag.asset!r}) under {self.base_dir!r}"
                )
            if dry_run:
                continue
            frame = call_frames.get(asset_dir)
            if frame is None:
                frame = self._asset_frame(asset_dir)
                call_frames[asset_dir] = frame
            rows = frame[
                (frame["tag"] == tag.name)
                & (frame["timestamp"] >= start)
                & (frame["timestamp"] < end)
            ]
            if rows.empty:
                raise ValueError(
                    f"IROC asset {tag.asset!r} has no rows for tag "
                    f"{tag.name!r} in [{start}, {end})"
                )
            series = pd.Series(
                rows["value"].to_numpy(dtype=float),
                index=pd.DatetimeIndex(rows["timestamp"]),
                name=tag.name,
            ).sort_index()
            yield series


class DataLakeProvider(GordoBaseDataProvider):
    """The reference's auth-owning facade: routes each tag by asset to the
    reader that claims it (NCS's per-tag directory layout first, then
    IROC's concatenated CSVs).

    Two transports (VERDICT r3 #6):

    - ``base_dir`` set → the mounted lake, read with local ``os``
      semantics (unchanged fast path);
    - ``base_dir`` None + ``storename`` set → Azure Data Lake via
      :func:`~.azure_utils.create_adl_filesystem`: credentials resolve
      from ``dl_service_auth_str`` / the ``DL_SERVICE_AUTH_STR`` env var /
      ``interactive``, and the readers run against the ADL filesystem
      adapter. A *provided* credential is validated eagerly (a malformed
      config fails at construction, offline); an *absent* one is not an
      error until first use — ``to_dict()`` drops the secret, so
      ``from_dict()`` reconstruction must construct cleanly and resolve
      ``DL_SERVICE_AUTH_STR`` on the host that actually touches the lake.
      The SDK-touching client build is LAZY (first ``can_handle_tag``/
      ``load_series`` call, under a lock) so eagerly constructing
      providers for every config at server startup is safe, and the whole
      path is injectable (``client_factory`` for tests). Only the default
      factory's SDK import refuses in this offline image, at that first
      actual lake touch.

    ``adl_root``: lake-side path prefix the asset directories live under
    (Azure transport only; defaults to the lake root).
    """

    def __init__(
        self,
        base_dir: Optional[str] = None,
        interactive: bool = False,
        storename: Optional[str] = None,
        dl_service_auth_str: Optional[str] = None,
        assets: Optional[List[str]] = None,
        adl_root: str = "",
        client_factory: Optional[Any] = None,
        **kwargs: Any,
    ):
        # NOTE: dl_service_auth_str (a secret) and client_factory (an
        # object) are deliberately NOT serialized — to_dict() output lands
        # in served build metadata, mirroring InfluxDataProvider's rule
        self._init_kwargs = {
            "base_dir": base_dir,
            "interactive": interactive,
            "storename": storename,
            "assets": assets,
            **({"adl_root": adl_root} if adl_root else {}),
            **kwargs,
        }
        if base_dir is None and storename is None:
            raise ValueError(
                "DataLakeProvider needs a transport: base_dir=<mounted "
                "lake path>, or storename=<ADL store> with credentials "
                "(dl_service_auth_str / DL_SERVICE_AUTH_STR / interactive)"
            )
        self.interactive = interactive
        self.storename = storename
        self._assets = assets
        self._readers: Optional[List[GordoBaseDataProvider]] = None
        self._readers_lock = threading.Lock()
        if base_dir is not None:
            self.base_dir = base_dir
            self._make_fs = None  # readers default to the local filesystem
        else:
            self.base_dir = adl_root
            if dl_service_auth_str is not None:
                # a PROVIDED credential is validated now (malformed configs
                # fail at config time) — but an ABSENT one is not an error
                # yet: to_dict() deliberately drops the secret, so
                # from_dict() reconstruction (CompositeDataProvider, fleet
                # YAML round trips) must construct and resolve the env var
                # on the host that actually touches the lake
                parse_dl_service_auth_str(dl_service_auth_str)

            # the SDK/network-touching client build is deferred to first
            # use, so constructing providers eagerly (server startup over
            # many configs) cannot fail on transport
            def _make_fs():
                return create_adl_filesystem(
                    storename,
                    dl_service_auth_str=dl_service_auth_str,
                    interactive=interactive,
                    client_factory=client_factory,
                )

            self._make_fs = _make_fs

    def _get_readers(self) -> List[GordoBaseDataProvider]:
        with self._readers_lock:  # one auth token / one warm reader cache
            # even when concurrent requests race the first lake touch
            if self._readers is None:
                fs = self._make_fs() if self._make_fs is not None else None
                self._readers = [
                    NcsReader(self.base_dir, assets=self._assets, fs=fs),
                    IrocReader(self.base_dir, assets=self._assets, fs=fs),
                ]
            return self._readers

    def _reader_for(self, tag: SensorTag) -> GordoBaseDataProvider:
        for reader in self._get_readers():
            if reader.can_handle_tag(tag):
                return reader
        raise FileNotFoundError(
            f"No reader (NCS/IROC) can handle tag {tag.name!r} "
            f"(asset {tag.asset!r}) under {self.base_dir!r}"
        )

    def can_handle_tag(self, tag: SensorTag) -> bool:
        return any(r.can_handle_tag(tag) for r in self._get_readers())

    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        # contiguous same-reader runs batch into ONE reader call while
        # preserving the caller's tag order (the dataset joins series
        # positionally against tag_list) — per-tag [tag] calls would defeat
        # the readers' per-call memoization (IrocReader probes each asset's
        # files once per load_series call, round trips on a remote lake)
        run: List[SensorTag] = []
        run_reader: Optional[GordoBaseDataProvider] = None
        for tag in tag_list:
            reader = self._reader_for(tag)
            if reader is not run_reader and run:
                yield from run_reader.load_series(
                    train_start_date, train_end_date, run, dry_run=dry_run
                )
                run = []
            run_reader = reader
            run.append(tag)
        if run:
            yield from run_reader.load_series(
                train_start_date, train_end_date, run, dry_run=dry_run
            )
