"""Abstract data-provider contract.

Reference parity: ``gordo_components/dataset/data_provider/base.py``
[UNVERIFIED]. A provider yields one ``pd.Series`` per requested tag over a
half-open ``[train_start_date, train_end_date)`` range; ``can_handle_tag``
lets a composite provider dispatch per-tag to sub-readers by asset.
"""

from __future__ import annotations

import abc
from datetime import datetime
from typing import Any, Dict, Iterable, List

import pandas as pd

from ...utils.config import resolve_config_class
from ..sensor_tag import SensorTag


class GordoBaseDataProvider(abc.ABC):
    @abc.abstractmethod
    def load_series(
        self,
        train_start_date: datetime,
        train_end_date: datetime,
        tag_list: List[SensorTag],
        dry_run: bool = False,
    ) -> Iterable[pd.Series]:
        """Yield one series per tag covering ``[start, end)``.

        ``dry_run`` should verify availability (auth, paths) without reading
        bulk data — used by config validation, mirroring the reference.
        """

    @abc.abstractmethod
    def can_handle_tag(self, tag: SensorTag) -> bool:
        """Whether this provider knows how to read ``tag``."""

    def to_dict(self) -> Dict[str, Any]:
        """Serializable config; inverse of :meth:`from_dict`."""
        return {
            "type": f"{self.__class__.__module__}.{self.__class__.__name__}",
            **getattr(self, "_init_kwargs", {}),
        }

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "GordoBaseDataProvider":
        config = dict(config)
        type_path = config.pop("type", None)
        if type_path is None:
            raise ValueError("Provider config requires a 'type' key")
        provider_cls = resolve_config_class(
            type_path,
            GordoBaseDataProvider,
            default_module="gordo_components_tpu.dataset.data_provider.providers",
        )
        return provider_cls(**config)
