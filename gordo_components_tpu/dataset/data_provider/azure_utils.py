"""Azure Data Lake auth + filesystem abstraction for the lake readers.

Reference parity [UNVERIFIED, path-level]:
``gordo_components/dataset/data_provider/azure_utils.py`` — the
reference authenticates to Azure Data Lake Store Gen1 either
interactively (device-code flow) or with a service principal packed into
``dl_service_auth_str`` (``"<tenant>:<client_id>:<client_secret>"``,
also read from the ``DL_SERVICE_AUTH_STR`` env var), then hands the
readers an ``AzureDLFileSystem``.

This rebuild keeps all of that REAL except the final network touch
(VERDICT r3 #6): credential parsing, env-var resolution, the
interactive-vs-service-principal decision, and the filesystem adapter
the readers consume are plain importable code, exercised offline by
injecting a fake client factory. Only ``_default_client_factory`` needs
the Azure SDK + network, and it is the single place that refuses when
they are absent — a config carrying ``storename``/``dl_service_auth_str``
now exercises the whole dispatch path up to that line instead of being
rejected at construction.

The reader-facing surface is :class:`LakeFileSystem`-shaped (``isdir`` /
``exists`` / ``listdir`` / ``mtime`` / ``open``): :class:`LocalFileSystem`
implements it with ``os`` for mounted lakes, and :class:`ADLFileSystem`
adapts any ``AzureDLFileSystem``-shaped client (``exists``/``ls``/
``info``/``open``) — the real SDK object or a test fake.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, NamedTuple, Optional

ENV_AUTH_VAR = "DL_SERVICE_AUTH_STR"


class ServicePrincipal(NamedTuple):
    tenant: str
    client_id: str
    client_secret: str


def parse_dl_service_auth_str(auth_str: str) -> ServicePrincipal:
    """``"<tenant>:<client_id>:<client_secret>"`` → parts, validating shape
    early so a malformed credential fails at config time, not inside the
    SDK. Splits at most twice: a client SECRET may itself contain ':'."""
    parts = auth_str.split(":", 2)
    if len(parts) != 3:
        raise ValueError(
            "dl_service_auth_str must be '<tenant>:<client_id>:"
            f"<client_secret>' (got {len(parts)} ':'-separated parts)"
        )
    if not all(p.strip() for p in parts):
        blank = [
            name
            for name, part in zip(("tenant", "client_id", "client_secret"), parts)
            if not part.strip()
        ]
        raise ValueError(
            f"dl_service_auth_str has blank component(s): {blank}"
        )
    return ServicePrincipal(*(p.strip() for p in parts))


class LocalFileSystem:
    """The mounted-lake (and test) backend: plain ``os`` semantics."""

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def mtime(self, path: str) -> float:
        return os.path.getmtime(path)

    def open(self, path: str, mode: str = "rb"):
        return open(path, mode)


class ADLFileSystem:
    """Adapter from the ``AzureDLFileSystem`` client shape (``exists`` /
    ``ls`` / ``info`` / ``open``) to the reader-facing surface. Works
    against the real SDK client and any fake with the same four methods."""

    def __init__(self, client: Any):
        self._client = client

    def isdir(self, path: str) -> bool:
        try:
            info = self._client.info(path)
        except FileNotFoundError:
            # ONLY not-found maps to False — a PermissionError (ACL denial)
            # must surface as itself, or the operator debugs lake layout
            # instead of the actual auth problem
            return False
        return str(info.get("type", "")).upper() == "DIRECTORY"

    def exists(self, path: str) -> bool:
        return bool(self._client.exists(path))

    def listdir(self, path: str) -> List[str]:
        # ls returns full lake paths; readers join against the dir name, so
        # normalize to basenames like os.listdir
        return sorted(
            entry.rstrip("/").rsplit("/", 1)[-1]
            for entry in self._client.ls(path)
        )

    def mtime(self, path: str) -> float:
        info = self._client.info(path)
        # ADL Gen1 reports epoch milliseconds
        return float(info.get("modificationTime", 0)) / 1000.0

    def open(self, path: str, mode: str = "rb"):
        return self._client.open(path, mode)


def _default_client_factory(
    storename: str,
    principal: Optional[ServicePrincipal],
    interactive: bool,
) -> Any:
    """THE network/SDK touch: everything before this point runs offline.
    Raises a clear RuntimeError when the Azure SDK is absent (this image)."""
    try:
        from azure.datalake.store import core, lib  # type: ignore
    except ImportError as exc:
        raise RuntimeError(
            "Azure Data Lake access needs the 'azure-datalake-store' "
            "package (plus network), which this environment lacks. Mount "
            "the lake and pass base_dir=<mount point>, or inject "
            "client_factory=..."
        ) from exc
    if principal is not None:
        token = lib.auth(
            tenant_id=principal.tenant,
            client_id=principal.client_id,
            client_secret=principal.client_secret,
        )
    else:  # resolve_adl_credentials validated: no principal => interactive
        token = lib.auth()  # device-code flow on the operator's terminal
    return core.AzureDLFileSystem(token, store_name=storename)


def resolve_adl_credentials(
    dl_service_auth_str: Optional[str] = None, interactive: bool = False
) -> Optional[ServicePrincipal]:
    """The offline half of auth: explicit auth string > ``DL_SERVICE_AUTH_
    STR`` env var > interactive flag. Returns the parsed principal (None
    for interactive) or raises at CONFIG time — no SDK, no network."""
    auth_str = dl_service_auth_str or os.environ.get(ENV_AUTH_VAR)
    principal = parse_dl_service_auth_str(auth_str) if auth_str else None
    if principal is None and not interactive:
        raise ValueError(
            "DataLakeProvider without base_dir needs credentials: pass "
            f"dl_service_auth_str, set {ENV_AUTH_VAR}, or interactive=True"
        )
    return principal


def create_adl_filesystem(
    storename: str,
    dl_service_auth_str: Optional[str] = None,
    interactive: bool = False,
    client_factory: Optional[Callable[..., Any]] = None,
) -> ADLFileSystem:
    """Resolve credentials (:func:`resolve_adl_credentials`) and build the
    reader-facing filesystem. ``client_factory(storename, principal,
    interactive)`` is injectable so the full auth-resolution path runs in
    tests without SDK or network."""
    principal = resolve_adl_credentials(dl_service_auth_str, interactive)
    factory = client_factory or _default_client_factory
    return ADLFileSystem(factory(storename, principal, interactive))
