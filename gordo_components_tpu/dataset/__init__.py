"""Dataset layer: sensor tags, data providers, and time-series assembly.

Mirrors the capability surface of the reference's ``gordo_components/dataset``
package (SURVEY.md L1/L2) with a TPU-first twist: ``get_data`` produces
contiguous float32 matrices ready for device transfer, and all windowing is
done on-device with static shapes (see :mod:`gordo_components_tpu.ops`).
"""

from .base import GordoBaseDataset
from .dataset import TimeSeriesDataset, RandomDataset, join_timeseries
from .sensor_tag import SensorTag, normalize_sensor_tags

__all__ = [
    "GordoBaseDataset",
    "TimeSeriesDataset",
    "RandomDataset",
    "join_timeseries",
    "SensorTag",
    "normalize_sensor_tags",
]
