"""Canonical sensor-tag identity.

Capability parity with the reference's ``gordo_components/dataset/sensor_tag.py``
[UNVERIFIED — reference mount empty, path-level citation only]: a tag is a
``(name, asset)`` pair, and ``normalize_sensor_tags`` accepts the many spellings
that fleet YAML configs use (bare strings, ``[name, asset]`` lists,
``{"name": ..., "asset": ...}`` dicts, or ``SensorTag`` instances), inferring
the asset from tag-name prefix conventions when it is not given explicitly.
"""

from __future__ import annotations

import re
from typing import Dict, List, NamedTuple, Optional, Union


class SensorTag(NamedTuple):
    name: str
    asset: Optional[str] = None

    def to_dict(self) -> Dict[str, Optional[str]]:
        return {"name": self.name, "asset": self.asset}


class SensorTagNormalizationError(ValueError):
    """Raised when a tag spec cannot be resolved into a ``SensorTag``."""


# Prefix → asset conventions. The reference ships a site-specific table for
# Equinor installations; ours is configurable via ``register_tag_prefix`` and
# seeded with the same *shape* of convention (numeric plant prefixes).
TAG_PREFIX_TO_ASSET: Dict[str, str] = {
    "ASGB": "asgb",
    "GRA": "gra",
    "1901": "asgb",
    "1776": "gra",
    "1125": "kvb",
    "1138": "val",
}

_TAG_RE = re.compile(r"^([A-Za-z0-9]+)[._-]")


def register_tag_prefix(prefix: str, asset: str) -> None:
    """Extend the prefix→asset inference table (site-specific conventions)."""
    TAG_PREFIX_TO_ASSET[prefix.upper()] = asset


def _infer_asset(tag_name: str) -> Optional[str]:
    match = _TAG_RE.match(tag_name)
    if match:
        # a separator-delimited prefix is the tag's authoritative prefix: look
        # it up exactly, and do NOT fall through to the loose startswith scan
        # (else "GRADIENT.01" would wrongly match the "GRA" convention)
        return TAG_PREFIX_TO_ASSET.get(match.group(1).upper())
    # no separator (e.g. "1901TAG"): longest registered prefix at the start
    upper = tag_name.upper()
    best = None
    for prefix, asset in TAG_PREFIX_TO_ASSET.items():
        if upper.startswith(prefix) and (best is None or len(prefix) > len(best[0])):
            best = (prefix, asset)
    return best[1] if best else None


TagSpec = Union[str, List, Dict, SensorTag]


def normalize_sensor_tag(tag: TagSpec, asset: Optional[str] = None) -> SensorTag:
    """Resolve one tag spec into a ``SensorTag``.

    Accepted forms (matching the reference's accepted YAML spellings):

    - ``SensorTag`` — returned as-is
    - ``{"name": "TAG", "asset": "plant"}``
    - ``["TAG", "plant"]`` (a 2-list)
    - ``"TAG"`` — asset from the ``asset`` default or prefix inference
    """
    if isinstance(tag, SensorTag):
        return tag
    if isinstance(tag, dict):
        try:
            name = tag["name"]
        except KeyError as exc:
            raise SensorTagNormalizationError(
                f"Tag dict {tag!r} has no 'name' key"
            ) from exc
        return SensorTag(name=str(name), asset=tag.get("asset") or asset or _infer_asset(str(name)))
    if isinstance(tag, (list, tuple)):
        if len(tag) == 2:
            if tag[1] is None:
                return normalize_sensor_tag(str(tag[0]), asset)
            return SensorTag(name=str(tag[0]), asset=str(tag[1]))
        if len(tag) == 1:
            return normalize_sensor_tag(tag[0], asset)
        raise SensorTagNormalizationError(
            f"Tag list {tag!r} must have 1 or 2 elements (name[, asset])"
        )
    if isinstance(tag, str):
        return SensorTag(name=tag, asset=asset or _infer_asset(tag))
    raise SensorTagNormalizationError(f"Cannot normalize tag of type {type(tag)}: {tag!r}")


def normalize_sensor_tags(
    tag_list: List[TagSpec], asset: Optional[str] = None
) -> List[SensorTag]:
    """Normalize a heterogeneous list of tag specs into ``SensorTag`` objects."""
    return [normalize_sensor_tag(tag, asset=asset) for tag in tag_list]


def to_list_of_strings(tag_list: List[SensorTag]) -> List[str]:
    return [tag.name for tag in tag_list]
