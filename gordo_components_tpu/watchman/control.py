"""Watchman promoted from prober to control plane.

The original watchman (server.py in this package) OBSERVES a fleet:
``GET /`` polls every machine's healthz and reports. This module closes
the loop for the horizontal serving tier: the same probe machinery —
per-target circuit breakers, the quarantine ledger — now DRIVES repair.
A worker whose process died, or whose probes tripped its breaker
(unreachable / hung, not merely degraded), is ejected: quarantined,
terminated, and respawned through the supervisor; its recovery is
probe-verified like any quarantined machine's.

Probe scheduling carries ±``jitter_frac`` jitter (default ±10%): a large
fleet whose control planes all woke on the same tick would thundering-
herd every worker's ``/healthz`` simultaneously — and, worse, eject in
lockstep. Jitter decorrelates the fleet for free.

Health vocabulary (what the router reads per worker):

- ``ok`` — process alive, last probe answered 200 ready.
- ``degraded`` — answered, but named sick machines (still routable).
- ``draining`` — answered 503 with the draining marker: the worker is
  shutting down gracefully; route AROUND it, do not eject it (its exit
  is deliberate — a rolling restart in progress).
- ``unreachable`` — probe failed at transport level; breaker counts it.
- ``dead`` — the process itself is gone.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..analysis import lockcheck
from ..observability.registry import REGISTRY
from ..resilience import faults
from ..resilience.admission import DRAINING_HEADER
from ..resilience.breaker import BreakerBoard
from ..resilience.quarantine import Quarantine

logger = logging.getLogger(__name__)

__all__ = ["ControlPlane", "DRAINING_HEADER", "jittered_interval"]

_M_WORKER_PROBES = REGISTRY.counter(
    "gordo_watchman_worker_probes_total",
    "Control-plane worker health probes, by outcome (ok / degraded / "
    "draining / unhealthy / unreachable / dead / short_circuit)",
    labels=("outcome",),
)
_M_EJECTIONS = REGISTRY.counter(
    "gordo_watchman_worker_ejections_total",
    "Workers ejected (terminated + respawned) by the control plane, by "
    "cause (dead = process exited, unreachable = breaker tripped)",
    labels=("worker", "cause"),
)


def jittered_interval(
    interval: float,
    frac: float = 0.1,
    rng: Callable[[float, float], float] = random.uniform,
) -> float:
    """``interval`` ± ``frac`` (uniform): probe ticks across a fleet of
    control planes (and across this one's successive ticks) decorrelate
    instead of synchronizing into a thundering herd. ``rng`` is
    injectable so tests assert the bounds instead of sampling."""
    if interval <= 0:
        return 0.0
    return interval * (1.0 + frac * rng(-1.0, 1.0))


class ControlPlane:
    """Probe workers; eject and respawn the sick ones.

    ``supervisor``: a :class:`router.workers.WorkerSupervisor` (anything
    with ``specs / workers() / alive() / respawn()``). ``respawn``:
    False turns repair off (observe-only — the original watchman
    behavior, useful in tests and for a read-only status plane).

    The breaker board and quarantine ledger are PUBLIC: the router
    shares them, so a worker that probes unreachable is also skipped by
    routing within one probe cycle, and a routing failure burst
    contributes to the same circuit the prober reads.
    """

    def __init__(
        self,
        supervisor,
        probe_timeout: float = 3.0,
        breaker_recovery: float = 10.0,
        quarantine_cooldown: float = 10.0,
        respawn: bool = True,
        jitter_frac: float = 0.1,
        boot_grace: float = 30.0,
        clock=time.monotonic,
        history: int = 64,
    ):
        self.supervisor = supervisor
        self.probe_timeout = probe_timeout
        self.respawn = respawn
        self.jitter_frac = jitter_frac
        self.boot_grace = boot_grace
        self._clock = clock
        # respawn timestamps: a worker younger than boot_grace whose
        # probes fail is BOOTING, not sick — without this, probe failures
        # during a respawned worker's jax-import window would trip its
        # breaker and eject it again, a respawn storm that never converges
        self._spawned_at: Dict[str, float] = {}
        # per-WORKER circuits: only transport-level unreachability counts,
        # mirroring the watchman prober's host-circuit semantics
        self.breakers = BreakerBoard(
            recovery_time=breaker_recovery, clock=clock
        )
        self.quarantine = Quarantine(
            cooldown=quarantine_cooldown, clock=clock
        )
        self._lock = lockcheck.named_lock("watchman.control")
        self._last: Dict[str, Dict[str, Any]] = {}
        self._events: deque = deque(maxlen=history)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # pooled connections for the probe loop — the control plane's
        # steady-state hottest HTTP caller must not pay a TCP handshake
        # per worker per tick (and warm sockets keep the measured
        # /healthz latency honest)
        self._session = None

    def _http(self):
        import requests

        if self._session is None:
            self._session = requests.Session()
        return self._session

    # -- probing -------------------------------------------------------------
    def _probe_worker(self, name: str, spec) -> Dict[str, Any]:
        import requests

        worker = self.supervisor.worker(name)
        if worker is None or not worker.alive():
            return {"state": "dead", "error": "process not running"}
        breaker = self.breakers.get(name)
        if not breaker.allow():
            _M_WORKER_PROBES.labels("short_circuit").inc()
            return {
                "state": "unreachable",
                "error": (
                    f"circuit open; next probe in "
                    f"{breaker.retry_after():.0f}s"
                ),
                "short_circuit": True,
            }
        started = time.perf_counter()
        try:
            # chaos seam: `probe:<worker>:error` stands in for a wedged
            # worker without wedging one
            faults.inject("probe", name)
            response = self._http().get(
                f"{spec.base_url}/healthz", timeout=self.probe_timeout
            )
        except (requests.RequestException, faults.FaultInjected) as exc:
            with self._lock:
                spawned = self._spawned_at.get(name)
            if (
                spawned is not None
                and self._clock() - spawned < self.boot_grace
            ):
                # booting, not sick: don't feed the breaker, don't eject
                _M_WORKER_PROBES.labels("booting").inc()
                return {"state": "booting", "error": repr(exc)}
            breaker.record(False)
            _M_WORKER_PROBES.labels("unreachable").inc()
            return {
                "state": "unreachable",
                "error": repr(exc),
                "latency_ms": (time.perf_counter() - started) * 1000,
            }
        breaker.record(True)
        latency_ms = (time.perf_counter() - started) * 1000
        body: Dict[str, Any] = {}
        try:
            parsed = response.json()
            if isinstance(parsed, dict):
                body = parsed
        except ValueError:
            pass
        if response.headers.get(DRAINING_HEADER) or (
            body.get("status") == "draining"
        ):
            # deliberate shutdown in progress (rolling restart): route
            # around it, never eject it — ejecting would kill the very
            # drain that makes the restart zero-drop
            _M_WORKER_PROBES.labels("draining").inc()
            return {"state": "draining", "latency_ms": latency_ms}
        if response.status_code != 200 or not body.get("ready", True):
            _M_WORKER_PROBES.labels("unhealthy").inc()
            return {
                "state": "unhealthy",
                "error": f"HTTP {response.status_code}",
                "latency_ms": latency_ms,
            }
        state = "degraded" if body.get("status") == "degraded" else "ok"
        _M_WORKER_PROBES.labels(state).inc()
        return {
            "state": state,
            "latency_ms": latency_ms,
            "quarantined": sorted(body.get("quarantined") or {}),
            "generations": (body.get("store") or {}).get("generations"),
            "worker_id": body.get("worker_id"),
        }

    def probe_once(self) -> Dict[str, Dict[str, Any]]:
        """One probe sweep over every worker slot; drives eject/respawn.
        Returns the per-worker result map (also kept for ``status()``)."""
        # first sight of a slot stamps its spawn time: the INITIAL boot
        # deserves the same grace a respawn gets — without this, a
        # worker still importing jax when probing begins would be
        # ejected mid-boot (the trade: a worker already wedged when the
        # control plane starts waits out one boot_grace before eject)
        now = self._clock()
        with self._lock:
            for name in self.supervisor.specs:
                self._spawned_at.setdefault(name, now)
        results: Dict[str, Dict[str, Any]] = {}
        for name, spec in sorted(self.supervisor.specs.items()):
            result = self._probe_worker(name, spec)
            result["worker"] = name
            result["base_url"] = spec.base_url
            results[name] = result
            state = result["state"]
            if state == "dead":
                self._eject(name, "dead", result.get("error", ""))
            elif (
                state == "unreachable"
                and self.breakers.get(name).state != "closed"
                and not result.get("short_circuit")
            ):
                # the probe that TRIPPED (or re-opened) the circuit: the
                # worker is alive but not answering — eject it. Short-
                # circuited sweeps skip this: the previous eject already
                # acted, and the respawned worker deserves its boot time.
                self._eject(name, "unreachable", result.get("error", ""))
            elif state in ("ok", "degraded"):
                # boot complete: drop the grace so a LATER wedge ejects
                # promptly instead of waiting out the rest of the window
                with self._lock:
                    self._spawned_at.pop(name, None)
                if self.quarantine.recover(name):
                    self._note_event("recovered", name, "")
        with self._lock:
            self._last = results
        return results

    def _eject(self, name: str, cause: str, error: str) -> None:
        already = self.quarantine.is_quarantined(name)
        self.quarantine.quarantine(name, error or cause, "probe")
        if not already:
            _M_EJECTIONS.labels(name, cause).inc()
            self._note_event("ejected", name, f"{cause}: {error}")
        if self.respawn:
            try:
                self.supervisor.respawn(name, cause=cause)
                with self._lock:
                    self._spawned_at[name] = self._clock()
                self._note_event("respawned", name, cause)
            except Exception:
                logger.exception("Respawn of worker %s failed", name)
                self._note_event("respawn_failed", name, cause)

    def _note_event(self, event: str, worker: str, detail: str) -> None:
        with self._lock:
            self._events.append(
                {
                    "at": time.strftime("%Y-%m-%d %H:%M:%S%z"),
                    "event": event,
                    "worker": worker,
                    "detail": detail,
                }
            )
        logger.info("Control plane: %s %s (%s)", event, worker, detail)

    # -- router-facing health view -------------------------------------------
    def routable(self, name: str) -> bool:
        """May the router send traffic to this worker right now? Alive
        process, circuit not open, not mid-drain, not quarantined. A
        worker with NO probe history yet is routable (boot grace — the
        router's own forward failures will trip the breaker if not)."""
        if not self.supervisor.alive(name):
            return False
        if self.quarantine.is_quarantined(name):
            return False
        if self.breakers.get(name).state == "open":
            return False
        with self._lock:
            last = self._last.get(name)
        return last is None or last["state"] != "draining"

    def last_probe(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            result = self._last.get(name)
            return dict(result) if result else None

    def forget(self, name: str) -> None:
        """Drop all health state for a worker that LEFT the slot table
        (elastic retire, §20): probe history, spawn grace, quarantine
        entry, and its circuit — a retired worker must not haunt status
        views, and a future worker reusing the name starts clean."""
        with self._lock:
            self._last.pop(name, None)
            self._spawned_at.pop(name, None)
        self.quarantine.recover(name)
        forget = getattr(self.breakers, "forget", None)
        if callable(forget):
            forget(name)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            last = {name: dict(r) for name, r in self._last.items()}
            events = list(self._events)
        return {
            "workers": last,
            "routable": {
                name: self.routable(name)
                for name in sorted(self.supervisor.specs)
            },
            "circuits": self.breakers.states(),
            "quarantined": self.quarantine.quarantined(),
            "respawns": self.supervisor.respawn_counts(),
            "events": events[-20:],
        }

    # -- scheduling ----------------------------------------------------------
    def start(self, interval: float = 2.0) -> None:
        """Run the probe loop on a daemon thread, each tick separated by
        a JITTERED interval (±``jitter_frac``)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.probe_once()
                except Exception:
                    logger.exception("Control-plane probe sweep failed")
                self._stop.wait(
                    jittered_interval(interval, self.jitter_frac)
                )

        self._thread = threading.Thread(
            target=loop, name="gordo-control-plane", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._session is not None:
            try:
                self._session.close()
            except Exception:  # lint: allow-swallow(probe-session teardown while the plane stops; nothing left to count)
                pass
            self._session = None  # a restarted plane rebuilds its pool
