from .server import WatchmanServer, build_watchman_app, run_watchman

__all__ = ["WatchmanServer", "build_watchman_app", "run_watchman"]
