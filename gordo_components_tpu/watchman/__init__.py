from .server import (
    WatchmanServer,
    build_watchman_app,
    read_build_progress,
    run_watchman,
    watch_build_progress,
)

__all__ = [
    "WatchmanServer",
    "build_watchman_app",
    "read_build_progress",
    "run_watchman",
    "watch_build_progress",
]
