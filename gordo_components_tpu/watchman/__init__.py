from .control import ControlPlane, jittered_interval
from .server import (
    WatchmanServer,
    build_watchman_app,
    read_build_progress,
    run_watchman,
    watch_build_progress,
)

__all__ = [
    "ControlPlane",
    "WatchmanServer",
    "build_watchman_app",
    "jittered_interval",
    "read_build_progress",
    "run_watchman",
    "watch_build_progress",
]
