"""Watchman: fleet health aggregator.

Reference parity: ``gordo_components/watchman/server.py`` [UNVERIFIED] — a
small service configured with the project name and machine list; ``GET /``
polls every model endpoint's ``/healthz`` and reports which are up.

Here the fleet usually lives behind ONE multi-model server process (TPU
serving consolidation), so watchman polls
``{target}/gordo/v0/<project>/<machine>/healthz`` per machine — but the
machine list may also point at several hosts (``{machine: base_url}``),
matching the reference's one-deployment-per-model layout.
"""

from __future__ import annotations

import json
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Union

from werkzeug.wrappers import Request, Response

logger = logging.getLogger(__name__)


class WatchmanServer:
    def __init__(
        self,
        project: str,
        machines: Union[Sequence[str], Dict[str, str]],
        target_url: Optional[str] = None,
        timeout: float = 5.0,
        max_poll_workers: int = 32,
        manifest_path: Optional[str] = None,
    ):
        """``machines``: list of names served at ``target_url``, or an
        explicit ``{machine: base_url}`` map. Health polls fan out over a
        thread pool of ``max_poll_workers`` so a 1000-machine fleet with a
        few dead endpoints answers ``GET /`` in ~``timeout`` seconds, not
        ``n_dead * timeout``.

        ``manifest_path``: a fleet build's ``fleet_manifest.json``; when
        given, ``GET /`` also reports build progress (completed/pending
        counts and the pending names) read from the manifest — the
        reference's later watchman evolution replaced HTTP polling with
        k8s CRD status; the manifest is this rebuild's equivalent build
        source of truth (rewritten atomically after every slice)."""
        if isinstance(machines, dict):
            self.machine_urls = dict(machines)
        else:
            if target_url is None:
                raise ValueError(
                    "target_url is required when machines is a name list"
                )
            self.machine_urls = {name: target_url for name in machines}
        self.project = project
        self.timeout = timeout
        self.max_poll_workers = max(1, int(max_poll_workers))
        self.manifest_path = manifest_path

    def _check(self, machine: str, base_url: str) -> Dict:
        import requests

        url = (
            f"{base_url.rstrip('/')}/gordo/v0/{self.project}/{machine}/healthz"
        )
        started = time.perf_counter()
        try:
            response = requests.get(url, timeout=self.timeout)
            healthy = response.status_code == 200
        except requests.RequestException as exc:
            logger.warning("Watchman: %s unreachable: %r", machine, exc)
            healthy = False
        return {
            "endpoint": url,
            "target": machine,
            "healthy": healthy,
            "latency_ms": (time.perf_counter() - started) * 1000,
        }

    def _build_progress(self) -> Optional[Dict]:
        if not self.manifest_path:
            return None
        return read_build_progress(self.manifest_path)

    def status(self) -> Dict:
        targets = sorted(self.machine_urls.items())
        workers = min(self.max_poll_workers, max(1, len(targets)))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            endpoints: List[Dict] = list(
                pool.map(lambda mu: self._check(*mu), targets)
            )
        body = {
            "project-name": self.project,
            "ok": all(e["healthy"] for e in endpoints),
            "endpoints": endpoints,
        }
        build = self._build_progress()
        if build is not None:
            body["build"] = build
        return body

    def __call__(self, environ, start_response):
        request = Request(environ)
        if request.path in ("/", ""):
            body = self.status()
            status = 200
        elif request.path == "/healthz":
            body, status = {"ok": True}, 200
        else:
            body, status = {"error": "not found"}, 404
        response = Response(
            json.dumps(body), status=status, mimetype="application/json"
        )
        return response(environ, start_response)


def read_build_progress(manifest_path: str, pending_cap: int = 50) -> Dict:
    """Unioned fleet-build progress from the manifest file(s), or an error
    record when the path is set but unreadable (a monitor must see that the
    manifest is gone, not a silently vanished field).

    Multi-host builds write one manifest per process
    (``fleet_manifest.json`` + ``fleet_manifest.p<i>.json`` siblings — see
    build_fleet._write_manifest); the union is the fleet view: completed
    machines are the union of every file's, and a machine is pending only
    while NO process has completed it. Shared by the HTTP view and the CLI
    ``run-watchman --watch`` follower."""
    import glob
    import os

    stem, ext = os.path.splitext(manifest_path)
    paths = [manifest_path] + sorted(glob.glob(f"{stem}.p*{ext}"))
    try:
        completed: Dict = {}
        pending: set = set()
        updated = None
        for path in paths:
            with open(path) as fh:
                manifest = json.load(fh)
            completed.update(manifest.get("machines") or {})
            pending |= set(manifest.get("pending") or [])
            updated = max(updated or "", manifest.get("updated") or "")
        still_pending = sorted(pending - set(completed))
        return {
            "updated": updated or None,
            "n_completed": len(completed),
            "n_pending": len(still_pending),
            "pending": still_pending[:pending_cap],  # capped for 10k fleets
        }
    except (OSError, ValueError, AttributeError, TypeError) as exc:
        # wrong-shaped JSON (top-level list, null pending) must degrade
        # to an error field, not take the whole health view down
        return {"error": f"manifest unreadable: {exc}"}


def watch_build_progress(
    manifest_path: str,
    interval_s: float = 5.0,
    emit=print,
    sleep=time.sleep,
    max_iterations: Optional[int] = None,
) -> bool:
    """CRD-style build follower (the reference eventually replaced watchman
    HTTP polling with k8s CRD status — SURVEY §3 watchman row): emit one
    JSON progress line per interval from the manifest file(s), returning
    True once every machine is completed, False if ``max_iterations``
    elapsed first. No HTTP anywhere — this reads the same files the build
    writes atomically."""
    i = 0
    while True:
        progress = read_build_progress(manifest_path)
        emit(json.dumps(progress))
        if not progress.get("error") and progress.get("n_pending") == 0:
            return True
        i += 1
        if max_iterations is not None and i >= max_iterations:
            return False
        sleep(interval_s)


def build_watchman_app(
    project: str,
    machines: Union[Sequence[str], Dict[str, str]],
    target_url: Optional[str] = None,
    manifest_path: Optional[str] = None,
) -> WatchmanServer:
    return WatchmanServer(
        project, machines, target_url, manifest_path=manifest_path
    )


def run_watchman(
    project: str,
    machines: Union[Sequence[str], Dict[str, str]],
    target_url: Optional[str] = None,
    host: str = "0.0.0.0",
    port: int = 5556,
    manifest_path: Optional[str] = None,
) -> None:
    from werkzeug.serving import run_simple

    run_simple(
        host,
        port,
        build_watchman_app(
            project, machines, target_url, manifest_path=manifest_path
        ),
    )
