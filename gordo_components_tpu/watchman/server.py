"""Watchman: fleet health aggregator.

Reference parity: ``gordo_components/watchman/server.py`` [UNVERIFIED] — a
small service configured with the project name and machine list; ``GET /``
polls every model endpoint's ``/healthz`` and reports which are up.

Here the fleet usually lives behind ONE multi-model server process (TPU
serving consolidation), so watchman polls
``{target}/gordo/v0/<project>/<machine>/healthz`` per machine — but the
machine list may also point at several hosts (``{machine: base_url}``),
matching the reference's one-deployment-per-model layout.

Observability: every probe's duration and failure reason is surfaced
per-target in ``status()`` (a 4.9 s probe against a 5 s timeout is a
dying machine, not a healthy one) and counted into the process registry.
``GET /metrics`` scrapes each distinct model-server base URL's own
``/metrics`` JSON and aggregates the engine counters into ONE fleet-wide
view — the scrape-of-scrapes the reference's watchman never had.

Resilience: each target's probe runs behind a circuit breaker — an
UNREACHABLE endpoint (connect/read timeout, not an HTTP error answer)
trips its circuit after a few failures, and until the recovery window
elapses its probes short-circuit in microseconds. Without this, a
1000-machine fleet with a handful of dead hosts pays ``n_dead × timeout``
per ``GET /`` even with the thread pool absorbing most of it.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Union

from werkzeug.wrappers import Request, Response

from ..observability import exposition
from ..observability.registry import REGISTRY
from ..resilience import faults
from ..resilience.breaker import BreakerBoard

logger = logging.getLogger(__name__)

_M_PROBES = REGISTRY.counter(
    "gordo_watchman_probes_total",
    "Health probes issued, by outcome (healthy / unhealthy / unreachable "
    "/ short_circuit)",
    labels=("outcome",),
)
_M_PROBE_SECONDS = REGISTRY.histogram(
    "gordo_watchman_probe_seconds",
    "Per-target health-probe duration",
)


class WatchmanServer:
    def __init__(
        self,
        project: str,
        machines: Union[Sequence[str], Dict[str, str]],
        target_url: Optional[str] = None,
        timeout: float = 5.0,
        max_poll_workers: int = 32,
        manifest_path: Optional[str] = None,
        breaker_recovery: float = 30.0,
        breaker_clock=time.monotonic,
    ):
        """``machines``: list of names served at ``target_url``, or an
        explicit ``{machine: base_url}`` map. Health polls fan out over a
        thread pool of ``max_poll_workers`` so a 1000-machine fleet with a
        few dead endpoints answers ``GET /`` in ~``timeout`` seconds, not
        ``n_dead * timeout``.

        ``manifest_path``: a fleet build's ``fleet_manifest.json``; when
        given, ``GET /`` also reports build progress (completed/pending
        counts and the pending names) read from the manifest — the
        reference's later watchman evolution replaced HTTP polling with
        k8s CRD status; the manifest is this rebuild's equivalent build
        source of truth (rewritten atomically after every slice).

        ``breaker_recovery``: seconds a tripped target's circuit stays
        open before one probe tests it again (``breaker_clock`` is
        injectable so state-machine tests advance time, not sleep)."""
        if isinstance(machines, dict):
            self.machine_urls = dict(machines)
        else:
            if target_url is None:
                raise ValueError(
                    "target_url is required when machines is a name list"
                )
            self.machine_urls = {name: target_url for name in machines}
        self.project = project
        self.timeout = timeout
        self.max_poll_workers = max(1, int(max_poll_workers))
        self.manifest_path = manifest_path
        # last failure per target, kept ACROSS polls: a machine that is
        # healthy right now but failed an hour ago reads differently from
        # one that never failed (the reference's watchman forgot everything
        # between GETs)
        self._last_errors: Dict[str, str] = {}
        self._errors_lock = threading.Lock()
        # one circuit per HOST (base URL), shared by every machine probed
        # there: unreachability is a host property, so a dead host is
        # contained after min_calls timeouts TOTAL, not min_calls × N
        # machines. Only unreachability trips it — an endpoint that
        # ANSWERS (even 503) keeps its circuit closed.
        self._breakers = BreakerBoard(
            recovery_time=breaker_recovery, clock=breaker_clock
        )

    def _note_error(self, machine: str, error: str) -> None:
        stamped = f"{time.strftime('%Y-%m-%d %H:%M:%S%z')} {error}"
        with self._errors_lock:
            self._last_errors[machine] = stamped

    def _check(self, machine: str, base_url: str) -> Dict:
        import requests

        url = (
            f"{base_url.rstrip('/')}/gordo/v0/{self.project}/{machine}/healthz"
        )
        breaker = self._breakers.get(base_url.rstrip("/"))
        if not breaker.allow():
            # open circuit: the target was unreachable recently — answer
            # from state in microseconds instead of burning another timeout
            _M_PROBES.labels("short_circuit").inc()
            with self._errors_lock:
                last_error = self._last_errors.get(machine)
            return {
                "endpoint": url,
                "target": machine,
                "healthy": False,
                "latency_ms": 0.0,
                "error": (
                    f"circuit open (unreachable; next probe in "
                    f"{breaker.retry_after():.0f}s)"
                ),
                "last_error": last_error or "",
                "circuit": breaker.state,
                "generation": None,
                "verified": None,
            }
        started = time.perf_counter()
        error: Optional[str] = None
        reachable = True
        generation: Optional[str] = None
        verified: Optional[bool] = None
        try:
            # chaos seam: a `probe:<machine>:error` fault stands in for a
            # dead endpoint without anything actually dying
            faults.inject("probe", machine)
            response = requests.get(url, timeout=self.timeout)
            healthy = response.status_code == 200
            if not healthy:
                error = f"HTTP {response.status_code}"
            # artifact-integrity facet (store/): the machine healthz body
            # names the serving generation and its manifest-verify status —
            # surface them per target so a fleet operator sees WHICH gen
            # each machine runs (and a rollback taking effect) from one
            # watchman GET. Absent/non-JSON bodies (old servers) skip it.
            body = None
            json_fn = getattr(response, "json", None)
            if callable(json_fn):
                try:
                    body = json_fn()
                except ValueError:
                    body = None
            if isinstance(body, dict):
                generation = body.get("generation")
                verified = body.get("verified")
            _M_PROBES.labels("healthy" if healthy else "unhealthy").inc()
        except (requests.RequestException, faults.FaultInjected) as exc:
            logger.warning("Watchman: %s unreachable: %r", machine, exc)
            healthy = False
            reachable = False
            error = repr(exc)
            _M_PROBES.labels("unreachable").inc()
        breaker.record(reachable)
        probe_s = time.perf_counter() - started
        _M_PROBE_SECONDS.observe(probe_s)
        if error is not None:
            self._note_error(machine, error)
        with self._errors_lock:
            last_error = self._last_errors.get(machine)
        return {
            "endpoint": url,
            "target": machine,
            "healthy": healthy,
            "latency_ms": probe_s * 1000,
            # current probe's failure ('' when this probe succeeded) and
            # the most recent failure ever seen, timestamped — a slow/dead
            # machine is distinguishable from a healthy one at a glance
            "error": error or "",
            "last_error": last_error or "",
            "circuit": breaker.state,
            # serving generation + manifest-verify status from the machine
            # healthz body (None when the target predates the store)
            "generation": generation,
            "verified": verified,
        }

    def _build_progress(self) -> Optional[Dict]:
        if not self.manifest_path:
            return None
        return read_build_progress(self.manifest_path)

    def _slowest_request(self, base_url: str) -> Optional[Dict]:
        """The target server's slowest recorded request — the flight
        recorder's summary row (trace id, duration, dominant stage) from
        ``/debug/requests`` — or None when the target predates the
        recorder or is unreachable. One scrape per distinct base URL, so
        a 1000-machine single-server fleet costs one extra GET per
        status poll."""
        import requests

        # read-only breaker peek (allow() would consume the half-open
        # probe slot the health checks own): an unreachable host must not
        # cost an extra timeout per poll on top of its probe
        if self._breakers.get(base_url.rstrip("/")).state != "closed":
            return None
        url = f"{base_url.rstrip('/')}/debug/requests?limit=1"
        try:
            response = requests.get(url, timeout=self.timeout)
            if response.status_code != 200:
                return None
            json_fn = getattr(response, "json", None)
            body = json_fn() if callable(json_fn) else None
        except (requests.RequestException, ValueError):
            return None
        if not isinstance(body, dict):
            return None
        return body.get("slowest")

    def status(self) -> Dict:
        targets = sorted(self.machine_urls.items())
        workers = min(self.max_poll_workers, max(1, len(targets)))
        urls = sorted(set(self.machine_urls.values()))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            endpoints: List[Dict] = list(
                pool.map(lambda mu: self._check(*mu), targets)
            )
            slow = dict(zip(urls, pool.map(self._slowest_request, urls)))
        body = {
            "project-name": self.project,
            "ok": all(e["healthy"] for e in endpoints),
            "endpoints": endpoints,
            # non-closed circuits only: the interesting subset at a glance
            # (every endpoint entry carries its own "circuit" field too)
            "open-circuits": {
                name: state
                for name, state in self._breakers.states().items()
                if state != "closed"
            },
            # per-target slowest recorded request (flight recorder): the
            # "which trace do I open in Perfetto" pointer, fleet-wide
            "slow-requests": {
                url: summary for url, summary in slow.items()
                if summary is not None
            },
        }
        build = self._build_progress()
        if build is not None:
            body["build"] = build
        return body

    # engine.stats() fields that are meaningfully summable across model
    # servers — the fleet-wide totals a capacity dashboard wants
    _SUMMED_ENGINE_STATS = (
        "machines",
        "buckets",
        "compiled_programs",
        "dispatches",
        "batched_requests",
        "hot_machines",
        "hot_requests",
    )

    def _scrape_one(self, base_url: str) -> Dict:
        import requests

        url = f"{base_url.rstrip('/')}/metrics"
        started = time.perf_counter()
        try:
            response = requests.get(url, timeout=self.timeout)
            response.raise_for_status()
            body = response.json()
        except (requests.RequestException, ValueError) as exc:
            return {"error": repr(exc), "scrape_ms": (time.perf_counter() - started) * 1000}
        return {
            "engine": body.get("engine") or {},
            "latency": body.get("latency") or {},
            "scrape_ms": (time.perf_counter() - started) * 1000,
        }

    def metrics(self) -> Dict:
        """Scrape every distinct model-server base URL's ``/metrics`` JSON
        and aggregate the engine counters fleet-wide. One multi-model
        server hosting the whole fleet scrapes once; a per-host layout
        scrapes each host — either way the ``fleet`` block is the single
        place to read total dispatches, batched requests, and how many
        machines serve via the slow host path."""
        urls = sorted(set(self.machine_urls.values()))
        workers = min(self.max_poll_workers, max(1, len(urls)))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            scraped = dict(zip(urls, pool.map(self._scrape_one, urls)))
        fleet: Dict = {key: 0 for key in self._SUMMED_ENGINE_STATS}
        fleet["host_path_machines"] = {}
        up = 0
        for url, result in scraped.items():
            engine = result.get("engine")
            if engine is None:
                continue
            up += 1
            for key in self._SUMMED_ENGINE_STATS:
                value = engine.get(key)
                if isinstance(value, (int, float)):
                    fleet[key] += value
            # keep WHICH machines are slow, not just how many — prefixed
            # by target when several servers report
            for name, reason in (engine.get("host_path_machines") or {}).items():
                key = name if len(urls) == 1 else f"{url}/{name}"
                fleet["host_path_machines"][key] = reason
        return {
            "project-name": self.project,
            "targets-up": up,
            "targets-total": len(urls),
            "fleet": fleet,
            "targets": scraped,
        }

    def __call__(self, environ, start_response):
        request = Request(environ)
        if request.path in ("/", ""):
            body = self.status()
            status = 200
        elif request.path == "/healthz":
            body, status = {"ok": True}, 200
        elif request.path == "/metrics":
            if request.args.get("format") == "prometheus":
                # watchman's OWN series (probe counts/durations), text-form
                response = Response(
                    exposition.render_prometheus(REGISTRY),
                    content_type=exposition.CONTENT_TYPE,
                )
                return response(environ, start_response)
            body = self.metrics()
            status = 200
        else:
            body, status = {"error": "not found"}, 404
        response = Response(
            json.dumps(body), status=status, mimetype="application/json"
        )
        return response(environ, start_response)


def read_build_progress(manifest_path: str, pending_cap: int = 50) -> Dict:
    """Unioned fleet-build progress from the manifest file(s), or an error
    record when the path is set but unreadable (a monitor must see that the
    manifest is gone, not a silently vanished field).

    Multi-host builds write one manifest per process
    (``fleet_manifest.json`` + ``fleet_manifest.p<i>.json`` siblings — see
    build_fleet._write_manifest); the union is the fleet view: completed
    machines are the union of every file's, and a machine is pending only
    while NO process has completed it. Shared by the HTTP view and the CLI
    ``run-watchman --watch`` follower."""
    import glob
    import os

    stem, ext = os.path.splitext(manifest_path)
    paths = [manifest_path] + sorted(glob.glob(f"{stem}.p*{ext}"))
    try:
        completed: Dict = {}
        pending: set = set()
        updated = None
        for path in paths:
            with open(path) as fh:
                manifest = json.load(fh)
            completed.update(manifest.get("machines") or {})
            pending |= set(manifest.get("pending") or [])
            updated = max(updated or "", manifest.get("updated") or "")
        still_pending = sorted(pending - set(completed))
        return {
            "updated": updated or None,
            "n_completed": len(completed),
            "n_pending": len(still_pending),
            "pending": still_pending[:pending_cap],  # capped for 10k fleets
        }
    except (OSError, ValueError, AttributeError, TypeError) as exc:
        # wrong-shaped JSON (top-level list, null pending) must degrade
        # to an error field, not take the whole health view down
        return {"error": f"manifest unreadable: {exc}"}


def watch_build_progress(
    manifest_path: str,
    interval_s: float = 5.0,
    emit=print,
    sleep=time.sleep,
    max_iterations: Optional[int] = None,
) -> bool:
    """CRD-style build follower (the reference eventually replaced watchman
    HTTP polling with k8s CRD status — SURVEY §3 watchman row): emit one
    JSON progress line per interval from the manifest file(s), returning
    True once every machine is completed, False if ``max_iterations``
    elapsed first. No HTTP anywhere — this reads the same files the build
    writes atomically. Ticks are jittered ±10% (control.jittered_interval)
    so many followers over one shared filesystem don't all stat the
    manifests on the same beat."""
    from .control import jittered_interval

    i = 0
    while True:
        progress = read_build_progress(manifest_path)
        emit(json.dumps(progress))
        if not progress.get("error") and progress.get("n_pending") == 0:
            return True
        i += 1
        if max_iterations is not None and i >= max_iterations:
            return False
        sleep(jittered_interval(interval_s))


def build_watchman_app(
    project: str,
    machines: Union[Sequence[str], Dict[str, str]],
    target_url: Optional[str] = None,
    manifest_path: Optional[str] = None,
) -> WatchmanServer:
    return WatchmanServer(
        project, machines, target_url, manifest_path=manifest_path
    )


def run_watchman(
    project: str,
    machines: Union[Sequence[str], Dict[str, str]],
    target_url: Optional[str] = None,
    host: str = "0.0.0.0",
    port: int = 5556,
    manifest_path: Optional[str] = None,
) -> None:
    from werkzeug.serving import run_simple

    run_simple(
        host,
        port,
        build_watchman_app(
            project, machines, target_url, manifest_path=manifest_path
        ),
    )
