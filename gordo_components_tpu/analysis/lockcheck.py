"""Runtime lock-order validator: the witness half of lock discipline.

Static analysis (:mod:`.lock_discipline`) proposes an order from the
source; this module CONFIRMS it at runtime. With ``GORDO_LOCKCHECK=1``
every architectural lock is created through :func:`named_lock` /
:func:`named_condition` as a thin tracked wrapper: each acquisition
records (held-locks -> new-lock) edges per thread and immediately
checks them against the declared hierarchy in :mod:`.locks`. The
concurrency tests run with the validator on (see tests/conftest.py) and
fail on any violation; :func:`report` also re-checks the accumulated
edge set for cycles — redundant under a rank order, kept as the
belt-and-braces the ISSUE asks for.

With the knob off (the default), the factories return plain
``threading.Lock`` / ``threading.Condition`` objects — zero wrappers,
zero overhead, bit-identical behavior. Never enable in production
serving: every acquisition pays a thread-local list walk.

Same-NAME nesting across different instances (two buckets' hot locks)
would be reported as an inversion; no code path does that today, and
any future one should justify itself by renaming the second lock into
its own rank slot.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

from .locks import LOCK_RANKS


def _enabled() -> bool:
    return os.environ.get("GORDO_LOCKCHECK", "0").strip().lower() not in (
        "", "0", "false", "off", "no",
    )


enabled = _enabled()

_held = threading.local()          # per-thread stack of lock names
_state_lock = threading.Lock()     # guards the two tables below
_edges: Dict[Tuple[str, str], int] = {}   # (outer, inner) -> times seen
_violations: List[str] = []


def _stack() -> List[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _note_acquired(name: str) -> None:
    stack = _stack()
    for outer in stack:
        edge = (outer, name)
        with _state_lock:
            _edges[edge] = _edges.get(edge, 0) + 1
        if LOCK_RANKS[name] <= LOCK_RANKS[outer]:
            message = (
                f"lock-order violation on thread "
                f"{threading.current_thread().name!r}: acquired "
                f"{name!r} (rank {LOCK_RANKS[name]}) while holding "
                f"{outer!r} (rank {LOCK_RANKS[outer]}); declared order "
                "is strictly rank-increasing (analysis/locks.py)"
            )
            with _state_lock:
                _violations.append(message)
    stack.append(name)


def _note_released(name: str) -> None:
    stack = _stack()
    # release order may differ from acquisition order (with-blocks can
    # interleave with explicit acquire/release); remove the most recent
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


class TrackedLock:
    """A named ``threading.Lock`` recording acquisition order. Exposes
    the protocol ``threading.Condition`` needs (``_is_owned`` via owner
    tracking) so it can back a condition too."""

    def __init__(self, name: str):
        if name not in LOCK_RANKS:
            raise ValueError(
                f"lock {name!r} is not declared in analysis/locks.py — "
                "add it to LOCK_RANKS (and ARCHITECTURE §17)"
            )
        self._name = name
        self._lock = threading.Lock()
        self._owner: Optional[int] = None

    # -- lock protocol -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        acquired = self._lock.acquire(blocking, timeout)
        if acquired:
            self._owner = threading.get_ident()
            _note_acquired(self._name)
        return acquired

    def release(self) -> None:
        _note_released(self._name)
        self._owner = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc_info) -> None:
        self.release()

    # -- Condition support ---------------------------------------------------
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        # Condition.wait: the lock is dropped while waiting, so the
        # held-stack entry must go too (a notify-side acquisition during
        # our wait is NOT nested under us)
        _note_released(self._name)
        self._owner = None
        self._lock.release()

    def _acquire_restore(self, saved) -> None:
        self._lock.acquire()
        self._owner = threading.get_ident()
        _note_acquired(self._name)

    def __repr__(self) -> str:
        return f"<TrackedLock {self._name} {self._lock!r}>"


def named_lock(name: str):
    """A lock participating in the declared hierarchy: tracked under
    ``GORDO_LOCKCHECK=1``, a plain ``threading.Lock`` otherwise."""
    if not enabled:
        return threading.Lock()
    return TrackedLock(name)


def named_condition(name: str):
    """A condition whose underlying latch participates in the declared
    hierarchy (the wait/notify handoff is order-transparent: waiting
    releases the lock and re-entering records a fresh acquisition)."""
    if not enabled:
        return threading.Condition()
    return threading.Condition(TrackedLock(name))


# -- guarded-state runtime twin ----------------------------------------------


def assert_guard(name: str) -> None:
    """Runtime half of the guarded-state contract (GUARDED_FIELDS in
    :mod:`.locks`): mutation sites call this with the OWNING lock's name
    and, under ``GORDO_LOCKCHECK=1``, a violation is recorded when the
    calling thread does not hold it. The static checker
    (:mod:`.guarded_state`) proves the lexical shape; this witnesses
    the dynamic one — including every ``allow-unguarded`` escape and
    one-level blessing the static pass took on faith. With the knob off
    it is a single early return, cheap enough for dispatch-path
    mutation sites."""
    if not enabled:
        return
    if name not in LOCK_RANKS:
        raise ValueError(
            f"guard {name!r} is not declared in analysis/locks.py — "
            "add it to LOCK_RANKS (and ARCHITECTURE §21)"
        )
    if name not in _stack():
        import traceback

        # extract_stack(limit=2) keeps the two INNERMOST frames,
        # oldest-first: [0] is the mutation site that called
        # assert_guard, [1] is this frame
        site = traceback.extract_stack(limit=2)[0]
        message = (
            f"guarded-state violation on thread "
            f"{threading.current_thread().name!r}: mutation at "
            f"{site.filename}:{site.lineno} ({site.name}) without "
            f"holding its declared guard {name!r} "
            f"(held: {_stack() or 'nothing'})"
        )
        with _state_lock:
            _violations.append(message)


# -- reporting ---------------------------------------------------------------


def violations() -> List[str]:
    with _state_lock:
        return list(_violations)


def observed_edges() -> Dict[Tuple[str, str], int]:
    with _state_lock:
        return dict(_edges)


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _violations.clear()


def _find_cycle(edges: Set[Tuple[str, str]]) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for outer, inner in edges:
        graph.setdefault(outer, []).append(inner)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    path: List[str] = []

    def visit(node: str) -> Optional[List[str]]:
        color[node] = GRAY
        path.append(node)
        for nxt in graph.get(node, ()):
            state = color.get(nxt, WHITE)
            if state == GRAY:
                return path[path.index(nxt):] + [nxt]
            if state == WHITE:
                cycle = visit(nxt)
                if cycle is not None:
                    return cycle
        path.pop()
        color[node] = BLACK
        return None

    for node in list(graph):
        if color.get(node, WHITE) == WHITE:
            cycle = visit(node)
            if cycle is not None:
                return cycle
    return None


def report() -> List[str]:
    """All problems the run witnessed: per-acquisition rank violations
    plus a whole-graph cycle check over the observed edge set."""
    problems = violations()
    cycle = _find_cycle(set(observed_edges()))
    if cycle is not None:
        problems.append(
            "cycle in observed lock-acquisition graph: "
            + " -> ".join(cycle)
        )
    return problems
