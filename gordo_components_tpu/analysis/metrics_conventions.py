"""Metrics-conventions checker: the §7 contract, machine-checked.

Grammar (docs/ARCHITECTURE.md §7/§17): every registry metric is
``gordo_<component>_<noun>[_<unit>]`` where ``<component>`` is one of
the known layers; counters MUST end in ``_total``; histograms MUST end
in an explicit unit (``_seconds``, ``_bytes``, or a declared
dimensionless unit like ``_size``); gauges are current-state nouns and
must NOT carry ``_total``/``_seconds``. Labels come from the §7
allowlist — low-cardinality enums, never request data — and label
VALUES built from f-strings/concatenation are flagged as
unbounded-cardinality.

The grammar is exported for reuse as :func:`check_name` /
:func:`check_family_name`: ``tools/scrape_metrics.py --require-gordo``
validates live exposition family names with THIS grammar instead of
its own regex.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .astscan import Module, dotted, iter_calls
from .findings import Finding

CHECKER = "metrics-conventions"

# the known layers a metric may belong to (longest-prefix matched, so
# ``compile_cache`` wins over a hypothetical ``compile``)
COMPONENTS = (
    "server", "engine", "client", "build", "builds", "fleet", "watchman",
    "router", "resilience", "store", "compile_cache", "span", "stage",
    "drift", "lint", "slo", "autopilot", "mesh", "telemetry", "tenant",
    "incident",
)

# §7 label allowlist: low-cardinality enums only. ``machine``/``worker``/
# ``target`` are bounded by fleet/tier size — the documented exceptions.
# ``window`` is the two-value fast/slow burn-rate window enum (§18).
# ``precision`` is the three-value f32/bf16/int8 ladder enum (§19).
# ``actuator``/``direction`` are the autopilot's decision enums (§20).
# ``shard`` is bounded by the serving mesh's shard count (§23).
# ``tenant`` is bounded by the DECLARED tenant table — unknown header
# values fold into 'default' before any label is minted — and ``class``
# is the three-value interactive/standard/bulk enum (§25).
# ``actor`` is the control ledger's closed writer enum — unknown actors
# fold into 'operator' before the label is minted (§28).
ALLOWED_LABELS = frozenset(
    {
        "endpoint", "status", "kind", "outcome", "path", "event", "phase",
        "reason", "stage", "name", "trigger", "format", "worker",
        "machine", "target", "cause", "point", "to", "where", "error",
        "window", "precision", "actuator", "direction", "shard",
        "tenant", "class", "actor",
    }
)

# histogram unit suffixes: real units first, declared dimensionless
# units after (counts of things per observation window)
HIST_UNITS = (
    "seconds", "bytes", "size", "requests", "machines", "occupancy",
)

_NAME_RE = re.compile(r"^gordo(_[a-z0-9]+)+$")
_EXPOSITION_SUFFIXES = ("_bucket", "_count", "_sum")


def component_of(name: str) -> Optional[str]:
    rest = name[len("gordo_"):]
    best = None
    for component in COMPONENTS:
        if rest == component or rest.startswith(component + "_"):
            if best is None or len(component) > len(best):
                best = component
    return best


def check_name(name: str, kind: str) -> Optional[str]:
    """One metric name against the grammar; an error message or None.
    ``kind`` in counter/gauge/histogram — or 'family' for exposition
    names whose kind is unknown (grammar + component only)."""
    if not _NAME_RE.match(name):
        return (
            f"{name!r} is not gordo_<component>_<noun> "
            "(lower_snake_case, gordo_ prefix)"
        )
    if component_of(name) is None:
        return (
            f"{name!r} names no known component "
            f"(expected one of {', '.join(COMPONENTS)} after gordo_)"
        )
    if kind == "counter" and not name.endswith("_total"):
        return f"counter {name!r} must end in _total"
    if kind == "histogram" and not any(
        name.endswith("_" + unit) for unit in HIST_UNITS
    ):
        return (
            f"histogram {name!r} must end in an explicit unit "
            f"({', '.join('_' + u for u in HIST_UNITS)})"
        )
    if kind == "gauge" and name.endswith("_total"):
        return (
            f"gauge {name!r} ends in _total — that suffix is reserved "
            "for counters (gauges may carry unit suffixes like _seconds)"
        )
    return None


def check_family_name(name: str) -> Optional[str]:
    """Exposition-side validation (scrape_metrics): family names with
    the histogram suffixes stripped must still fit the grammar."""
    base = name
    for suffix in _EXPOSITION_SUFFIXES:
        if base.endswith(suffix):
            base = base[: -len(suffix)]
            break
    return check_name(base, "family")


_METRIC_FACTORIES = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}


def _registry_call(call: ast.Call) -> Optional[str]:
    """'counter'/'gauge'/'histogram' when this is a registry metric
    declaration (receiver named REGISTRY/registry/self.registry)."""
    name = dotted(call.func)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) < 2 or parts[-1] not in _METRIC_FACTORIES:
        return None
    receiver = parts[-2].lower()
    if receiver in ("registry", "_registry"):
        return parts[-1]
    return None


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _unbounded_value(node: ast.AST) -> bool:
    """Statically-unbounded label value: built per call site from
    runtime data (f-string, %-format, .format, concatenation)."""
    if isinstance(node, ast.JoinedStr):
        return any(
            isinstance(part, ast.FormattedValue) for part in node.values
        )
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Mod)
    ):
        return True
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        return bool(name) and name.split(".")[-1] == "format"
    return False


def check(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    for call in iter_calls(module.tree):
        kind = _registry_call(call)
        if kind is not None:
            findings.extend(_check_declaration(module, call, kind))
            continue
        name = dotted(call.func)
        if name and name.split(".")[-1] == "labels":
            for position, arg in enumerate(call.args):
                if _unbounded_value(arg):
                    findings.append(
                        Finding(
                            checker=CHECKER, code="unbounded-label-value",
                            file=module.relpath, line=call.lineno,
                            key=f"{name}:{position}",
                            message=(
                                "label value is built from runtime data "
                                "(f-string/format/concat) — unbounded "
                                "series cardinality"
                            ),
                            hint=(
                                "label with a closed enum and put the "
                                "variable part in the log/trace instead"
                            ),
                        )
                    )
    return findings


def _check_declaration(
    module: Module, call: ast.Call, kind: str
) -> List[Finding]:
    findings: List[Finding] = []
    name = _literal_str(call.args[0]) if call.args else None
    if name is None:
        for keyword in call.keywords:
            if keyword.arg == "name":
                name = _literal_str(keyword.value)
    if name is None:
        return findings  # dynamic name: tests build these; not a contract
    error = check_name(name, kind)
    if error is not None:
        findings.append(
            Finding(
                checker=CHECKER, code="bad-metric-name",
                file=module.relpath, line=call.lineno, key=name,
                message=error,
                hint="see the naming table in docs/ARCHITECTURE.md §7/§17",
            )
        )
    labels = _declared_labels(call)
    for label in labels or ():
        if label not in ALLOWED_LABELS:
            findings.append(
                Finding(
                    checker=CHECKER, code="unknown-label",
                    file=module.relpath, line=call.lineno,
                    key=f"{name}:{label}",
                    message=(
                        f"label {label!r} on {name!r} is not in the §7 "
                        "allowlist"
                    ),
                    hint=(
                        "use an existing label name, or extend "
                        "ALLOWED_LABELS in analysis/metrics_conventions.py "
                        "with an ARCHITECTURE note"
                    ),
                )
            )
    return findings


def _declared_labels(call: ast.Call) -> Optional[Tuple[str, ...]]:
    node = None
    for keyword in call.keywords:
        if keyword.arg in ("labels", "labelnames"):
            node = keyword.value
    if node is None:
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for element in node.elts:
            text = _literal_str(element)
            if text is None:
                return None
            out.append(text)
        return tuple(out)
    return None
