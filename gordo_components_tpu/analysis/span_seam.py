"""Span-seam checker: thread/asyncio handoffs must carry SpanContext.

The PR 4 regression class: work handed to another thread
(``threading.Thread(target=...)``, ``executor.submit(...)``,
``loop.call_soon_threadsafe(...)``, ``run_coroutine_threadsafe(...)``)
inherits NO contextvars, so spans recorded and log lines emitted on the
far side silently lose their timeline and ``X-Gordo-Trace-Id`` unless
the seam explicitly captures and re-binds a ``SpanContext``
(``spans.capture()`` at enqueue, ``spans.bind()`` /
``spans.record_into()`` on the far side). PR 5 fixed the instances;
this checker keeps the class fixed.

Rule: for every seam call whose target resolves to a function in the
same module, if the target's body (or, one level down, a same-module
callee's body) records spans or logs, then there must be binding
evidence — ``spans.bind`` / ``record_into`` / ``event_into`` in the
target's reachable bodies, or a ``spans.capture()`` in the enqueuing
function. Targets that neither record nor log (pure plumbing like a
server ``shutdown``) pass; unresolvable targets (callables from other
modules) are skipped rather than guessed at.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .astscan import Module, dotted, iter_calls, resolve_target
from .findings import Finding

CHECKER = "span-seam"

# seams where contextvars are lost
_SEAM_ATTRS = frozenset(
    {"submit", "call_soon_threadsafe", "run_coroutine_threadsafe"}
)
_BIND_EVIDENCE = ("bind", "record_into", "event_into")
_RECORD_ATTRS = frozenset({"stage", "event", "begin", "record_into",
                           "event_into", "add_span", "add_event"})
_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "error", "exception", "critical", "log"}
)


def _seam_target(call: ast.Call) -> Optional[ast.AST]:
    """The callable expression a seam call hands across threads, or
    None when this call is not a seam."""
    name = dotted(call.func)
    if not name:
        return None
    last = name.split(".")[-1]
    if last == "Thread" or name.endswith("threading.Thread"):
        for keyword in call.keywords:
            if keyword.arg == "target":
                return keyword.value
        return None
    if last in _SEAM_ATTRS and call.args:
        # executor.submit(fn, ...), loop.call_soon_threadsafe(fn),
        # asyncio.run_coroutine_threadsafe(coro_call, loop)
        if last == "submit" and _looks_like_queue_put(name):
            return None
        return call.args[0]
    return None


def _looks_like_queue_put(name: str) -> bool:
    # ``prefetcher.submit`` on an executor IS a seam; guard only against
    # obvious non-executor ``submit`` like the engine's bucket.submit —
    # whose receiver is a bucket, not a pool/executor.
    chain = [part.lower() for part in name.split(".")[:-1]]
    return any("bucket" in part or "engine" in part for part in chain)


def _records_spans(node: ast.AST) -> Optional[int]:
    for call in iter_calls(node):
        name = dotted(call.func)
        if not name:
            continue
        parts = name.split(".")
        if len(parts) >= 2 and parts[-2] == "spans" and (
            parts[-1] in _RECORD_ATTRS
        ):
            return call.lineno
    return None


def _logs(node: ast.AST) -> Optional[int]:
    for call in iter_calls(node):
        name = dotted(call.func)
        if not name:
            continue
        parts = name.split(".")
        if len(parts) >= 2 and parts[-1] in _LOG_METHODS and (
            "logger" in parts[-2] or parts[-2] == "logging"
        ):
            return call.lineno
    return None


def _has_bind_evidence(node: ast.AST) -> bool:
    for call in iter_calls(node):
        name = dotted(call.func)
        if not name:
            continue
        parts = name.split(".")
        if parts[-1] in _BIND_EVIDENCE:
            return True
    return False


def _has_capture(node: ast.AST) -> bool:
    for call in iter_calls(node):
        name = dotted(call.func)
        if name and name.split(".")[-1] == "capture":
            return True
    return False


def _reachable_bodies(module: Module, target: ast.AST) -> List[ast.AST]:
    """The target body plus one level of same-module callees."""
    bodies = [target]
    for call in iter_calls(target):
        name = dotted(call.func)
        if not name:
            continue
        parts = name.split(".")
        # sound resolution only: bare names and self.method (see _resolve)
        if len(parts) > 2 or (len(parts) == 2 and parts[0] != "self"):
            continue
        callee = module.functions.get(parts[-1])
        if callee is not None and callee is not target:
            bodies.append(callee)
    return bodies


def _own_calls(scope: ast.AST) -> List[ast.Call]:
    """Calls at this scope's own level — nested function bodies are
    their own scopes and must not be re-reported here."""
    nested: Set[int] = set()
    for sub in ast.walk(scope):
        if sub is scope:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            for inner in ast.walk(sub):
                nested.add(id(inner))
    return [
        call for call in iter_calls(scope) if id(call) not in nested
    ]


def check(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    scopes: List[ast.AST] = [module.tree]
    seen: Set[int] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if id(node) not in seen:
                seen.add(id(node))
                scopes.append(node)
    for scope in scopes:
        scope_name = getattr(scope, "name", "<module>")
        for call in _own_calls(scope):
            target_expr = _seam_target(call)
            if target_expr is None:
                continue
            target_name, target_node = resolve_target(
                module, scope, target_expr
            )
            if target_node is None:
                continue  # external callable; nothing to inspect
            bodies = _reachable_bodies(module, target_node)
            span_line = next(
                (line for line in map(_records_spans, bodies)
                 if line is not None), None,
            )
            log_line = next(
                (line for line in map(_logs, bodies) if line is not None),
                None,
            )
            if span_line is None and log_line is None:
                continue  # pure plumbing: no observability on the far side
            if any(_has_bind_evidence(body) for body in bodies):
                continue
            if _has_capture(scope):
                continue  # enqueue-side capture: ctx handed along explicitly
            what = []
            if span_line is not None:
                what.append(f"records spans (line {span_line})")
            if log_line is not None:
                what.append(f"logs (line {log_line})")
            findings.append(
                Finding(
                    checker=CHECKER, code="unbound-seam",
                    file=module.relpath, line=call.lineno,
                    key=f"{scope_name}:{target_name}",
                    message=(
                        f"{target_name!r} crosses a thread/asyncio seam "
                        f"and {' and '.join(what)} without binding a "
                        "SpanContext — its spans and log records lose "
                        "the request's trace id (the PR 4 bug class)"
                    ),
                    hint=(
                        "capture ctx = spans.capture() at the enqueue "
                        "site and wrap the far side in spans.bind(ctx) "
                        "(or record via spans.record_into/event_into)"
                    ),
                )
            )
    return findings
