"""THE declared lock order — the one copy both halves check against.

The serving system's locks form a strict hierarchy: a thread may only
acquire a lock of HIGHER rank than every lock it already holds. Rank
gaps are deliberate slack for future locks. The table below is the
machine-readable twin of docs/ARCHITECTURE.md §17; the static checker
(:mod:`.lock_discipline`) flags source-level acquisitions that violate
it, and the runtime validator (:mod:`.lockcheck`) fails real executions
whose observed order it forbids.

Why ranks and not an edge list: a total-ish order makes every nesting
decidable (no "we never declared that pair" ambiguity), and cycles are
impossible by construction — any cycle must contain a rank inversion.

``HOT_LOCKS`` are the request-path locks: holding one while making a
blocking call (device fetch, HTTP, joins, sleeps, XLA compiles) stalls
either live scoring traffic or the dispatch pipeline behind it, so the
static checker flags those calls. Deliberate exceptions carry a
``# lint: allow-blocking(<reason>)`` comment — the reason is mandatory.
"""

from __future__ import annotations

from typing import Dict, Tuple

# lock name -> rank. Acquisition must be strictly rank-increasing per
# thread. Locks never held together may share a rank tier spacing, but
# no two locks that can nest may share a rank.
LOCK_RANKS: Dict[str, int] = {
    # -- admin / control-plane outer locks (held across whole operations)
    "server.reload": 10,        # server.py _reload_lock: one reload at a time
    "fleet.reconcile": 11,      # reconciler.py _lock: held across repairs,
                                # which nest into every admin lock below
    "autopilot.state": 12,      # controller.py _lock: tick/decision state
    "autopilot.elastic": 13,    # elastic.py _lock: one scale op at a time
    "parallel.shard_plan": 14,  # shard_plan.py plan cache (boot/reload/router)
    "router.op": 15,            # rollout.py _op_lock: one rollout/rollback
    "fleet.spec": 16,           # spec.py _lock: journal cache + commits
                                # (reconciler rollback nests under 11/15)
    "server.admission": 20,     # admission.py gate condition
    "resilience.qos": 22,       # qos.py tenant quota table + header sketch
    "server.state_cond": 25,    # server.py _ServerState in-flight tracking
    "router.models": 30,        # router.py cached fleet model list
    "watchman.control": 35,     # control.py probe bookkeeping
    "router.rollout_state": 40, # rollout.py last-result state
    "router.workers": 45,       # workers.py supervisor slot table
    "router.placement": 50,     # placement.py ring + hot-tracking state
    "resilience.breaker_board": 55,  # breaker.py per-name board
    "resilience.breaker": 60,   # breaker.py one circuit's state
    "router.stitch": 52,        # router.py truncated-stitch pull ledger
    "resilience.quarantine": 62,  # quarantine.py ledger
    "resilience.faults": 64,    # faults.py injection plan
    "observability.incident": 65,  # incidents.py report ring + cooldowns
                                # (the correlator GATHERS lock-free; this
                                # only guards its in-memory state)
    "client.io": 66,            # client.py pooled-loop lifecycle
    "observability.telemetry": 67,  # telemetry.py warehouse index + segments
    "observability.slo": 68,    # slo.py evaluator history + breach state
    "observability.ledger": 69, # ledger.py control-event segments — a LEAF
                                # below every control-plane writer's lock
                                # (emit acquires nothing inside it)
    # -- engine data plane (innermost: these sit under everything above
    # via reload-time warmup and request-path scoring)
    "engine.bucket_cond": 70,   # _Bucket._cond leader/follower latch
    "engine.collector": 75,     # _Bucket._collector_lock handover
    "engine.hot": 80,           # _Bucket._hot_lock shard hot cache
    "engine.mega": 82,          # _Bucket._mega_lock residency routing
    "engine.host_cache": 84,    # host_cache.py LRU dict + byte ledger (§22)
    "engine.shard_dispatch": 90,  # process-global collective-launch lock
    # innermost of all: the traffic accountant's note() runs on the
    # request path inside scoring (§24) — nothing may nest under it
    "observability.traffic": 95,  # traffic.py sketch + EWMA pending state
}

# Request-hot-path locks: blocking calls under these stall live traffic
# (or the pipeline draining toward it). The admin locks — reload,
# rollout op, supervisor — deliberately block for seconds and are not
# listed.
HOT_LOCKS = frozenset(
    {
        "server.admission",
        "resilience.qos",
        "server.state_cond",
        "router.models",
        "router.placement",
        "router.stitch",
        "resilience.breaker_board",
        "resilience.breaker",
        "engine.bucket_cond",
        "engine.collector",
        "engine.hot",
        "engine.mega",
        "engine.host_cache",
        "engine.shard_dispatch",
        "observability.traffic",
    }
)

# (file suffix, attribute name) -> lock name: how the static checker
# maps a ``with self._hot_lock:`` (or module-global) expression in a
# given file onto the declared hierarchy. Attribute collisions across
# files (every module calls its lock ``_lock``) are resolved by the
# file suffix, which is why the mapping is keyed this way.
LOCK_ATTRS: Dict[Tuple[str, str], str] = {
    ("server/engine.py", "_SHARD_DISPATCH_LOCK"): "engine.shard_dispatch",
    ("server/engine.py", "_dispatch_lock"): "engine.shard_dispatch",
    ("server/engine.py", "_cond"): "engine.bucket_cond",
    ("server/engine.py", "_hot_lock"): "engine.hot",
    ("server/engine.py", "_mega_lock"): "engine.mega",
    ("server/engine.py", "_collector_lock"): "engine.collector",
    ("server/host_cache.py", "_lock"): "engine.host_cache",
    ("server/server.py", "_cond"): "server.state_cond",
    ("server/server.py", "_reload_lock"): "server.reload",
    ("resilience/admission.py", "_cond"): "server.admission",
    ("resilience/qos.py", "_lock"): "resilience.qos",
    ("resilience/breaker.py", "_lock"): "resilience.breaker",
    ("resilience/quarantine.py", "_lock"): "resilience.quarantine",
    ("resilience/faults.py", "_lock"): "resilience.faults",
    ("router/router.py", "_models_lock"): "router.models",
    ("router/router.py", "_stitch_lock"): "router.stitch",
    ("observability/slo.py", "_lock"): "observability.slo",
    ("observability/telemetry.py", "_lock"): "observability.telemetry",
    ("observability/traffic.py", "_lock"): "observability.traffic",
    ("observability/ledger.py", "_lock"): "observability.ledger",
    ("observability/incidents.py", "_lock"): "observability.incident",
    ("autopilot/controller.py", "_lock"): "autopilot.state",
    ("autopilot/elastic.py", "_lock"): "autopilot.elastic",
    ("parallel/shard_plan.py", "_PLAN_LOCK"): "parallel.shard_plan",
    ("router/rollout.py", "_op_lock"): "router.op",
    ("router/rollout.py", "_lock"): "router.rollout_state",
    ("router/placement.py", "_lock"): "router.placement",
    ("router/workers.py", "_lock"): "router.workers",
    ("watchman/control.py", "_lock"): "watchman.control",
    ("client/client.py", "_io_lock"): "client.io",
    ("fleet/spec.py", "_lock"): "fleet.spec",
    ("fleet/reconciler.py", "_lock"): "fleet.reconcile",
}


# (file suffix, attribute/global name) -> lock name: the GUARDED-STATE
# declaration. Every mutable field listed here is OWNED by one declared
# lock — any read or write outside a ``with <its lock>:`` scope is a
# static finding (:mod:`.guarded_state`) and, under ``GORDO_LOCKCHECK=1``,
# a runtime violation at mutation (:func:`.lockcheck.assert_guard`).
# Keyed like LOCK_ATTRS: the attribute names collide across files
# (``_hot`` is an engine cache AND a placement set), the file suffix
# disambiguates. ``__init__``/``__new__`` are exempt (construction
# happens-before publication); deliberate lock-free reads carry
# ``# lint: allow-unguarded(<reason>)`` — the reason is mandatory.
GUARDED_FIELDS: Dict[Tuple[str, str], str] = {
    # engine bucket state: the shard hot cache and the megabatch
    # residency slot table (§12/§15)
    ("server/engine.py", "_hot"): "engine.hot",
    ("server/engine.py", "_mega_slots"): "engine.mega",
    # layout-plan residency pins (§27): seed/steer the mega promoter
    ("server/engine.py", "_mega_pinned"): "engine.mega",
    # host-RAM spill tier: the LRU dict, its byte ledger, and the
    # in-flight prefetch claims (§22)
    ("server/host_cache.py", "_entries"): "engine.host_cache",
    ("server/host_cache.py", "_bytes"): "engine.host_cache",
    ("server/host_cache.py", "_inflight"): "engine.host_cache",
    # server in-flight tracking: the drain/quiesce latch (§16)
    ("server/server.py", "_inflight"): "server.state_cond",
    # admission counters: occupancy, queue depth, closed marker (§10)
    ("resilience/admission.py", "_inflight"): "server.admission",
    ("resilience/admission.py", "_waiting"): "server.admission",
    ("resilience/admission.py", "_waiting_by"): "server.admission",
    ("resilience/admission.py", "_closed"): "server.admission",
    ("resilience/admission.py", "_shed_level"): "server.admission",
    ("resilience/admission.py", "_class_sheds"): "server.admission",
    ("resilience/admission.py", "_releases"): "server.admission",
    # tenant quota table: raw-header sketch fed under the qos lock (§25)
    ("resilience/qos.py", "_header_sketch"): "resilience.qos",
    # fault-injection plan (module global, not an attribute)
    ("resilience/faults.py", "_rules"): "resilience.faults",
    # router: cached fleet model list + placement ring/rate state +
    # supervisor slot table (§16)
    ("router/router.py", "_models_cache"): "router.models",
    ("router/placement.py", "ring"): "router.placement",
    ("router/placement.py", "_rates"): "router.placement",
    ("router/placement.py", "_rotation"): "router.placement",
    ("router/placement.py", "_hot"): "router.placement",
    # mesh serving (§23): worker→shard table the candidate walk reorders
    # by, and the process-wide layout-plan cache
    ("router/placement.py", "_worker_shards"): "router.placement",
    ("parallel/shard_plan.py", "_PLAN_CACHE"): "parallel.shard_plan",
    ("router/workers.py", "_workers"): "router.workers",
    ("router/workers.py", "_respawns"): "router.workers",
    # SLO burn-rate history + breach edge state (§18)
    ("observability/slo.py", "_history"): "observability.slo",
    ("observability/slo.py", "_breached"): "observability.slo",
    ("observability/slo.py", "_breach_counts"): "observability.slo",
    # autopilot actuator state + decision journal (§20)
    ("autopilot/controller.py", "_state"): "autopilot.state",
    ("autopilot/controller.py", "_decisions"): "autopilot.state",
    # telemetry warehouse query index / byte ledger + the traffic
    # accountant's between-ticks pending counts and EWMA table (§24)
    ("observability/telemetry.py", "_index"): "observability.telemetry",
    ("observability/traffic.py", "_pending"): "observability.traffic",
    ("observability/traffic.py", "_rates"): "observability.traffic",
    # control ledger segment index + incident report ring (§28)
    ("observability/ledger.py", "_index"): "observability.ledger",
    ("observability/incidents.py", "_reports"): "observability.incident",
    # fleet spec journal cache + reconciler repair ring / WAL step map (§26)
    ("fleet/spec.py", "_records"): "fleet.spec",
    ("fleet/reconciler.py", "_ring"): "fleet.reconcile",
    ("fleet/reconciler.py", "_steps"): "fleet.reconcile",
}


def rank_of(name: str) -> int:
    return LOCK_RANKS[name]


def may_nest(outer: str, inner: str) -> bool:
    """Whether acquiring ``inner`` while holding ``outer`` respects the
    declared hierarchy (strictly increasing rank)."""
    return LOCK_RANKS[inner] > LOCK_RANKS[outer]
