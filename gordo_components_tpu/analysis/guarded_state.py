"""Static guarded-state checker: declared fields only under their lock.

:data:`.locks.GUARDED_FIELDS` maps mutable attributes (and module
globals) to the one lock that owns them. This checker flags any read or
write of a declared field outside a lexical ``with <its lock>:`` scope.
The lock-ordering checker (:mod:`.lock_discipline`) proves acquisitions
nest legally; THIS one proves the state those locks exist for is never
touched without them — the invariant a multi-host refactor must keep
while it moves state across processes.

Resolution mirrors :mod:`.lock_discipline`'s self/bare-callee rule,
made transitive by a fixpoint: a helper whose every same-module call
site (bare name or ``self.method``) sits inside ``with <lock>:`` — or
inside a function itself always called under it — is BLESSED for that
lock, because the caller's critical section extends into it (the
``_evaluate_locked`` → ``_journal_locked`` chains). A helper with any
unguarded call site (or none the checker can see — cross-object calls
like ``bucket._promote()`` are deliberately not resolved) gets no
blessing: annotate the access with
``# lint: allow-unguarded(<reason>)`` if the contract really holds.
``__init__``/``__new__`` are exempt — construction happens-before
publication.

The runtime twin is :func:`.lockcheck.assert_guard`: mutation sites
assert the guard is actually HELD under ``GORDO_LOCKCHECK=1``, so the
blessing above (and every escape hatch) is witnessed by real
executions, not just believed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astscan import Module, attr_chain_names, dotted
from .findings import Finding
from .locks import GUARDED_FIELDS, LOCK_ATTRS

CHECKER = "guarded-state"

_EXEMPT_SCOPES = frozenset({"__init__", "__new__"})


def _field_map_for(relpath: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for (suffix, attr), lock in GUARDED_FIELDS.items():
        if relpath.endswith(suffix):
            out[attr] = lock
    return out


def _lock_map_for(relpath: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for (suffix, attr), name in LOCK_ATTRS.items():
        if relpath.endswith(suffix):
            out[attr] = name
    return out


def _is_function(node: ast.AST) -> bool:
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))


class _Access:
    __slots__ = ("field", "lock", "line", "scope", "write")

    def __init__(self, field: str, lock: str, line: int, scope: str,
                 write: bool):
        self.field = field
        self.lock = lock
        self.line = line
        self.scope = scope
        self.write = write


class _ScopeWalk:
    """One function (or module) body: collect guarded-field accesses not
    lexically under their lock, plus every same-module call site with
    the lock set held at that site (for the blessing pass). Scope names
    are class-qualified (``Bucket._promote``) so that same-named
    methods of DIFFERENT classes never share blessing: ``self.method``
    resolves inside the walker's own class, bare names to module-level
    functions."""

    def __init__(self, module: Module, field_map: Dict[str, str],
                 lock_map: Dict[str, str], scope_name: str,
                 class_name: Optional[str] = None):
        self.module = module
        self.field_map = field_map
        self.lock_map = lock_map
        self.scope_name = scope_name
        self.class_name = class_name
        self.held: List[str] = []
        self.unguarded: List[_Access] = []
        # callee short name -> held-lock sets at its call sites here
        self.call_sites: Dict[str, List[Set[str]]] = {}

    def visit(self, node: ast.AST) -> None:
        if _is_function(node):
            return  # separate scope: walked on its own with no locks held
        # Lambdas are NOT skipped: their bodies are checked with the
        # locks lexically held at the definition site. The dominant
        # pattern is immediate invocation (a sort/max key under the
        # lock); a deferred lambda that escapes its critical section is
        # the same one-sided faith every lexical check here takes.
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    self._check_leaf(sub)
                lock = self._resolve_lock(item.context_expr)
                if lock is not None:
                    self.held.append(lock)
                    pushed += 1
            try:
                for child in node.body:
                    self.visit(child)
            finally:
                if pushed:
                    del self.held[-pushed:]
            return
        self._check_leaf(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _check_leaf(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name:
                parts = name.split(".")
                callee: Optional[str] = None
                if len(parts) == 1:
                    callee = parts[0]  # module-level function
                elif len(parts) == 2 and parts[0] == "self" and (
                    self.class_name is not None
                ):
                    callee = f"{self.class_name}.{parts[1]}"
                if callee is not None:
                    self.call_sites.setdefault(callee, []).append(
                        set(self.held)
                    )
        field: Optional[str] = None
        write = False
        if isinstance(node, ast.Attribute) and node.attr in self.field_map:
            field = node.attr
            write = isinstance(node.ctx, (ast.Store, ast.Del))
        elif isinstance(node, ast.Name) and node.id in self.field_map:
            # module-global guarded state (faults._rules); skip the
            # declaration site itself (module scope Store at import)
            if self.scope_name == "<module>" and isinstance(
                node.ctx, ast.Store
            ):
                return
            field = node.id
            write = isinstance(node.ctx, (ast.Store, ast.Del))
        if field is None:
            return
        lock = self.field_map[field]
        if lock in self.held:
            return
        self.unguarded.append(
            _Access(field, lock, node.lineno, self.scope_name, write)
        )

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        for name in attr_chain_names(expr):
            lock = self.lock_map.get(name)
            if lock is not None:
                return lock
        return None


def _blessed_guards(
    scope_names: Set[str],
    called_under: Dict[str, List[Tuple[str, Set[str]]]],
    relevant: Set[str],
) -> Dict[str, Set[str]]:
    """Least fixpoint: the set of guard locks PROVABLY held whenever
    each function runs. A function with no visible call site (an entry
    point, or one only reached through unresolvable receivers) holds
    nothing; otherwise it holds the intersection over call sites of
    (lexical locks at the site ∪ what the calling scope itself provably
    holds). Starting EMPTY and iterating upward matters: blessing must
    be earned from a real guarded entry point, never self-supported —
    an optimistic start would let a recursive function (or a mutual
    cycle) whose only visible call sites are its own bless itself for
    every lock. The transfer is monotone on the ⊆-lattice, so upward
    iteration terminates."""
    guards: Dict[str, Set[str]] = {name: set() for name in scope_names}
    guards["<module>"] = set()
    changed = True
    while changed:
        changed = False
        for name in scope_names:
            sites = called_under.get(name)
            if not sites:
                continue
            new: Optional[Set[str]] = None
            for caller, held in sites:
                effective = held | guards.get(caller, set())
                new = effective if new is None else (new & effective)
            new = (new or set()) & relevant
            if new != guards[name]:
                guards[name] = new
                changed = True
    return guards


def check(module: Module) -> List[Finding]:
    field_map = _field_map_for(module.relpath)
    if not field_map:
        return []
    lock_map = _lock_map_for(module.relpath)

    # function -> enclosing class (innermost), so scope names qualify
    enclosing_class: Dict[int, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            for child in ast.walk(node):
                if _is_function(child):
                    enclosing_class.setdefault(id(child), node.name)

    scopes: List[Tuple[str, Optional[str], ast.AST]] = [
        ("<module>", None, module.tree)
    ]
    for node in ast.walk(module.tree):
        if _is_function(node):
            cls = enclosing_class.get(id(node))
            name = f"{cls}.{node.name}" if cls else node.name
            scopes.append((name, cls, node))

    walks: List[_ScopeWalk] = []
    # qualified callee name -> (caller scope name, held-lock set) per site
    called_under: Dict[str, List[Tuple[str, Set[str]]]] = {}
    for scope_name, cls, scope_node in scopes:
        walk = _ScopeWalk(module, field_map, lock_map, scope_name, cls)
        for child in scope_node.body:  # type: ignore[attr-defined]
            walk.visit(child)
        walks.append(walk)
        for callee, held_sets in walk.call_sites.items():
            called_under.setdefault(callee, []).extend(
                (scope_name, held) for held in held_sets
            )

    guards = _blessed_guards(
        {walk.scope_name for walk in walks}, called_under,
        set(field_map.values()),
    )

    findings: List[Finding] = []
    flagged: Set[Tuple[str, str, str]] = set()
    for walk in walks:
        if walk.scope_name.rsplit(".", 1)[-1] in _EXEMPT_SCOPES:
            continue
        for access in walk.unguarded:
            # transitive blessing: every visible call-site chain of this
            # scope holds the guard -> the callers' critical sections
            # cover us
            if access.lock in guards.get(walk.scope_name, frozenset()):
                continue
            suppression = module.allows("unguarded", access.line)
            if suppression is not None:
                if not suppression.reason:
                    findings.append(
                        Finding(
                            checker=CHECKER, code="empty-escape-reason",
                            file=module.relpath, line=access.line,
                            key=f"{access.scope}:{access.field}",
                            message=(
                                "allow-unguarded escape hatch carries no "
                                "reason — the reason is the contract"
                            ),
                            hint=(
                                "write # lint: allow-unguarded(<why the "
                                "lock-free access is safe>)"
                            ),
                        )
                    )
                continue
            dedupe = (access.scope, access.field, access.lock)
            if dedupe in flagged:
                continue
            flagged.add(dedupe)
            verb = "mutates" if access.write else "reads"
            findings.append(
                Finding(
                    checker=CHECKER, code="unguarded-access",
                    file=module.relpath, line=access.line,
                    key=f"{access.field}:{access.scope}",
                    message=(
                        f"{access.scope} {verb} {access.field!r} outside "
                        f"'with <{access.lock}>:' — the field is declared "
                        f"guarded by {access.lock!r} (analysis/locks.py "
                        "GUARDED_FIELDS)"
                    ),
                    hint=(
                        "take the guarding lock, call this only from "
                        "under it, or annotate the line with "
                        "# lint: allow-unguarded(<reason>)"
                    ),
                )
            )
    return findings
