"""Exception-hygiene checker: no silent broad swallows.

A ``except Exception: pass`` (or bare ``except:``) that neither logs
nor publishes a counter erases evidence — the resilience layers (§10)
exist precisely so failures surface as ``gordo_resilience_*`` /
component series instead of vanishing. This checker flags broad
handlers whose body is INERT: no call at all (so no logger, no metric,
no cleanup), no ``raise``. A handler that calls anything is presumed to
be handling (cleanup counts as handling; the narrow-exception form is
always fine) — the rule targets the pure swallow the ISSUE names.

Escape hatch: ``# lint: allow-swallow(<reason>)`` on the ``except``
line; the reason is mandatory.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .astscan import Module
from .findings import Finding

CHECKER = "exception-hygiene"

_BROAD = frozenset({"Exception", "BaseException"})


def _broad_catch(handler: ast.ExceptHandler) -> Optional[str]:
    """'bare'/'Exception'/'BaseException' when the handler catches
    everything, else None."""
    node = handler.type
    if node is None:
        return "bare"
    candidates = node.elts if isinstance(node, ast.Tuple) else [node]
    for candidate in candidates:
        if isinstance(candidate, ast.Name) and candidate.id in _BROAD:
            return candidate.id
    return None


def _inert(handler: ast.ExceptHandler) -> bool:
    """True when the handler body does nothing observable: no call, no
    raise, and no use of the bound exception (``except ... as exc:``
    bodies that store ``exc`` somewhere propagate the error by value —
    the engine's ``it.error = exc`` fan-out — which is handling, not
    swallowing)."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Call)):
                return False
            if (
                handler.name
                and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)
            ):
                return False
    return True


def check(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        breadth = _broad_catch(node)
        if breadth is None or not _inert(node):
            continue
        suppression = module.allows("swallow", node.lineno)
        if suppression is not None:
            if not suppression.reason:
                findings.append(
                    Finding(
                        checker=CHECKER, code="empty-escape-reason",
                        file=module.relpath, line=node.lineno,
                        key=f"L{node.lineno}",
                        message=(
                            "allow-swallow escape hatch carries no "
                            "reason — the reason is the contract"
                        ),
                        hint="write # lint: allow-swallow(<why silence "
                             "is correct here>)",
                    )
                )
            continue
        label = "except:" if breadth == "bare" else f"except {breadth}:"
        scope = _enclosing_function(module, node)
        findings.append(
            Finding(
                checker=CHECKER, code="counterless-swallow",
                file=module.relpath, line=node.lineno,
                key=f"{scope}:{breadth}",
                message=(
                    f"{label} swallows every error without logging or "
                    "publishing a counter — failures here leave no "
                    "evidence in logs or gordo_* series"
                ),
                hint=(
                    "log it, count it (e.g. a gordo_<component>_*_total "
                    "outcome label), narrow the except, or annotate with "
                    "# lint: allow-swallow(<reason>)"
                ),
            )
        )
    return findings


def _enclosing_function(module: Module, target: ast.AST) -> str:
    """Innermost function containing ``target`` (key stability: line
    numbers move, scope names rarely do)."""
    best = "<module>"
    best_size = None
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                node.lineno <= target.lineno
                and target.lineno <= (node.end_lineno or node.lineno)
            ):
                size = (node.end_lineno or node.lineno) - node.lineno
                if best_size is None or size < best_size:
                    best = node.name
                    best_size = size
    return best
