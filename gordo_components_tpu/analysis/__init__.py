"""Machine-checked invariants: the repo's concurrency and conventions
contracts as analyzers, not prose.

Eight PRs grew the seed pipeline into a threaded serving system whose
correctness rules lived in ARCHITECTURE.md: lock ordering across the
engine/router/resilience layers, the thread/asyncio seam rule that spans
and log records must carry an explicit ``SpanContext`` (the PR 4 trace
loss), ``gordo_*`` metric naming and label conventions (§7), and the
``GORDO_*`` env-knob zoo. This package encodes those rules so
``gordo lint`` / ``make lint`` can search the tree for violations
(Automap's "search instead of hand-annotate", applied to our own
annotations):

- :mod:`.locks` — THE declared lock order (ranks), hot-lock set, and
  blocking-call vocabulary, shared by the static checker and the
  runtime validator.
- :mod:`.lock_discipline` — static lock-order + blocking-under-hot-lock
  checker (``# lint: allow-blocking(<reason>)`` escape hatches).
- :mod:`.span_seam` — thread/asyncio handoffs whose far side records
  spans or logs must capture-and-bind ``SpanContext``.
- :mod:`.metrics_conventions` — ``gordo_<component>_<noun>_<unit>``
  name grammar + §7 label allowlist (the grammar is also what
  ``tools/scrape_metrics.py --require-gordo`` validates with).
- :mod:`.knobs` / :mod:`.knob_registry` — every ``GORDO_*`` env read
  must be declared in the central knob registry; README's knob table
  is generated from it.
- :mod:`.lockcheck` — the optional ``GORDO_LOCKCHECK=1`` runtime
  validator: named locks record real acquisition orders during the
  concurrency tests and fail on any order the declaration forbids.
  Static analysis proposes, the runtime witness confirms.

Everything here is pure stdlib (``ast``) — ``make lint`` must run in
seconds without importing jax. Keep this ``__init__`` import-free for
the same reason: the engine imports :mod:`.lockcheck` at module scope.
"""
