"""``python -m gordo_components_tpu.analysis`` — the jax-free lint
entry point ``make lint`` calls (the ``gordo lint`` CLI verb delegates
here too)."""

import sys

from .runner import main

if __name__ == "__main__":
    sys.exit(main())
