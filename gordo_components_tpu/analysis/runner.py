"""``gordo lint`` / ``make lint`` entry point: run every checker, apply
the baseline, print ``file:line severity checker message`` findings.

Pure stdlib and import-light on purpose — the gate must run in seconds,
before any jax import could slow it down. Exit status: 0 = clean (no
non-baselined findings), 1 = findings, 2 = usage error.

Two-phase shape so the scan parallelizes: per-file checkers run in a
:func:`_scan_one` worker (``--jobs N`` fans files over processes; the
default ``--jobs 1`` stays in-process and deterministic), returning
findings + the cross-file EVIDENCE (knob mentions, wire-contract
producer/consumer sites, fault-seam references). The aggregate half —
wire finalize, fault finalize, stale knobs, README knob table — joins
the evidence single-threaded. Per-checker wall time is accumulated
either way and reported in the summary line (``--format json`` for CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from . import (
    exception_hygiene,
    fault_coverage,
    guarded_state,
    knob_registry,
    knobs,
    lock_discipline,
    metrics_conventions,
    span_seam,
    wire_contracts,
)
from .astscan import parse_module
from .findings import Baseline, Finding

# checker -> repo-relative path prefixes it runs over
SCOPES: Dict[str, Tuple[str, ...]] = {
    "lock-discipline": ("gordo_components_tpu/",),
    "guarded-state": ("gordo_components_tpu/",),
    "span-seam": (
        "gordo_components_tpu/server/",
        "gordo_components_tpu/client/",
        "gordo_components_tpu/router/",
        "gordo_components_tpu/watchman/",
    ),
    "metrics-conventions": (
        "gordo_components_tpu/", "tools/", "bench.py", "bench_serving.py",
    ),
    "knob-registry": (
        "gordo_components_tpu/", "tools/", "tests/", "bench.py",
        "bench_serving.py",
    ),
    # tests legitimately swallow in teardown helpers; the hygiene rule
    # covers the shipped tree
    "exception-hygiene": ("gordo_components_tpu/", "tools/"),
    "wire-contracts": ("gordo_components_tpu/", "tools/"),
    "fault-coverage": ("gordo_components_tpu/", "tools/", "tests/"),
}

KNOB_TABLE_BEGIN = "<!-- knob-table:begin (generated: make lint) -->"
KNOB_TABLE_END = "<!-- knob-table:end -->"


def repo_root(start: Optional[str] = None) -> str:
    """The checkout root: the directory holding gordo_components_tpu/."""
    here = os.path.abspath(start or os.path.dirname(__file__))
    probe = here
    while True:
        if os.path.isdir(os.path.join(probe, "gordo_components_tpu")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return os.path.abspath(start or os.getcwd())
        probe = parent


def _iter_files(root: str) -> List[str]:
    out: List[str] = []
    for prefix in ("gordo_components_tpu", "tools", "tests"):
        base = os.path.join(root, prefix)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [
                d for d in dirnames
                # lint_corpus: seeded-BAD snippets the analysis tests
                # feed the checkers directly — not part of the tree gate
                if d not in ("__pycache__", ".jax_compilation_cache",
                             "lint_corpus")
            ]
            for filename in sorted(filenames):
                if filename.endswith(".py"):
                    out.append(os.path.join(dirpath, filename))
    for single in ("bench.py", "bench_serving.py"):
        path = os.path.join(root, single)
        if os.path.exists(path):
            out.append(path)
    return out


def _in_scope(relpath: str, checker: str) -> bool:
    return relpath.startswith(SCOPES[checker]) or relpath in SCOPES[checker]


def _check_knob_table(root: str) -> List[Finding]:
    """README's knob table must equal the generated one."""
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, "r", encoding="utf-8") as handle:
            text = handle.read()
    except OSError:
        return []
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin == -1 or end == -1:
        return [
            Finding(
                checker="knob-registry", code="readme-table-missing",
                file="README.md", line=1, key="knob-table",
                message=(
                    "README.md has no generated knob-table block "
                    f"({KNOB_TABLE_BEGIN} ... {KNOB_TABLE_END})"
                ),
                hint="run: python -m gordo_components_tpu.analysis "
                     "--write-knob-table",
            )
        ]
    current = text[begin + len(KNOB_TABLE_BEGIN):end].strip()
    expected = knobs.render_markdown_table().strip()
    if current != expected:
        line = text[:begin].count("\n") + 1
        return [
            Finding(
                checker="knob-registry", code="readme-table-drift",
                file="README.md", line=line, key="knob-table",
                message=(
                    "README knob table differs from the registry in "
                    "analysis/knobs.py — docs drifted"
                ),
                hint="run: python -m gordo_components_tpu.analysis "
                     "--write-knob-table",
            )
        ]
    return []


def write_knob_table(root: str) -> bool:
    """Rewrite README's generated knob-table block in place."""
    readme = os.path.join(root, "README.md")
    with open(readme, "r", encoding="utf-8") as handle:
        text = handle.read()
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin == -1 or end == -1:
        return False
    rendered = (
        text[: begin + len(KNOB_TABLE_BEGIN)]
        + "\n"
        + knobs.render_markdown_table()
        + "\n"
        + text[end:]
    )
    with open(readme, "w", encoding="utf-8") as handle:
        handle.write(rendered)
    return True


# -- per-file scan (the parallelizable half) ----------------------------------

# (checker name, check callable) for the simple per-file checkers
_PER_FILE = (
    ("lock-discipline", lock_discipline.check),
    ("guarded-state", guarded_state.check),
    ("span-seam", span_seam.check),
    ("metrics-conventions", metrics_conventions.check),
    ("exception-hygiene", exception_hygiene.check),
)


def _scan_one(job: Tuple[str, str]) -> Dict[str, Any]:
    """Worker: parse one file, run every in-scope per-file checker, and
    collect the cross-file evidence. Returns only picklable data so
    ``--jobs N`` can fan it across processes."""
    path, relpath = job
    result: Dict[str, Any] = {
        "findings": [], "knob_mentions": set(), "wire": None,
        "fault": None, "timings": {},
    }
    module = parse_module(path, relpath)
    if module is None:
        result["findings"].append(
            Finding(
                checker="lint", code="unparseable", file=relpath,
                line=1, key=relpath,
                message="file does not parse; checkers skipped it",
            )
        )
        return result
    timings: Dict[str, float] = result["timings"]
    for checker, check in _PER_FILE:
        if _in_scope(relpath, checker):
            started = time.perf_counter()
            result["findings"].extend(check(module))
            timings[checker] = (
                timings.get(checker, 0.0) + time.perf_counter() - started
            )
    if _in_scope(relpath, "knob-registry") and (
        relpath != "gordo_components_tpu/analysis/knobs.py"
    ):
        # knobs.py itself is the registry: its literals would make
        # every registered knob count as "mentioned" (circular
        # staleness) and can never be unregistered
        started = time.perf_counter()
        result["findings"].extend(knob_registry.check(module))
        result["knob_mentions"] = knob_registry.collect_mentions(module)
        timings["knob-registry"] = (
            timings.get("knob-registry", 0.0)
            + time.perf_counter() - started
        )
    if _in_scope(relpath, "wire-contracts") and not relpath.startswith(
        "gordo_components_tpu/analysis/"
    ):
        # the registry module's own docstrings/specs are not evidence
        started = time.perf_counter()
        wire_findings, wire_evidence = wire_contracts.scan(module)
        result["findings"].extend(wire_findings)
        result["wire"] = wire_evidence
        timings["wire-contracts"] = (
            timings.get("wire-contracts", 0.0)
            + time.perf_counter() - started
        )
    if _in_scope(relpath, "fault-coverage") and not relpath.startswith(
        "gordo_components_tpu/analysis/"
    ):
        started = time.perf_counter()
        result["fault"] = fault_coverage.scan(module)
        timings["fault-coverage"] = (
            timings.get("fault-coverage", 0.0)
            + time.perf_counter() - started
        )
    return result


def run_lint(
    root: Optional[str] = None,
    jobs: int = 1,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    root = root or repo_root()
    if timings is None:
        timings = {}
    job_list = [
        (path, os.path.relpath(path, root).replace(os.sep, "/"))
        for path in _iter_files(root)
    ]
    if jobs == 0:
        jobs = os.cpu_count() or 1
    if jobs > 1:
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor

        # spawn, not fork: run_lint is also called in-process by the
        # test suite, where jax has already spun up worker threads —
        # forking a multithreaded process can deadlock in the child.
        # The analysis package imports in ~0.3s, so spawn stays cheap.
        with ProcessPoolExecutor(
            max_workers=jobs,
            mp_context=multiprocessing.get_context("spawn"),
        ) as pool:
            results = list(pool.map(_scan_one, job_list, chunksize=8))
    else:
        results = [_scan_one(job) for job in job_list]

    findings: List[Finding] = []
    mentions = set()
    wire_evidence = []
    fault_evidence = []
    for result in results:
        findings.extend(result["findings"])
        mentions |= result["knob_mentions"]
        if result["wire"] is not None:
            wire_evidence.append(result["wire"])
        if result["fault"] is not None:
            fault_evidence.append(result["fault"])
        for checker, spent in result["timings"].items():
            timings[checker] = timings.get(checker, 0.0) + spent

    started = time.perf_counter()
    findings.extend(wire_contracts.finalize(wire_evidence))
    timings["wire-contracts"] = (
        timings.get("wire-contracts", 0.0) + time.perf_counter() - started
    )
    started = time.perf_counter()
    findings.extend(fault_coverage.finalize(fault_evidence))
    timings["fault-coverage"] = (
        timings.get("fault-coverage", 0.0) + time.perf_counter() - started
    )

    started = time.perf_counter()
    # registered-but-unmentioned knobs. README PROSE counts as a
    # mention, but the generated knob-table block must NOT: it always
    # contains every registered knob (it is rendered FROM the
    # registry), so counting it would make the stale check circular
    # and dead knobs would live forever.
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, "r", encoding="utf-8") as handle:
            readme_text = handle.read()
    except OSError:
        readme_text = ""
    begin = readme_text.find(KNOB_TABLE_BEGIN)
    end = readme_text.find(KNOB_TABLE_END)
    if begin != -1 and end != -1:
        readme_text = readme_text[:begin] + readme_text[end:]
    # word-bounded: prose naming GORDO_COMPILE_CACHE_STORE must not
    # also count as a mention of its prefix GORDO_COMPILE_CACHE
    readme_mentions = set(knob_registry._KNOB_RE.findall(readme_text))
    findings.extend(
        knob_registry.stale_knobs(set(mentions) | readme_mentions)
    )
    findings.extend(_check_knob_table(root))
    timings["knob-registry"] = (
        timings.get("knob-registry", 0.0) + time.perf_counter() - started
    )
    return findings


def _render_timings(timings: Dict[str, float]) -> str:
    return ", ".join(
        f"{checker} {spent:.2f}s"
        for checker, spent in sorted(
            timings.items(), key=lambda item: -item[1]
        )
    )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="gordo lint",
        description=(
            "Invariant linter: lock discipline, guarded state, span "
            "seams, wire contracts, fault-seam coverage, exception "
            "hygiene, metric conventions, knob registry "
            "(docs/ARCHITECTURE.md §17/§21)."
        ),
    )
    parser.add_argument("--root", default=None,
                        help="checkout root (default: auto-detect)")
    parser.add_argument("--baseline", default=None,
                        help="baseline path (default: <root>/lint_baseline"
                             ".json)")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="parallel per-file scan processes "
                             "(0 = one per CPU; default 1, in-process)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="output format (json: one object with "
                             "findings/baselined/timings, CI-friendly)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather every current finding into the "
                             "baseline (reasons start as TODO — fill them "
                             "in; a TODO-stubbed entry is itself reported "
                             "as baseline[unjustified-keep] until a real "
                             "reason lands)")
    parser.add_argument("--write-knob-table", action="store_true",
                        help="regenerate README.md's knob table from "
                             "analysis/knobs.py and exit")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print findings the baseline suppresses")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    if args.write_knob_table:
        if not write_knob_table(root):
            print("README.md has no knob-table markers", file=sys.stderr)
            return 2
        print("README.md knob table regenerated")
        return 0

    started = time.perf_counter()
    timings: Dict[str, float] = {}
    findings = run_lint(root, jobs=args.jobs, timings=timings)
    baseline_path = args.baseline or os.path.join(root, "lint_baseline.json")
    baseline = Baseline.load(baseline_path)

    if args.write_baseline:
        # rebuild from CURRENT findings: existing reasons survive, new
        # findings start as TODO, and entries whose violation is gone
        # are pruned — a freshly written baseline always gates clean
        baseline.entries = {
            finding.ident: baseline.entries.get(
                finding.ident, "TODO: justify"
            )
            for finding in findings
        }
        baseline.save(baseline_path)
        print(f"baseline written: {len(baseline.entries)} entr(ies) in "
              f"{baseline_path}")
        return 0

    fresh, suppressed = baseline.split(findings)
    fresh.sort(key=lambda f: (f.file, f.line, f.checker, f.code))
    elapsed = time.perf_counter() - started

    if args.format == "json":
        def _as_dict(finding: Finding) -> Dict[str, Any]:
            return {
                "file": finding.file, "line": finding.line,
                "severity": finding.severity, "checker": finding.checker,
                "code": finding.code, "key": finding.key,
                "message": finding.message, "hint": finding.hint,
                "ident": finding.ident,
            }

        print(json.dumps(
            {
                "findings": [_as_dict(f) for f in fresh],
                "baselined": [
                    dict(_as_dict(f),
                         reason=baseline.entries.get(f.ident, ""))
                    for f in suppressed
                ],
                "timings": {
                    checker: round(spent, 4)
                    for checker, spent in sorted(timings.items())
                },
                "elapsed": round(elapsed, 4),
                "clean": not fresh,
            },
            indent=2,
        ))
        return 1 if fresh else 0

    for finding in fresh:
        print(finding.render())
    if args.show_baselined and suppressed:
        print(f"-- {len(suppressed)} baselined finding(s):")
        for finding in suppressed:
            print(f"   {finding.render()}  "
                  f"[baseline: {baseline.entries.get(finding.ident, '')}]")
    print(
        f"lint: {len(fresh)} finding(s), {len(suppressed)} baselined, "
        f"{elapsed:.2f}s [{_render_timings(timings)}]"
    )
    return 1 if fresh else 0
