"""``gordo lint`` / ``make lint`` entry point: run every checker, apply
the baseline, print ``file:line severity checker message`` findings.

Pure stdlib and import-light on purpose — the gate must run in seconds,
before any jax import could slow it down. Exit status: 0 = clean (no
non-baselined findings), 1 = findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

from . import (
    knob_registry,
    knobs,
    lock_discipline,
    metrics_conventions,
    span_seam,
)
from .astscan import Module, parse_module
from .findings import Baseline, Finding

# checker -> repo-relative path prefixes it runs over
SCOPES: Dict[str, Tuple[str, ...]] = {
    "lock-discipline": ("gordo_components_tpu/",),
    "span-seam": (
        "gordo_components_tpu/server/",
        "gordo_components_tpu/client/",
        "gordo_components_tpu/router/",
        "gordo_components_tpu/watchman/",
    ),
    "metrics-conventions": (
        "gordo_components_tpu/", "tools/", "bench.py", "bench_serving.py",
    ),
    "knob-registry": (
        "gordo_components_tpu/", "tools/", "tests/", "bench.py",
        "bench_serving.py",
    ),
}

KNOB_TABLE_BEGIN = "<!-- knob-table:begin (generated: make lint) -->"
KNOB_TABLE_END = "<!-- knob-table:end -->"


def repo_root(start: Optional[str] = None) -> str:
    """The checkout root: the directory holding gordo_components_tpu/."""
    here = os.path.abspath(start or os.path.dirname(__file__))
    probe = here
    while True:
        if os.path.isdir(os.path.join(probe, "gordo_components_tpu")):
            return probe
        parent = os.path.dirname(probe)
        if parent == probe:
            return os.path.abspath(start or os.getcwd())
        probe = parent


def _iter_files(root: str) -> List[str]:
    out: List[str] = []
    for prefix in ("gordo_components_tpu", "tools", "tests"):
        base = os.path.join(root, prefix)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [
                d for d in dirnames
                # lint_corpus: seeded-BAD snippets the analysis tests
                # feed the checkers directly — not part of the tree gate
                if d not in ("__pycache__", ".jax_compilation_cache",
                             "lint_corpus")
            ]
            for filename in sorted(filenames):
                if filename.endswith(".py"):
                    out.append(os.path.join(dirpath, filename))
    for single in ("bench.py", "bench_serving.py"):
        path = os.path.join(root, single)
        if os.path.exists(path):
            out.append(path)
    return out


def _in_scope(relpath: str, checker: str) -> bool:
    return relpath.startswith(SCOPES[checker]) or relpath in SCOPES[checker]


def _check_knob_table(root: str) -> List[Finding]:
    """README's knob table must equal the generated one."""
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, "r", encoding="utf-8") as handle:
            text = handle.read()
    except OSError:
        return []
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin == -1 or end == -1:
        return [
            Finding(
                checker="knob-registry", code="readme-table-missing",
                file="README.md", line=1, key="knob-table",
                message=(
                    "README.md has no generated knob-table block "
                    f"({KNOB_TABLE_BEGIN} ... {KNOB_TABLE_END})"
                ),
                hint="run: python -m gordo_components_tpu.analysis "
                     "--write-knob-table",
            )
        ]
    current = text[begin + len(KNOB_TABLE_BEGIN):end].strip()
    expected = knobs.render_markdown_table().strip()
    if current != expected:
        line = text[:begin].count("\n") + 1
        return [
            Finding(
                checker="knob-registry", code="readme-table-drift",
                file="README.md", line=line, key="knob-table",
                message=(
                    "README knob table differs from the registry in "
                    "analysis/knobs.py — docs drifted"
                ),
                hint="run: python -m gordo_components_tpu.analysis "
                     "--write-knob-table",
            )
        ]
    return []


def write_knob_table(root: str) -> bool:
    """Rewrite README's generated knob-table block in place."""
    readme = os.path.join(root, "README.md")
    with open(readme, "r", encoding="utf-8") as handle:
        text = handle.read()
    begin = text.find(KNOB_TABLE_BEGIN)
    end = text.find(KNOB_TABLE_END)
    if begin == -1 or end == -1:
        return False
    rendered = (
        text[: begin + len(KNOB_TABLE_BEGIN)]
        + "\n"
        + knobs.render_markdown_table()
        + "\n"
        + text[end:]
    )
    with open(readme, "w", encoding="utf-8") as handle:
        handle.write(rendered)
    return True


def run_lint(root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    findings: List[Finding] = []
    mentions: Set[str] = set()
    for path in _iter_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        module = parse_module(path, relpath)
        if module is None:
            findings.append(
                Finding(
                    checker="lint", code="unparseable", file=relpath,
                    line=1, key=relpath,
                    message="file does not parse; checkers skipped it",
                )
            )
            continue
        if _in_scope(relpath, "lock-discipline"):
            findings.extend(lock_discipline.check(module))
        if _in_scope(relpath, "span-seam"):
            findings.extend(span_seam.check(module))
        if _in_scope(relpath, "metrics-conventions"):
            findings.extend(metrics_conventions.check(module))
        if _in_scope(relpath, "knob-registry") and (
            relpath != "gordo_components_tpu/analysis/knobs.py"
        ):
            # knobs.py itself is the registry: its literals would make
            # every registered knob count as "mentioned" (circular
            # staleness) and can never be unregistered
            findings.extend(knob_registry.check(module))
            mentions |= knob_registry.collect_mentions(module)
    # registered-but-unmentioned knobs. README PROSE counts as a
    # mention, but the generated knob-table block must NOT: it always
    # contains every registered knob (it is rendered FROM the
    # registry), so counting it would make the stale check circular
    # and dead knobs would live forever.
    readme = os.path.join(root, "README.md")
    try:
        with open(readme, "r", encoding="utf-8") as handle:
            readme_text = handle.read()
    except OSError:
        readme_text = ""
    begin = readme_text.find(KNOB_TABLE_BEGIN)
    end = readme_text.find(KNOB_TABLE_END)
    if begin != -1 and end != -1:
        readme_text = readme_text[:begin] + readme_text[end:]
    # word-bounded: prose naming GORDO_COMPILE_CACHE_STORE must not
    # also count as a mention of its prefix GORDO_COMPILE_CACHE
    readme_mentions = set(knob_registry._KNOB_RE.findall(readme_text))
    findings.extend(
        knob_registry.stale_knobs(set(mentions) | readme_mentions)
    )
    findings.extend(_check_knob_table(root))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="gordo lint",
        description=(
            "Invariant linter: lock discipline, span seams, metric "
            "conventions, knob registry (docs/ARCHITECTURE.md §17)."
        ),
    )
    parser.add_argument("--root", default=None,
                        help="checkout root (default: auto-detect)")
    parser.add_argument("--baseline", default=None,
                        help="baseline path (default: <root>/lint_baseline"
                             ".json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather every current finding into the "
                             "baseline (reasons start as TODO — fill them "
                             "in; a TODO-stubbed entry is itself reported "
                             "as baseline[unjustified-keep] until a real "
                             "reason lands)")
    parser.add_argument("--write-knob-table", action="store_true",
                        help="regenerate README.md's knob table from "
                             "analysis/knobs.py and exit")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print findings the baseline suppresses")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    if args.write_knob_table:
        if not write_knob_table(root):
            print("README.md has no knob-table markers", file=sys.stderr)
            return 2
        print("README.md knob table regenerated")
        return 0

    started = time.perf_counter()
    findings = run_lint(root)
    baseline_path = args.baseline or os.path.join(root, "lint_baseline.json")
    baseline = Baseline.load(baseline_path)

    if args.write_baseline:
        # rebuild from CURRENT findings: existing reasons survive, new
        # findings start as TODO, and entries whose violation is gone
        # are pruned — a freshly written baseline always gates clean
        baseline.entries = {
            finding.ident: baseline.entries.get(
                finding.ident, "TODO: justify"
            )
            for finding in findings
        }
        baseline.save(baseline_path)
        print(f"baseline written: {len(baseline.entries)} entr(ies) in "
              f"{baseline_path}")
        return 0

    fresh, suppressed = baseline.split(findings)
    fresh.sort(key=lambda f: (f.file, f.line, f.checker, f.code))
    for finding in fresh:
        print(finding.render())
    if args.show_baselined and suppressed:
        print(f"-- {len(suppressed)} baselined finding(s):")
        for finding in suppressed:
            print(f"   {finding.render()}  "
                  f"[baseline: {baseline.entries.get(finding.ident, '')}]")
    elapsed = time.perf_counter() - started
    print(
        f"lint: {len(fresh)} finding(s), {len(suppressed)} baselined, "
        f"{elapsed:.2f}s"
    )
    return 1 if fresh else 0
