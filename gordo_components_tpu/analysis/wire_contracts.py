"""Wire-contract checker: the router↔worker protocol, machine-checked.

The HTTP surface — routes, ``X-Gordo-*`` headers, status-code semantics
— is hand-maintained in four producers/consumers at once (server,
router, client, watchman) plus every smoke tool. Nothing type-checks
HTTP: a header the router stamps and nobody reads, a route a smoke tool
calls that no server serves, a ``gordo_*`` series a smoke tool asserts
that nothing emits — all of these "work" until the one real consumer
meets the one real producer in production. Before the fleet spans
hosts (ROADMAP item 1), the contract gets a declared registry and a
cross-reference pass.

Three rule families:

1. **headers** — every ``X-Gordo-*`` literal (and ``Retry-After``) must
   be declared in :data:`HEADERS`; across the scanned tree, a declared
   header with read evidence but NO stamp evidence is
   ``header-never-stamped``, and stamp evidence with no read anywhere is
   ``header-never-read``. Stamp vs read is classified from AST context
   (tuple/dict/subscript-store/``.add`` = stamp; ``.get``/``in``/
   subscript-load/``HTTP_X_GORDO_*`` environ key = read).
2. **routes** — every ``Rule("<path>")`` literal must be declared in
   :data:`ROUTES`; a declared route with no serve evidence anywhere is
   ``route-not-served``; a URL path fragment used in an HTTP call (or a
   base-url f-string) that aligns with NO declared route template is
   ``unserved-route-call``.
3. **series** — every ``gordo_*`` name asserted by ``tools/*_smoke.py``
   / ``tools/scrape_metrics.py`` must be emitted by a registry metric
   declaration somewhere in the package (exposition suffixes stripped,
   prefix assertions allowed) — else ``phantom-series``.

Evidence is collected per file by :func:`scan` and joined by
:func:`finalize` (the runner aggregates across the tree; the corpus
tests drive the pair directly).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astscan import Module, dotted
from .findings import Finding

CHECKER = "wire-contracts"

# -- the declared registry ----------------------------------------------------


@dataclass(frozen=True)
class HeaderSpec:
    name: str
    doc: str           # semantics, incl. status-code interplay
    request: bool = False    # travels on requests (client/router -> worker)
    response: bool = False   # travels on responses


HEADERS: Dict[str, HeaderSpec] = {
    header.name.lower(): header
    for header in (
        HeaderSpec(
            "X-Gordo-Trace-Id",
            "request: adopt the caller's trace id; response: echo the "
            "one the request ran under (§7)",
            request=True, response=True,
        ),
        HeaderSpec(
            "X-Gordo-Deadline",
            "absolute wall-clock deadline; pre-dispatch checks answer "
            "504 once it passes (§10)",
            request=True,
        ),
        HeaderSpec(
            "X-Gordo-Worker",
            "which worker answered — placement echo for routing "
            "stickiness checks (§16)",
            response=True,
        ),
        HeaderSpec(
            "X-Gordo-Draining",
            "stamped on every response while the server drains; paired "
            "with 503 + Retry-After: 0 so clients retry NOW (§16)",
            response=True,
        ),
        HeaderSpec(
            "X-Gordo-Shard",
            "which mesh shard answered — the owner in steady state; a "
            "different shard means the spill fallback rung served a "
            "dead owner's machine (§23)",
            response=True,
        ),
        HeaderSpec(
            "X-Gordo-Timeline",
            "request: router negotiates timeline capture (stamps '1'); "
            "response: base64(JSON) encoded timeline, size-capped (§18)",
            request=True, response=True,
        ),
        HeaderSpec(
            "X-Gordo-Timeline-Truncated",
            "response over the timeline size cap — the router pulls the "
            "full timeline from /debug/requests/<id> instead (§18)",
            response=True,
        ),
        HeaderSpec(
            "X-Gordo-Tenant",
            "which principal this request scores as (§25): the server "
            "maps it to a priority class + token-bucket quota; unknown "
            "names fold into 'default'; the router forwards it untouched",
            request=True,
        ),
        HeaderSpec(
            "Retry-After",
            "seconds to back off: admission shed / quarantine / draining "
            "503s carry it (draining floors it at 0), and quota 429s "
            "carry the bucket's refill time (§10/§16/§25)",
            response=True,
        ),
    )
}

_HEADER_RE = re.compile(r"^X-Gordo-[A-Za-z][A-Za-z0-9-]*$")
_ENVIRON_HEADER_RE = re.compile(r"^HTTP_X_GORDO_[A-Z0-9_]+$")


@dataclass(frozen=True)
class RouteSpec:
    path: str          # template; <var> segments are wildcards
    servers: Tuple[str, ...]   # components that serve it
    doc: str


ROUTES: Tuple[RouteSpec, ...] = (
    RouteSpec("/healthz", ("server", "router", "watchman"),
              "live/ready/degraded/draining; 503 while draining (§10/§16)"),
    RouteSpec("/metadata", ("server",), "model metadata"),
    RouteSpec("/metrics", ("server", "router", "watchman"),
              "JSON or ?format=prometheus; router: &aggregate=1 merges "
              "workers (§18)"),
    RouteSpec("/slo", ("server", "router"),
              "burn-rate objectives + per-stage attribution (§18)"),
    RouteSpec("/telemetry", ("server", "router"),
              "warehouse window queries + traffic top-K + cost ledger; "
              "?view=export = layout-input doc; router merges workers "
              "(§24)"),
    RouteSpec("/incidents", ("server", "router"),
              "incident reports + correlator status; ?view=ledger = raw "
              "control-ledger window; router merges workers (§28)"),
    RouteSpec("/incidents/<incident_id>", ("server", "router"),
              "one durable incident report: lookback control events, "
              "metric deltas, ranked root-cause candidates (§28)"),
    RouteSpec("/models", ("server", "router"), "served machine list"),
    RouteSpec("/prefetch", ("server",),
              "POST placement hint (§22): queue async host-cache loads "
              "for lazy machines; advisory, never blocks"),
    RouteSpec("/layout", ("server",),
              "layout-plan slice (§27): POST pins this worker's resident "
              "set/cap/prefetch hints under a plan fingerprint (or "
              "clears them); GET echoes what was applied"),
    RouteSpec("/reload", ("server", "router"),
              "adopt a new generation; router: canary→sweep rollout, "
              "busy answers 409 (§16)"),
    RouteSpec("/rollback", ("router",),
              "atomic fleet CURRENT swap then adoption (§16)"),
    RouteSpec("/router/status", ("router",), "placement + worker table"),
    RouteSpec("/autopilot", ("server", "router"),
              "controller status; reads are evaluation ticks (§20)"),
    RouteSpec("/autopilot/<action>", ("server", "router"),
              "POST enable|disable; 409 when hard-off (§20)"),
    RouteSpec("/fleet", ("router",),
              "reconciler status: committed spec revision, divergence "
              "counts, repair ring; reads are reconcile ticks (§26)"),
    RouteSpec("/fleet/<action>", ("router",),
              "GET status|diff, POST apply|rollback: journaled spec "
              "commits + read-only spec-vs-observed diff; 409 when "
              "hard-off (§26)"),
    RouteSpec("/prediction", ("server", "router"), "single-model scoring"),
    RouteSpec("/anomaly/prediction", ("server", "router"),
              "anomaly scoring; 503+Retry-After on shed/quarantine, "
              "504 past deadline, 429+Retry-After on quota (§10/§25)"),
    RouteSpec("/tenants", ("server", "router"),
              "QoS control surface (§25): tenant table, class limits + "
              "shed rung, raw-header heavy-hitter sketch"),
    RouteSpec("/bulk/anomaly/prediction", ("server",),
              "offline scoring surface (§25): forced-bulk class, large "
              "windows amortized through the spill tier"),
    RouteSpec("/download-model", ("server",), "serialized model bytes"),
    RouteSpec("/debug/requests", ("server", "router"),
              "flight-recorder rings (§13)"),
    RouteSpec("/debug/requests/<trace_id>", ("server", "router"),
              "one timeline; ?format=chrome = Perfetto; stitch pull "
              "source (§18)"),
    RouteSpec("/gordo/v0/<project>/<machine>/healthz", ("server",),
              "machine-scoped healthz"),
    RouteSpec("/gordo/v0/<project>/<machine>/metadata", ("server",),
              "machine-scoped metadata"),
    RouteSpec("/gordo/v0/<project>/<machine>/prediction", ("server",),
              "machine-scoped scoring"),
    RouteSpec("/gordo/v0/<project>/<machine>/anomaly/prediction",
              ("server",), "machine-scoped anomaly scoring"),
    RouteSpec("/gordo/v0/<project>/<machine>/bulk/anomaly/prediction",
              ("server",), "machine-scoped bulk scoring (§25)"),
    RouteSpec("/gordo/v0/<project>/<machine>/download-model", ("server",),
              "machine-scoped model download"),
    RouteSpec("/gordo/v0/<project>/<machine>/<path:rest>", ("router",),
              "machine-path forward: consistent-hash placement (§16)"),
    RouteSpec("/", ("watchman",), "watchman status page"),
)

# components whose files carry wire evidence (dataset/builder HTTP — the
# influx data plane — is NOT the router↔worker protocol and is excluded)
WIRE_COMPONENTS = frozenset(
    {"server", "router", "client", "watchman", "observability",
     "resilience", "autopilot", "fleet", "cli", "tools"}
)

_HTTP_VERBS = frozenset(
    {"get", "post", "put", "delete", "head", "request", "urlopen", "open"}
)
# 'get' and 'open' collide with dict/env .get() and the builtin open():
# those two only count as HTTP calls when their receiver looks like one
_HTTP_AMBIGUOUS_VERBS = frozenset({"get", "open"})
_HTTP_RECEIVER_RE = re.compile(
    r"session|requests|client|http|urll?ib|opener|conn|pool", re.I
)
_READ_METHODS = frozenset({"get", "pop", "getlist", "get_all"})
_STAMP_METHODS = frozenset({"add", "append", "set", "setdefault"})

_SERIES_RE = re.compile(r"\bgordo_[a-z0-9_]*[a-z0-9]\b")
_EXPOSITION_SUFFIXES = ("_bucket", "_count", "_sum")
_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})


def component_of(relpath: str) -> str:
    if relpath.startswith("tools/"):
        return "tools"
    if relpath.startswith("tests/"):
        return "tests"
    parts = relpath.split("/")
    if parts[0] == "gordo_components_tpu" and len(parts) > 1:
        return parts[1][:-3] if parts[1].endswith(".py") else parts[1]
    return parts[0]


# -- evidence -----------------------------------------------------------------


@dataclass
class WireEvidence:
    """Picklable per-file evidence the runner joins across the tree."""

    relpath: str = ""
    # canonical header name -> first (line) seen, per classification
    stamps: Dict[str, int] = field(default_factory=dict)
    reads: Dict[str, int] = field(default_factory=dict)
    # registered template -> line of serve evidence (Rule/.path compare)
    serves: Dict[str, int] = field(default_factory=dict)
    # gordo_* names asserted by smoke tools: name -> line
    asserted_series: Dict[str, int] = field(default_factory=dict)
    # metric family names declared via the registry in this file
    emitted_series: Set[str] = field(default_factory=set)
    # headers travel as named constants (tracing.TRACE_HEADER,
    # DRAINING_HEADER): defs map the *_HEADER name to its canonical
    # header here; uses record (alias, 'stamp'|'read', line) and are
    # resolved cross-file at finalize
    alias_defs: Dict[str, str] = field(default_factory=dict)
    alias_uses: List[Tuple[str, str, int]] = field(default_factory=list)


def _canonical_header(raw: str) -> Optional[str]:
    if _HEADER_RE.match(raw) or raw.lower() == "retry-after":
        return raw.lower()
    if _ENVIRON_HEADER_RE.match(raw):
        parts = raw[len("HTTP_"):].split("_")
        return "-".join(part.capitalize() for part in parts).lower()
    return None


def _display_header(canonical: str) -> str:
    spec = HEADERS.get(canonical)
    if spec is not None:
        return spec.name
    return "-".join(part.capitalize() for part in canonical.split("-"))


def _parent_map(tree: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _template_segments(path: str) -> List[str]:
    return [seg for seg in path.split("/") if seg]


def _is_var(segment: str) -> bool:
    return segment.startswith("<") and segment.endswith(">")


def _fragment_matches(fragment: str, templates: List[str]) -> bool:
    """A URL fragment (constant part of an f-string or a whole path
    literal) aligns with some declared route template. An f-string
    fragment carries no anchor information, so both alignments are
    tried: prefix-aligned (``"/gordo/v0/my-project/"`` — the literal
    values fill ``<var>`` segments) and suffix-aligned
    (``"/anomaly/prediction"`` — the tail after the interpolated
    machine). ``<var>`` segments wildcard in both directions."""
    fragment = fragment.split("?", 1)[0].split("#", 1)[0]
    if fragment in ("", "/"):
        return True
    open_ended = fragment.endswith("/")
    frag_segs = _template_segments(fragment)
    for template in templates:
        temp_segs = _template_segments(template)
        if len(frag_segs) > len(temp_segs):
            continue
        head = temp_segs[: len(frag_segs)]
        if all(_is_var(t) or t == f for t, f in zip(head, frag_segs)) and (
            open_ended or len(frag_segs) == len(temp_segs)
        ):
            return True
        # suffix alignment: the fragment is the constant TAIL of an
        # f-string, so its final segment must match a LITERAL template
        # segment — ending on a <var> (notably the router's
        # <path:rest> catch-all) would let any fragment match anything
        tail = temp_segs[-len(frag_segs):]
        if (
            not open_ended
            and not _is_var(tail[-1])
            and all(_is_var(t) or t == f for t, f in zip(tail, frag_segs))
        ):
            return True
    return False


def _url_fragments(node: ast.AST) -> List[Tuple[str, int]]:
    """Constant path fragments inside a URL expression: plain string
    literals and the constant parts of f-strings; absolute URLs are
    reduced to their path component."""
    out: List[Tuple[str, int]] = []
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Constant) and isinstance(sub.value, str)):
            continue
        text = sub.value
        if text.startswith(("http://", "https://")):
            rest = text.split("://", 1)[1]
            slash = rest.find("/")
            text = rest[slash:] if slash != -1 else ""
        if text.startswith("/") and text not in ("/", ""):
            out.append((text, sub.lineno))
    return out


def _base_url_fstring(node: ast.JoinedStr) -> bool:
    """f-strings of the idiom ``f"{base_url}/healthz"`` — the URL-build
    shape the tree uses when the call site is elsewhere."""
    if not node.values or not isinstance(node.values[0], ast.FormattedValue):
        return False
    name = dotted(node.values[0].value).lower()
    return "url" in name or "base" in name


# -- per-file scan ------------------------------------------------------------


def scan(module: Module) -> Tuple[List[Finding], WireEvidence]:
    evidence = WireEvidence(relpath=module.relpath)
    findings: List[Finding] = []
    component = component_of(module.relpath)
    in_wire_scope = component in WIRE_COMPONENTS
    is_smoke_tool = module.relpath.startswith("tools/") and (
        module.relpath.endswith("_smoke.py")
        or module.relpath.endswith("scrape_metrics.py")
    )
    parents = _parent_map(module.tree)
    templates = [route.path for route in ROUTES]
    known_paths = {route.path for route in ROUTES}

    # metric families declared via the registry (whole package: smoke
    # assertions may name any layer's series)
    for call in ast.walk(module.tree):
        if isinstance(call, ast.Call):
            name = dotted(call.func)
            if name and name.split(".")[-1] in _METRIC_FACTORIES:
                receiver = name.split(".")[-2].lower() if "." in name else ""
                if receiver in ("registry", "_registry") and call.args:
                    literal = call.args[0]
                    if isinstance(literal, ast.Constant) and isinstance(
                        literal.value, str
                    ):
                        evidence.emitted_series.add(literal.value)

    if is_smoke_tool:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for name in _SERIES_RE.findall(node.value):
                    evidence.asserted_series.setdefault(name, node.lineno)

    if not in_wire_scope:
        return findings, evidence

    # header-alias definitions: NAME_HEADER = "X-Gordo-..."
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            canonical = _canonical_header(node.value.value)
            if canonical is None:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and _is_alias_name(target.id):
                    evidence.alias_defs[target.id] = canonical

    flagged_headers: Set[str] = set()
    flagged_fragments: Set[str] = set()
    for node in ast.walk(module.tree):
        # -- header-alias uses ----------------------------------------------
        alias: Optional[str] = None
        if isinstance(node, ast.Attribute) and _is_alias_name(node.attr):
            alias = node.attr
        elif (
            isinstance(node, ast.Name)
            and _is_alias_name(node.id)
            and isinstance(node.ctx, ast.Load)
        ):
            alias = node.id
        if alias is not None:
            role = _classify_site(node, parents)
            if role is not None:
                evidence.alias_uses.append((alias, role, node.lineno))
        # -- headers ---------------------------------------------------------
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            canonical = _canonical_header(node.value)
            if canonical is not None:
                registered = canonical in HEADERS
                if not registered and canonical not in flagged_headers:
                    flagged_headers.add(canonical)
                    findings.append(
                        Finding(
                            checker=CHECKER, code="unregistered-header",
                            file=module.relpath, line=node.lineno,
                            key=_display_header(canonical),
                            message=(
                                f"{node.value!r} is not declared in the "
                                "wire-contract registry (analysis/"
                                "wire_contracts.py HEADERS)"
                            ),
                            hint=(
                                "declare the header with its semantics, "
                                "or drop the stray literal"
                            ),
                        )
                    )
                role = _classify_header_site(node, parents)
                if role == "stamp":
                    evidence.stamps.setdefault(canonical, node.lineno)
                elif role == "read":
                    evidence.reads.setdefault(canonical, node.lineno)
        # -- routes: serve evidence ------------------------------------------
        if isinstance(node, ast.Call):
            callee = dotted(node.func)
            last = callee.split(".")[-1] if callee else ""
            if last == "Rule" and node.args:
                literal = node.args[0]
                if isinstance(literal, ast.Constant) and isinstance(
                    literal.value, str
                ):
                    path = literal.value
                    if path in known_paths:
                        evidence.serves.setdefault(path, literal.lineno)
                    else:
                        findings.append(
                            Finding(
                                checker=CHECKER, code="unregistered-route",
                                file=module.relpath, line=literal.lineno,
                                key=path,
                                message=(
                                    f"served route {path!r} is not "
                                    "declared in the wire-contract "
                                    "registry (analysis/wire_contracts.py "
                                    "ROUTES)"
                                ),
                                hint="declare the route with its servers "
                                     "and status semantics",
                            )
                        )
            # route-path comparisons: ``request.path == "/healthz"`` /
            # membership tuples — watchman's dispatch idiom
        if isinstance(node, ast.Compare):
            names = [dotted(side) for side in [node.left] + node.comparators]
            if any(name.endswith(".path") for name in names if name):
                for side in [node.left] + node.comparators:
                    for sub in ast.walk(side):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ) and sub.value in known_paths:
                            evidence.serves.setdefault(
                                sub.value, sub.lineno
                            )
        # -- routes: call evidence -------------------------------------------
        fragments: List[Tuple[str, int]] = []
        if isinstance(node, ast.Call):
            callee = dotted(node.func)
            last = callee.split(".")[-1] if callee else ""
            receiver = callee.rsplit(".", 1)[0] if "." in callee else ""
            if (
                last in _HTTP_VERBS
                and (node.args or node.keywords)
                and not (
                    last in _HTTP_AMBIGUOUS_VERBS
                    and not _HTTP_RECEIVER_RE.search(receiver)
                )
            ):
                # only the URL position: arg 0 (arg 1 too for
                # requests.request(method, url)) — a .post() body or a
                # .get() default is not a route
                url_args = list(
                    node.args[: 2 if last == "request" else 1]
                ) + [kw.value for kw in node.keywords if kw.arg == "url"]
                for arg in url_args:
                    fragments.extend(_url_fragments(arg))
        elif isinstance(node, ast.JoinedStr) and _base_url_fstring(node):
            fragments.extend(_url_fragments(node))
        for fragment, line in fragments:
            if fragment in flagged_fragments:
                continue
            if not _fragment_matches(fragment, templates):
                flagged_fragments.add(fragment)
                findings.append(
                    Finding(
                        checker=CHECKER, code="unserved-route-call",
                        file=module.relpath, line=line, key=fragment,
                        message=(
                            f"calls {fragment!r}, which aligns with no "
                            "declared route template — nothing serves it"
                        ),
                        hint=(
                            "fix the path, or declare the route in "
                            "analysis/wire_contracts.py ROUTES (and "
                            "serve it)"
                        ),
                    )
                )
    return findings, evidence


_ALIAS_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*_HEADER$")


def _is_alias_name(name: str) -> bool:
    return bool(_ALIAS_NAME_RE.match(name))


def _classify_header_site(
    node: ast.Constant, parents: Dict[int, ast.AST]
) -> Optional[str]:
    """'stamp' / 'read' / None for a header string literal."""
    if _ENVIRON_HEADER_RE.match(node.value):
        return "read"  # WSGI environ key only exists on the read side
    return _classify_site(node, parents)


def _classify_site(
    node: ast.AST, parents: Dict[int, ast.AST]
) -> Optional[str]:
    """'stamp' / 'read' / None from the AST context of a header
    expression (string literal or *_HEADER alias reference)."""
    parent = parents.get(id(node))
    if parent is None:
        return None
    if isinstance(parent, ast.Tuple):
        # ("X-Gordo-Foo", value) response-header pair
        if len(parent.elts) >= 2 and parent.elts[0] is node:
            return "stamp"
        return None
    if isinstance(parent, ast.Dict):
        if node in parent.keys:
            return "stamp"
        return None
    if isinstance(parent, ast.Subscript):
        grand = parents.get(id(parent))
        if isinstance(parent.ctx, (ast.Store, ast.Del)) or (
            isinstance(grand, (ast.Assign, ast.AugAssign))
            and getattr(grand, "targets", [None])[0] is parent
        ):
            return "stamp"
        return "read"
    if isinstance(parent, ast.Compare):
        return "read"  # "X-Gordo-Foo" in response.headers
    if isinstance(parent, ast.Call) and node in parent.args:
        name = dotted(parent.func)
        last = name.split(".")[-1] if name else ""
        if last in _READ_METHODS and parent.args[0] is node:
            return "read"
        if last in _STAMP_METHODS and parent.args[0] is node and len(
            parent.args
        ) >= 2:
            return "stamp"
    return None


# -- cross-file finalize ------------------------------------------------------


def finalize(evidences: List[WireEvidence]) -> List[Finding]:
    findings: List[Finding] = []
    stamps: Dict[str, Tuple[str, int]] = {}
    reads: Dict[str, Tuple[str, int]] = {}
    serves: Dict[str, Tuple[str, int]] = {}
    emitted: Set[str] = set()
    asserted: List[Tuple[str, str, int]] = []
    alias_map: Dict[str, str] = {}
    for evidence in evidences:
        alias_map.update(evidence.alias_defs)
    for evidence in evidences:
        for header, line in evidence.stamps.items():
            stamps.setdefault(header, (evidence.relpath, line))
        for header, line in evidence.reads.items():
            reads.setdefault(header, (evidence.relpath, line))
        for alias, role, line in evidence.alias_uses:
            canonical = alias_map.get(alias)
            if canonical is None:
                continue
            target = stamps if role == "stamp" else reads
            target.setdefault(canonical, (evidence.relpath, line))
        for path, line in evidence.serves.items():
            serves.setdefault(path, (evidence.relpath, line))
        emitted |= evidence.emitted_series
        for name, line in evidence.asserted_series.items():
            asserted.append((name, evidence.relpath, line))

    for canonical, spec in sorted(HEADERS.items()):
        read_site = reads.get(canonical)
        stamp_site = stamps.get(canonical)
        if read_site is not None and stamp_site is None:
            findings.append(
                Finding(
                    checker=CHECKER, code="header-never-stamped",
                    file=read_site[0], line=read_site[1], key=spec.name,
                    message=(
                        f"{spec.name} is read here but NOTHING stamps it "
                        "anywhere in the tree — the consumer always sees "
                        "the default"
                    ),
                    hint="stamp it on the producing side, or delete the "
                         "dead read + registry entry",
                )
            )
        if stamp_site is not None and read_site is None:
            findings.append(
                Finding(
                    checker=CHECKER, code="header-never-read",
                    file=stamp_site[0], line=stamp_site[1], key=spec.name,
                    message=(
                        f"{spec.name} is stamped here but NOTHING reads "
                        "it anywhere in the tree — bytes on the wire "
                        "with no consumer"
                    ),
                    hint="read it where the contract says, or delete the "
                         "stamp + registry entry",
                )
            )

    for route in ROUTES:
        if route.path not in serves:
            findings.append(
                Finding(
                    checker=CHECKER, code="route-not-served",
                    file="gordo_components_tpu/analysis/wire_contracts.py",
                    line=1, key=route.path,
                    message=(
                        f"declared route {route.path!r} has no serve "
                        f"evidence in any of {'/'.join(route.servers)} — "
                        "the registry drifted from the URL maps"
                    ),
                    hint="serve it (Rule/.path dispatch) or delete the "
                         "registry entry",
                )
            )

    stripped: Set[str] = set(emitted)
    for name in emitted:
        for suffix in ("_total",):
            if name.endswith(suffix):
                stripped.add(name[: -len(suffix)])
    for name, relpath, line in sorted(asserted):
        base = name
        for suffix in _EXPOSITION_SUFFIXES:
            if base.endswith(suffix):
                base = base[: -len(suffix)]
                break
        if base in emitted or base in stripped:
            continue
        if any(family.startswith(base + "_") for family in emitted):
            continue  # prefix assertion ("gordo_resilience_...")
        findings.append(
            Finding(
                checker=CHECKER, code="phantom-series",
                file=relpath, line=line, key=name,
                message=(
                    f"smoke tool asserts series {name!r} but no registry "
                    "metric declaration emits it — the assertion can "
                    "only ever fail (or silently match nothing)"
                ),
                hint="fix the series name, or declare the metric it "
                     "expects",
            )
        )
    return findings
