"""Fault-seam coverage checker: chaos coverage cannot silently rot.

``resilience/faults.py`` declares the injection points (``POINTS``) and
production code wires them with ``inject(point, ...)`` / ``corrupt`` /
``damage_artifact`` calls. The chaos suite and smoke tools exercise
them through spec strings (``point:target:kind``) and direct calls —
but nothing ever checked that EVERY declared seam is still exercised:
delete the one test that injects at ``data-fetch`` and the seam keeps
existing, untested, forever.

Statically cross-referenced, three directions:

- ``uncovered-fault-seam`` — a declared point no test or smoke tool
  references (spec-string first segment, or a literal ``inject``/
  ``configure``/``parse_spec`` argument under ``tests/``/``tools/``).
- ``unwired-fault-point``  — declared but no production call site
  injects at it: a seam that cannot fire.
- ``undeclared-fault-point`` — a production ``inject(...)`` literal
  not in ``POINTS``: it can never match a rule, so it silently
  injects nothing.

Evidence collected per file by :func:`scan`, joined by :func:`finalize`.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Set, Tuple

from .astscan import Module, dotted
from .findings import Finding

CHECKER = "fault-coverage"

FAULTS_RELPATH = "gordo_components_tpu/resilience/faults.py"

_SEAM_CALLS = frozenset({"inject", "corrupt", "damage_artifact"})
_SPEC_CALLS = frozenset({"configure", "parse_spec"})
# a spec rule chunk: point:target:kind[:param]
_SPEC_RULE_RE = re.compile(
    r"([a-z][a-z0-9-]*):([^:;\s]+):([a-z][a-z0-9-]*)"
)


@dataclass
class FaultEvidence:
    relpath: str = ""
    # POINTS entries (faults.py only): name -> line
    declared: Dict[str, int] = field(default_factory=dict)
    # production inject/corrupt/damage_artifact literal points
    wired: Dict[str, int] = field(default_factory=dict)
    # test/tool references (direct-call args + spec-string points)
    referenced: Set[str] = field(default_factory=set)


def scan(module: Module) -> FaultEvidence:
    evidence = FaultEvidence(relpath=module.relpath)
    is_faults = module.relpath.endswith("resilience/faults.py")
    is_exerciser = module.relpath.startswith(("tests/", "tools/"))

    if is_faults:
        for node in module.tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "POINTS"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                for element in node.value.elts:
                    if isinstance(element, ast.Constant) and isinstance(
                        element.value, str
                    ):
                        evidence.declared[element.value] = element.lineno
        return evidence  # its own docstring examples are not coverage

    # docstrings are prose, not coverage: a seam spec MENTIONED in a
    # test's docstring must not keep the seam counted as exercised
    docstrings: Set[int] = set()
    for node in ast.walk(module.tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                   ast.AsyncFunctionDef)
        ):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                docstrings.add(id(body[0].value))

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            last = name.split(".")[-1] if name else ""
            if last in _SEAM_CALLS and node.args:
                literal = node.args[0]
                if isinstance(literal, ast.Constant) and isinstance(
                    literal.value, str
                ):
                    if is_exerciser:
                        evidence.referenced.add(literal.value)
                    else:
                        evidence.wired.setdefault(
                            literal.value, literal.lineno
                        )
            if is_exerciser and last in _SPEC_CALLS and node.args:
                literal = node.args[0]
                if isinstance(literal, ast.Constant) and isinstance(
                    literal.value, str
                ):
                    for match in _SPEC_RULE_RE.finditer(literal.value):
                        evidence.referenced.add(match.group(1))
        if is_exerciser and isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ) and id(node) not in docstrings:
            # spec strings travel as env values / CLI flags too
            for match in _SPEC_RULE_RE.finditer(node.value):
                evidence.referenced.add(match.group(1))
    return evidence


def finalize(evidences: List[FaultEvidence]) -> List[Finding]:
    declared: Dict[str, int] = {}
    wired: Dict[str, Tuple[str, int]] = {}
    referenced: Set[str] = set()
    for evidence in evidences:
        declared.update(evidence.declared)
        for point, line in evidence.wired.items():
            wired.setdefault(point, (evidence.relpath, line))
        referenced |= evidence.referenced

    findings: List[Finding] = []
    if not declared:
        return findings  # faults.py outside the scanned set (corpus runs)
    for point, line in sorted(declared.items()):
        if point not in referenced:
            findings.append(
                Finding(
                    checker=CHECKER, code="uncovered-fault-seam",
                    file=FAULTS_RELPATH, line=line, key=point,
                    message=(
                        f"injection point {point!r} is exercised by no "
                        "test or smoke tool — its chaos coverage rotted"
                    ),
                    hint=(
                        "add a test/smoke spec that injects at this "
                        "seam, or delete the point"
                    ),
                )
            )
        if point not in wired:
            findings.append(
                Finding(
                    checker=CHECKER, code="unwired-fault-point",
                    file=FAULTS_RELPATH, line=line, key=point,
                    message=(
                        f"injection point {point!r} has no production "
                        "inject()/corrupt()/damage_artifact() call site "
                        "— the seam can never fire"
                    ),
                    hint="wire the boundary, or delete the point",
                )
            )
    for point, (relpath, line) in sorted(wired.items()):
        if point not in declared:
            findings.append(
                Finding(
                    checker=CHECKER, code="undeclared-fault-point",
                    file=relpath, line=line, key=point,
                    message=(
                        f"inject point {point!r} is not in faults.POINTS "
                        "— no spec can ever match it, so it silently "
                        "injects nothing"
                    ),
                    hint="add it to POINTS (and the spec-grammar doc), "
                         "or fix the typo",
                )
            )
    return findings
