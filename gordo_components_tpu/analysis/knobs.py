"""THE ``GORDO_*`` env-knob registry: one declaration per knob.

Every ``os.environ`` / ``os.getenv`` / click ``envvar=`` read of a
``GORDO_*`` name anywhere in the tree must have an entry here — the
:mod:`.knob_registry` checker enforces it — and the README knob table
is GENERATED from this module (``python -m gordo_components_tpu.analysis
--write-knob-table``), so the docs cannot drift from the code again.

``default`` is the human-readable default (including "core-aware"
formulas), ``parser`` the accepted value shape. Keep docs to one line:
they become table cells.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Knob:
    name: str
    default: str
    parser: str      # int | float | str | bool | path | spec
    doc: str         # one line; becomes the README table cell
    component: str   # serving | engine | build | store | observability |
                     # resilience | test


def _knob(name, default, parser, doc, component) -> Tuple[str, Knob]:
    return name, Knob(name, default, parser, doc, component)


KNOBS: Dict[str, Knob] = dict(
    [
        # -- engine / serving data plane ---------------------------------
        _knob("GORDO_DISPATCH_DEPTH", "2 (≥4 CPUs) / 1", "int",
              "bounded in-flight device dispatches per bucket; 1 = serial "
              "bit-identical comparison mode", "engine"),
        _knob("GORDO_MEGABATCH", "1", "bool",
              "cross-machine fused dispatch (replicated engines only; "
              "`0`/`off` disables, `--no-megabatch` on `run-server`)",
              "engine"),
        _knob("GORDO_FILL_WINDOW_US", "250 µs (≥4 CPUs) / 1000 µs", "int",
              "bounded fill window a leader holds open when it observes "
              "concurrency; `0` = drain-only fusion; `--fill-window-us` "
              "on `run-server`", "engine"),
        _knob("GORDO_MEGABATCH_RESIDENCY", "128", "int",
              "machines per bucket resident in the stacked megabatch "
              "program; fleets at/under the cap are fully resident from "
              "boot, larger fleets earn slots hot-cache-style", "engine"),
        _knob("GORDO_SERVE_HOT_CACHE", "16", "int",
              "shard mode: machines keeping an unsharded hot device copy "
              "(skips the per-dispatch cross-device gather); 0 disables",
              "engine"),
        _knob("GORDO_HOST_CACHE_MB", "256", "int",
              "host-RAM spill tier (§22): megabytes of deserialized "
              "pre-stacked host arrays cached between device residency "
              "and the model store; `0` disables (every lazy request "
              "pays the store path)", "engine"),
        # -- server admission / lifecycle --------------------------------
        _knob("GORDO_MAX_INFLIGHT", "64", "int",
              "admission gate: concurrent admitted requests "
              "(`--max-inflight` on `run-server`)", "serving"),
        _knob("GORDO_MAX_QUEUE", "32", "int",
              "admission gate: waiters allowed behind a full gate "
              "(micro-burst absorption)", "serving"),
        _knob("GORDO_QUEUE_TIMEOUT", "1.0", "float",
              "seconds a waiter queues for admission before shedding 503",
              "serving"),
        # -- multi-tenant QoS (§25) ---------------------------------------
        _knob("GORDO_TENANTS", "unset", "spec",
              "multi-tenant QoS table (§25): "
              "`name:class[:rate[:burst[:key]]]` entries separated by "
              "`;` — class `interactive`/`standard`/`bulk`, rate in "
              "requests/s (0 = unmetered token bucket), key an optional "
              "API key; requests pick a tenant via `X-Gordo-Tenant`, "
              "unknown names fold into `default` (`--tenants` on "
              "`run-server` / `run-fleet-server`)", "serving"),
        _knob("GORDO_QOS_DEFAULT_CLASS", "standard", "str",
              "priority class for bare requests and undeclared tenants "
              "(`interactive`/`standard`/`bulk`)", "serving"),
        _knob("GORDO_QOS_WEIGHTS", "interactive=8,standard=4,bulk=1", "spec",
              "deficit-weighted fair-share ratios the megabatch fill "
              "window drains classes by (scores stay byte-identical; "
              "only intra-window ORDER changes)", "serving"),
        _knob("GORDO_DRAIN_TIMEOUT", "10", "float",
              "graceful-shutdown budget: seconds SIGTERM waits for "
              "in-flight requests before stopping the listener",
              "serving"),
        _knob("GORDO_WORKER_ID", "unset", "int",
              "horizontal tier: this worker's slot id (stamped on "
              "responses as `X-Gordo-Worker`; set by the router "
              "supervisor)", "serving"),
        _knob("GORDO_LAZY_BOOT", "0", "bool",
              "lazy fleet boot (§22): boot from the `FLEET_INDEX.json` "
              "sidecar — O(index read) instead of O(load the fleet); "
              "non-eager machines serve through the host-RAM spill tier "
              "with first-touch verification (`--lazy-boot` on "
              "`run-server`)", "serving"),
        _knob("GORDO_BOOT_EAGER", "0", "int",
              "lazy fleet boot: machines (index order) materialized "
              "eagerly at boot to warm the common architecture's "
              "programs; the rest stay behind the spill tier", "serving"),
        # -- mesh serving (§23) ------------------------------------------
        _knob("GORDO_MESH_SHARDS", "0", "int",
              "multi-host serving mesh (§23): total shard count the "
              "stacked fleet partitions across by ring position; 0 = "
              "single-host serving (`--mesh-shards` on `run-server` / "
              "`run-fleet-server`)", "serving"),
        _knob("GORDO_MESH_SHARD", "worker-id mod shards", "int",
              "mesh serving: THIS process's shard id (0-based); each "
              "shard stacks only its owned machines and serves the rest "
              "through the spill fallback rung (`--mesh-shard` on "
              "`run-server`)", "serving"),
        _knob("GORDO_MESH_MIN_SHARD_MACHINES", "2×shards", "int",
              "mesh serving's declared layout policy: fleets smaller "
              "than this stay replicated on every shard (the cross-host "
              "split would cost more than it frees); larger fleets "
              "shard by ring position", "serving"),
        # -- compile caches ----------------------------------------------
        _knob("GORDO_COMPILE_CACHE", "~/.cache/gordo-tpu/jax-compile",
              "path",
              "build-side persistent XLA compilation cache directory; "
              "`off` disables", "build"),
        _knob("GORDO_COMPILE_CACHE_STORE",
              "<models_root>/.compile-cache", "path",
              "serving-side AOT executable store; `off` disables "
              "(`--compile-cache-store` on `run-server`)", "serving"),
        # -- resilience --------------------------------------------------
        _knob("GORDO_FAULTS", "unset", "spec",
              "fault-injection plan (`point:target:kind[:arg]`, "
              "comma-separated) powering the chaos suite; `--faults` on "
              "`run-server`", "resilience"),
        # -- observability -----------------------------------------------
        _knob("GORDO_FLIGHTREC", "1", "bool",
              "always-on flight recorder; `0` disables recording "
              "(perf-comparison escape hatch)", "observability"),
        _knob("GORDO_FLIGHTREC_KEEP", "256", "int",
              "flight recorder: recent-request ring size", "observability"),
        _knob("GORDO_FLIGHTREC_SLOW_KEEP", "32", "int",
              "flight recorder: slowest-since-boot reservoir size",
              "observability"),
        _knob("GORDO_FLIGHTREC_ERROR_KEEP", "64", "int",
              "flight recorder: error-request ring size", "observability"),
        _knob("GORDO_LOG_LEVEL", "INFO", "str",
              "root log level (`--log-level`)", "observability"),
        _knob("GORDO_LOG_FORMAT", "text", "str",
              "`text` or `json` (one JSON object per record with "
              "trace/span ids; `--log-format`)", "observability"),
        _knob("GORDO_TRACE_DIR", "unset", "path",
              "jax.profiler device-trace output dir for build/warmup "
              "phases (`--trace-dir`)", "observability"),
        _knob("GORDO_DEBUG_NANS", "0", "bool",
              "jax_debug_nans: re-run op-by-op at the first NaN "
              "(diagnostics only; `--debug-nans`)", "observability"),
        _knob("GORDO_TIMELINE_MAX_BYTES", "8192", "int",
              "trace stitching: size cap for the worker's "
              "`X-Gordo-Timeline` response header (past it the router "
              "pulls the timeline from the worker instead)",
              "observability"),
        _knob("GORDO_METRICS_MACHINE_CARDINALITY", "64", "int",
              "machine-labeled metric families render at most this many "
              "distinct machines per family (top-K by traffic) plus one "
              "`machine=\"other\"` aggregate, so exposition size is "
              "bounded at any fleet size; `0` disables the bound",
              "observability"),
        _knob("GORDO_ROUTER_AGGREGATE", "1", "bool",
              "router scrape-of-scrapes: `0` makes "
              "`/metrics?aggregate=1` serve the router registry only "
              "(no worker fan-out scrape)", "observability"),
        _knob("GORDO_SLO", "1", "bool",
              "SLO engine: `0` disables evaluation (`/slo` answers "
              "disabled, no `gordo_slo_*` series)", "observability"),
        _knob("GORDO_SLO_LATENCY_MS", "250", "float",
              "latency objective threshold: scoring/route requests "
              "should finish under this many milliseconds",
              "observability"),
        _knob("GORDO_SLO_LATENCY_TARGET", "0.99", "float",
              "latency objective: fraction of requests that must meet "
              "the threshold", "observability"),
        _knob("GORDO_SLO_AVAILABILITY_TARGET", "0.999", "float",
              "availability objective: fraction of requests that must "
              "not error (5xx / unroutable)", "observability"),
        _knob("GORDO_SLO_FAST_WINDOW", "300", "float",
              "fast burn-rate window seconds (the page-now signal)",
              "observability"),
        _knob("GORDO_SLO_SLOW_WINDOW", "3600", "float",
              "slow burn-rate window seconds (the sustained-burn "
              "signal)", "observability"),
        _knob("GORDO_SLO_FAST_BURN", "14.4", "float",
              "burn-rate threshold whose crossing on the fast window "
              "fires a breach event", "observability"),
        _knob("GORDO_SLO_SLOW_BURN", "6.0", "float",
              "burn-rate threshold whose crossing on the slow window "
              "fires a breach event", "observability"),
        _knob("GORDO_SLO_EVAL_INTERVAL", "10", "float",
              "min seconds between scrape-driven SLO evaluation ticks "
              "(`/metrics` and `/slo` reads piggyback evaluation)",
              "observability"),
        _knob("GORDO_TELEMETRY", "1", "bool",
              "fleet telemetry warehouse (§24): `0` disables the "
              "snapshotter, traffic accounting, and `/telemetry` "
              "(answers disabled)", "observability"),
        _knob("GORDO_TELEMETRY_DIR", "unset", "path",
              "warehouse segment directory; unset = "
              "`<models_root>/.telemetry/worker-<id>` (in-memory only "
              "when no models root either)", "observability"),
        _knob("GORDO_TELEMETRY_MB", "64", "int",
              "hard byte budget for the on-disk warehouse in MiB; "
              "whole oldest segments are deleted to stay under it",
              "observability"),
        _knob("GORDO_TELEMETRY_INTERVAL", "15", "float",
              "min seconds between scrape-driven warehouse snapshot "
              "ticks (`/metrics` and `/telemetry` reads piggyback)",
              "observability"),
        _knob("GORDO_TELEMETRY_TOPK", "512", "int",
              "Space-Saving sketch capacity: how many heavy-hitter "
              "machines the traffic accountant tracks exactly-ish "
              "(error bounded by total/capacity)", "observability"),
        _knob("GORDO_TELEMETRY_SEGMENT_KB", "256", "int",
              "warehouse segment rotation threshold in KiB (smaller = "
              "finer-grained budget trims, more files)",
              "observability"),
        _knob("GORDO_LEDGER", "1", "bool",
              "control ledger (§28): `0` disables control-event "
              "recording (every writer's emit becomes a no-op)",
              "observability"),
        _knob("GORDO_LEDGER_DIR", "unset", "path",
              "ledger segment root; each process appends under its own "
              "role subdir (`worker-<id>`/`router`); unset = "
              "`<models_root>/.telemetry/ledger-<role>` (in-memory only "
              "when no models root either)", "observability"),
        _knob("GORDO_LEDGER_MB", "16", "int",
              "hard byte budget for the on-disk control ledger in MiB; "
              "whole oldest segments are deleted to stay under it",
              "observability"),
        _knob("GORDO_LEDGER_SEGMENT_KB", "128", "int",
              "ledger segment rotation threshold in KiB",
              "observability"),
        _knob("GORDO_INCIDENT_LOOKBACK", "600", "float",
              "incident correlator (§28): seconds of ledger history and "
              "warehouse deltas gathered into a breach report",
              "observability"),
        _knob("GORDO_INCIDENT_COOLDOWN", "120", "float",
              "min seconds between incident reports for the same "
              "objective (breach flapping folds into one incident)",
              "observability"),
        _knob("GORDO_INCIDENT_KEEP", "32", "int",
              "incident reports retained (ring + on-disk files); oldest "
              "are dropped past it", "observability"),
        # -- autopilot (§20) ---------------------------------------------
        _knob("GORDO_AUTOPILOT", "unset", "bool",
              "closed-loop controller: `1` enables at boot, unset boots "
              "disabled but runtime-enableable (`POST /autopilot/enable`), "
              "explicit `0` is the hard kill switch (no controller at all)",
              "autopilot"),
        _knob("GORDO_AUTOPILOT_INTERVAL", "5", "float",
              "min seconds between scrape-driven autopilot evaluation "
              "ticks (`/metrics` and `/autopilot` reads piggyback them)",
              "autopilot"),
        _knob("GORDO_AUTOPILOT_BURN_HIGH", "1.0", "float",
              "fast-window burn rate at/above which the controller backs "
              "actuators off (multiplicative decrease)", "autopilot"),
        _knob("GORDO_AUTOPILOT_BURN_LOW", "0.25", "float",
              "fast-window burn rate at/below which the controller may "
              "probe upward (additive increase)", "autopilot"),
        _knob("GORDO_AUTOPILOT_COOLDOWN", "30", "float",
              "per-actuator seconds between applied adaptations (the AIMD "
              "settling time)", "autopilot"),
        _knob("GORDO_AUTOPILOT_STEP", "0.5", "float",
              "AIMD additive-increase fraction of the current value "
              "(min +1) on an upward decision", "autopilot"),
        _knob("GORDO_AUTOPILOT_BACKOFF", "0.5", "float",
              "AIMD multiplicative-decrease factor on a downward "
              "decision (never less than -1 per step)", "autopilot"),
        _knob("GORDO_AUTOPILOT_CONFIRM", "2", "int",
              "hysteresis: consecutive ticks a direction must persist "
              "before the controller acts on it", "autopilot"),
        _knob("GORDO_AUTOPILOT_SCALE_TICKS", "3", "int",
              "elastic hysteresis: consecutive ticks of sustained burn / "
              "idle before a worker is spawned or retired", "autopilot"),
        _knob("GORDO_AUTOPILOT_IDLE_RPS", "1.0", "float",
              "observed fleet request rate below which (with zero burn) "
              "sustained idle may retire a worker down to the floor",
              "autopilot"),
        _knob("GORDO_AUTOPILOT_DEPTH_BOUNDS", "1:8", "spec",
              "`min:max` hard bounds for live dispatch-depth tuning "
              "(the GORDO_DISPATCH_DEPTH actuator)", "autopilot"),
        _knob("GORDO_AUTOPILOT_FILL_BOUNDS", "0:4000", "spec",
              "`min:max` hard bounds (µs) for live fill-window tuning "
              "(the GORDO_FILL_WINDOW_US actuator)", "autopilot"),
        _knob("GORDO_AUTOPILOT_INFLIGHT_BOUNDS", "8:256", "spec",
              "`min:max` hard bounds for live admission tuning (the "
              "GORDO_MAX_INFLIGHT actuator)", "autopilot"),
        _knob("GORDO_AUTOPILOT_RESIDENCY_BOUNDS", "16:1024", "spec",
              "`min:max` hard bounds for live megabatch-residency tuning "
              "(the GORDO_MEGABATCH_RESIDENCY actuator; partial-residency "
              "buckets only)", "autopilot"),
        _knob("GORDO_AUTOPILOT_WORKER_BOUNDS", "1:8", "spec",
              "`floor:ceiling` for the elastic worker count (the router's "
              "spawn/retire actuator)", "autopilot"),
        _knob("GORDO_AUTOPILOT_SHED_BOUNDS", "0:8", "spec",
              "`min:max` rungs for the shed-ladder actuator (§25): "
              "sustained SLO burn progressively tightens the BULK "
              "class's admission share, relaxing on recovery", "autopilot"),
        # -- fleet reconciler (§26) --------------------------------------
        _knob("GORDO_FLEET", "unset", "bool",
              "declarative fleet reconciler: unset/`1` constructs it "
              "(inert until a spec is committed via `/fleet/apply`), "
              "explicit `0` is the hard kill switch (no reconciler at "
              "all; `/fleet` answers hard_off)", "fleet"),
        _knob("GORDO_FLEET_INTERVAL", "10", "float",
              "min seconds between scrape-driven reconcile ticks "
              "(`/metrics` and `/fleet` reads piggyback them)", "fleet"),
        _knob("GORDO_FLEET_REPAIR_BUDGET", "2", "int",
              "max repairs applied per reconcile tick — a degraded "
              "fleet is nudged toward spec, never stormed; the rest "
              "journal `deferred`", "fleet"),
        _knob("GORDO_FLEET_COOLDOWN", "30", "float",
              "seconds a divergence class rests after a repair (seeded "
              "from the reconcile WAL on restart); the oscillation "
              "guard's hold window is 4× this", "fleet"),
        # -- layout compiler (§27) ---------------------------------------
        _knob("GORDO_LAYOUT_HORIZON", "10m", "str",
              "rate horizon the reconciler's layout staleness check and "
              "re-derive compile read telemetry over (seconds or "
              "`1m`/`10m`/`1h` forms; snaps to the nearest warehouse "
              "EWMA horizon)", "layout"),
        _knob("GORDO_LAYOUT_MAX_AGE", "900", "float",
              "seconds before a committed layout plan counts as stale "
              "on age alone and the reconciler re-derives it", "layout"),
        _knob("GORDO_LAYOUT_DRIFT", "0.35", "float",
              "total-variation distance between the plan's recorded "
              "traffic shares and fresh telemetry above which the plan "
              "counts as stale (0..1)", "layout"),
        _knob("GORDO_LAYOUT_REDERIVE", "1", "bool",
              "`0` stops the reconciler from re-deriving stale layout "
              "plans (it keeps converging on the committed one; "
              "`gordo layout apply` stays the only author)", "layout"),
        _knob("GORDO_LAYOUT_PARITY_BUDGET", "0", "float",
              "traffic-weighted parity budget `compile_plan` may spend "
              "on precision downgrades when the caller passes none "
              "(0 disables planned downgrades)", "layout"),
        # -- store -------------------------------------------------------
        _knob("GORDO_STORE_KEEP_GENERATIONS", "3", "int",
              "generations kept per machine after a commit prunes old "
              "ones (always ≥ 2 so one rollback step survives)", "store"),
        _knob("GORDO_MAX_ARTIFACT_BYTES", "2 GiB", "int",
              "bounded artifact loads: max decompressed tar bytes a "
              "model load will extract", "store"),
        _knob("GORDO_STORE_FSYNC", "1", "bool",
              "`0` disables commit-path fsyncs (durability escape hatch "
              "for bulk synthetic-fleet generation — atomicity is kept, "
              "power-cut durability is not)", "store"),
        # -- precision ladder (§19) --------------------------------------
        _knob("GORDO_PRECISION_DEFAULT", "f32", "str",
              "build-time default rung on the serving precision ladder "
              "(`f32`/`bf16`/`int8`); `--precision` on `build` and "
              "`fleet-build` overrides, `--precision-map` pins per "
              "machine", "build"),
        _knob("GORDO_PARITY_RTOL_BF16", "0.02", "float",
              "bf16 parity budget: max |bf16−f32| of total anomaly "
              "scores, normalized to the mean f32 score (gated by "
              "quant_smoke and the bench precision block)", "test"),
        _knob("GORDO_PARITY_RTOL_INT8", "0.08", "float",
              "int8 parity budget: same ruler as the bf16 budget, "
              "looser — int8 trades more accuracy for 4x weight "
              "compression", "test"),
        # -- build / multihost -------------------------------------------
        _knob("GORDO_FORCED_CPU", "0", "bool",
              "force the CPU backend even when an accelerator is visible "
              "(CI / wedged-tunnel escape hatch)", "build"),
        _knob("GORDO_COORDINATOR", "unset", "str",
              "multihost: coordinator address for "
              "`jax.distributed.initialize` (`--coordinator-address`)",
              "build"),
        _knob("GORDO_NUM_PROCESSES", "unset", "int",
              "multihost: world size (`--num-processes`)", "build"),
        _knob("GORDO_PROCESS_ID", "unset", "int",
              "multihost: this process's rank (`--process-id`)", "build"),
        _knob("GORDO_SLICE_TIMEOUT_S", "unset", "float",
              "fleet build: per-slice collective timeout before the "
              "straggler handling kicks in", "build"),
        _knob("GORDO_BUILD_FETCH_RETRIES", "2", "int",
              "fleet build: per-machine data-fetch retries before "
              "zero-weight isolation", "build"),
        _knob("GORDO_BUILD_FETCH_BACKOFF", "1.0", "float",
              "fleet build: base seconds between data-fetch retries "
              "(exponential)", "build"),
        # -- bench -------------------------------------------------------
        _knob("GORDO_BENCH_HISTORY", "BENCH_HISTORY.jsonl", "path",
              "where bench.py / bench_serving.py append their history "
              "rows (tests point it at /dev/null)", "bench"),
        _knob("GORDO_RESET_BENCH_ANCHOR", "0", "bool",
              "reseed the bench-regression anchor ring (after a rig "
              "change that legitimately moved the baseline)", "bench"),
        _knob("GORDO_CAPACITY_MACHINES", "2000 (smoke) / 10000 (bench)",
              "int",
              "capacity harness (§22): synthetic-fleet size for "
              "`tools/capacity_smoke.py` and the bench `capacity` block",
              "bench"),
        _knob("GORDO_CAPACITY_SECONDS", "8", "float",
              "capacity harness: seconds of production-shaped load per "
              "traffic phase", "bench"),
        _knob("GORDO_CAPACITY_SWEEP_MACHINES", "100000", "int",
              "capacity harness: fleet size for the `slow`-marked full "
              "sweep (tests/test_capacity_slow.py) — scale down for a "
              "faster manual run", "bench"),
        _knob("GORDO_TELEMETRY_SMOKE_MACHINES", "120", "int",
              "telemetry smoke (§24): synthetic-fleet size for "
              "`tools/telemetry_smoke.py`", "bench"),
        _knob("GORDO_TELEMETRY_SMOKE_SECONDS", "5", "float",
              "telemetry smoke: seconds of Zipf load through the "
              "2-worker router tier", "bench"),
        _knob("GORDO_TELEMETRY_BENCH_MACHINES", "300", "int",
              "bench `telemetry` block (§24): synthetic-fleet size",
              "bench"),
        _knob("GORDO_TELEMETRY_BENCH_SECONDS", "6", "float",
              "bench `telemetry` block: seconds of Zipf load before "
              "the scrape-cost and warehouse-economy measurements",
              "bench"),
        _knob("GORDO_QOS_SMOKE_MACHINES", "24", "int",
              "qos smoke (§25): synthetic-fleet size for "
              "`tools/qos_smoke.py`", "bench"),
        _knob("GORDO_QOS_SMOKE_SECONDS", "5", "float",
              "qos smoke: seconds of the three-tenant mix through the "
              "2-worker router tier", "bench"),
        _knob("GORDO_QOS_SMOKE_P99_MS", "6000", "float",
              "qos smoke: premium p99 bound under bulk saturation — "
              "deliberately coarse (below the queue-timeout cliff); "
              "zero premium sheds is the sharp gate", "bench"),
        _knob("GORDO_RECONCILE_SMOKE_MACHINES", "6", "int",
              "reconcile smoke (§26): synthetic-fleet size for "
              "`tools/reconcile_smoke.py`", "bench"),
        _knob("GORDO_RECONCILE_SMOKE_TIMEOUT", "240", "float",
              "reconcile smoke: per-phase convergence deadline in "
              "seconds (covers the bf16 precision rebuild)", "bench"),
        _knob("GORDO_LAYOUT_SMOKE_MACHINES", "48", "int",
              "layout smoke (§27): synthetic-fleet size for "
              "`tools/layout_smoke.py`", "bench"),
        _knob("GORDO_LAYOUT_BENCH_MACHINES", "48", "int",
              "bench `layout` block (§27): synthetic-fleet size for "
              "the name-hash vs computed-plan A/B", "bench"),
        _knob("GORDO_LAYOUT_BENCH_SECONDS", "5", "float",
              "bench `layout` block: seconds of Zipf load per A/B "
              "phase", "bench"),
        _knob("GORDO_LAYOUT_SMOKE_SECONDS", "5", "float",
              "layout smoke: seconds of skewed Zipf load per phase "
              "through the 2-worker router tier", "bench"),
        _knob("GORDO_INCIDENT_SMOKE_MACHINES", "8", "int",
              "incident smoke (§28): synthetic-fleet size for "
              "`tools/incident_smoke.py`", "bench"),
        _knob("GORDO_INCIDENT_SMOKE_SECONDS", "6", "float",
              "incident smoke: seconds of load driven through the "
              "fault-stalled server while waiting for the breach "
              "incident", "bench"),
        # -- test / validation harnesses ---------------------------------
        _knob("GORDO_LOCKCHECK", "0", "bool",
              "runtime lock-order validator: named locks record real "
              "acquisition orders and fail the tests on any order the "
              "declared hierarchy (analysis/locks.py) forbids", "test"),
        _knob("GORDO_ISOLATE_CPU", "0", "bool",
              "tools/tpu_isolate.py child: pin the CPU backend via "
              "jax.config for a real local compile measurement (the axon "
              "plugin ignores JAX_PLATFORMS)", "test"),
        _knob("GORDO_TEST_NO_COMPILE_CACHE", "0", "bool",
              "run the pytest suite with the persistent XLA compile "
              "cache disabled (jaxlib segfault-isolation experiment)",
              "test"),
    ]
)


def get(name: str) -> Optional[Knob]:
    return KNOBS.get(name)


def render_markdown_table(component: Optional[str] = None) -> str:
    """The README knob table (all components interleaved, sorted by
    component then name) — regenerate with
    ``python -m gordo_components_tpu.analysis --write-knob-table``."""
    rows = [
        knob for knob in KNOBS.values()
        if component is None or knob.component == component
    ]
    rows.sort(key=lambda knob: (knob.component, knob.name))
    lines = [
        "| knob | default | meaning |",
        "|---|---|---|",
    ]
    for knob in rows:
        lines.append(f"| `{knob.name}` | `{knob.default}` | {knob.doc} |")
    return "\n".join(lines)
