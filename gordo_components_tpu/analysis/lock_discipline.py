"""Static lock-discipline checker.

Two rules over the declared hierarchy in :mod:`.locks`:

1. **lock-order-inversion** — a ``with`` over a known lock while
   already (lexically) holding a lock of equal or higher rank. The
   analysis is intra-procedural over ``with``-statements: that is
   where every hot-path acquisition in this codebase lives
   (``acquire()``-style critical sections exist only on the admin
   paths; the runtime validator — :mod:`.lockcheck` — covers those
   and every cross-function composition the static walk cannot see).

2. **blocking-under-lock** — a blocking call (device fetch, HTTP,
   parameterless ``.join()``, ``sleep``, XLA ``.compile``) made while
   a HOT lock is held, including one level into same-module callees
   (the collector-handover join hides behind a method call). Escape
   hatch: ``# lint: allow-blocking(<reason>)`` on the flagged line;
   the reason is mandatory.

``Condition.wait`` is deliberately NOT a blocking call: waiting
releases the lock — that is the one blocking thing a condition is for.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astscan import (
    Module,
    attr_chain_names,
    dotted,
    iter_calls,
    resolve_target,
)
from .findings import Finding
from .locks import HOT_LOCKS, LOCK_ATTRS, LOCK_RANKS

CHECKER = "lock-discipline"

_HTTP_VERBS = frozenset(
    {"get", "post", "put", "delete", "head", "request", "send"}
)


def _lock_map_for(relpath: str) -> Dict[str, str]:
    """attribute name -> lock name, for the file being scanned."""
    out: Dict[str, str] = {}
    for (suffix, attr), name in LOCK_ATTRS.items():
        if relpath.endswith(suffix):
            out[attr] = name
    return out


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call blocks, or None. Vocabulary from ISSUE/§17:
    device fetches, HTTP, joins, sleeps, compiles."""
    name = dotted(call.func)
    if not name:
        return None
    last = name.split(".")[-1]
    parts = name.split(".")
    if last in ("device_get", "block_until_ready"):
        return f"{name} blocks on device completion"
    if last == "compile" and len(parts) > 1:
        return f"{name} pays an XLA compile"
    if last == "sleep":
        return f"{name} sleeps"
    if last == "join" and not call.args:
        # Queue.join()/Thread.join(): parameterless (or timeout-kwarg)
        # joins block; ``", ".join(parts)`` always has a positional arg
        return f"{name}() joins"
    if last in _HTTP_VERBS and len(parts) > 1:
        chain = [p.lower() for p in parts[:-1]]
        if any("session" in p or p == "requests" for p in chain):
            return f"{name} performs network I/O"
    return None


def _is_function(node: ast.AST) -> bool:
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))


class _Scope:
    def __init__(self, module: Module, lock_map: Dict[str, str],
                 scope_name: str, scope_node: ast.AST,
                 findings: List[Finding]):
        self.module = module
        self.lock_map = lock_map
        self.scope_name = scope_name
        self.scope_node = scope_node
        self.findings = findings
        self.held: List[str] = []

    # -- rule 2 ---------------------------------------------------------------
    def _flag_blocking(self, node: ast.AST, line: int, why: str,
                       key_extra: str, via: str = "") -> None:
        hot_held = [name for name in self.held if name in HOT_LOCKS]
        if not hot_held:
            return
        suppression = self.module.allows("blocking", line)
        if suppression is not None:
            if not suppression.reason:
                self.findings.append(
                    Finding(
                        checker=CHECKER, code="empty-escape-reason",
                        file=self.module.relpath, line=line,
                        key=f"{self.scope_name}:{key_extra}",
                        message=(
                            "allow-blocking escape hatch carries no "
                            "reason — the reason is the contract"
                        ),
                        hint="write # lint: allow-blocking(<why it is safe>)",
                    )
                )
            return
        lock = hot_held[-1]
        detail = f" (reached via {via})" if via else ""
        self.findings.append(
            Finding(
                checker=CHECKER, code="blocking-under-lock",
                file=self.module.relpath, line=line,
                key=f"{lock}:{self.scope_name}:{key_extra}",
                message=(
                    f"{why} while holding hot lock {lock!r}{detail} — "
                    "live requests stall behind this"
                ),
                hint=(
                    "move the call outside the lock, or annotate the "
                    "line with # lint: allow-blocking(<reason>)"
                ),
            )
        )

    def _check_call(self, call: ast.Call) -> None:
        why = _blocking_reason(call)
        if why is not None:
            self._flag_blocking(
                call, call.lineno, why, key_extra=dotted(call.func)
            )
            return
        # one level into same-module callees: a blocking call hidden
        # behind ``self._ensure_collector()`` still runs under our lock
        # (same sound bare-name/self.method resolution as span_seam)
        name, node = resolve_target(self.module, self.scope_node, call.func)
        if node is None or not _is_function(node):
            return
        for inner in iter_calls(node):
            if _within_nested_function(node, inner):
                continue
            inner_why = _blocking_reason(inner)
            if inner_why is not None:
                self._flag_blocking(
                    call, call.lineno, inner_why,
                    key_extra=f"{name}:{dotted(inner.func)}",
                    via=f"{name}() at line {inner.lineno}",
                )

    # -- walk -----------------------------------------------------------------
    def visit(self, node: ast.AST) -> None:
        if _is_function(node):
            return  # separate scope; analyzed on its own with no locks held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items acquire LEFT TO RIGHT, so each is pushed before the
            # next is checked — ``with a, b:`` must flag a→b inversions
            # exactly like the nested form. Context expressions that are
            # CALLS (``with session.post(url):``) evaluate under every
            # lock already held, so they get the blocking check too.
            pushed = 0
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        self._check_call(sub)
                lock = self._resolve_lock(item.context_expr)
                if lock is not None:
                    self._check_order(lock, node.lineno)
                    self.held.append(lock)
                    pushed += 1
            try:
                for child in node.body:
                    self.visit(child)
            finally:
                if pushed:
                    del self.held[-pushed:]
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        for name in attr_chain_names(expr):
            lock = self.lock_map.get(name)
            if lock is not None:
                return lock
        return None

    # -- rule 1 ---------------------------------------------------------------
    def _check_order(self, inner: str, line: int) -> None:
        for outer in self.held:
            if LOCK_RANKS[inner] <= LOCK_RANKS[outer]:
                self.findings.append(
                    Finding(
                        checker=CHECKER, code="lock-order-inversion",
                        file=self.module.relpath, line=line,
                        key=f"{outer}->{inner}:{self.scope_name}",
                        message=(
                            f"acquires {inner!r} (rank "
                            f"{LOCK_RANKS[inner]}) while holding "
                            f"{outer!r} (rank {LOCK_RANKS[outer]}); the "
                            "declared order is strictly rank-increasing"
                        ),
                        hint=(
                            "release the outer lock first, or re-rank in "
                            "analysis/locks.py with an ARCHITECTURE §17 "
                            "justification"
                        ),
                    )
                )


def _within_nested_function(scope: ast.AST, node: ast.AST) -> bool:
    """True when ``node`` sits inside a function nested under ``scope``
    (it runs later, not under the caller's locks)."""
    for sub in ast.walk(scope):
        if _is_function(sub) and sub is not scope:
            for inner in ast.walk(sub):
                if inner is node:
                    return True
    return False


def check(module: Module) -> List[Finding]:
    lock_map = _lock_map_for(module.relpath)
    if not lock_map:
        return []
    findings: List[Finding] = []
    scopes: List[Tuple[str, ast.AST]] = [("<module>", module.tree)]
    seen: Set[int] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if id(node) not in seen:
                seen.add(id(node))
                scopes.append((node.name, node))
    for scope_name, scope_node in scopes:
        scope = _Scope(module, lock_map, scope_name, scope_node, findings)
        for child in scope_node.body:  # type: ignore[attr-defined]
            scope.visit(child)
    return findings
