"""Knob-registry checker: every ``GORDO_*`` mention must be declared.

The rule is deliberately blanket: ANY ``GORDO_*`` token embedded in a
string constant in the scanned tree — an ``os.environ.get``, a click
``envvar=``, a generated k8s env spec, a docstring's prose mention —
must have a :mod:`.knobs` entry. Mentions in prose are exactly how
knob docs drift, so they are held to the same registry the README
table is generated from. (``analysis/knobs.py`` itself is excluded
from the scan by the runner — its literals ARE the registry, and
counting them would make the staleness check below circular.)

The runner adds the reverse direction: a registered knob mentioned
NOWHERE is stale and flagged (``collect_mentions`` feeds it).
"""

from __future__ import annotations

import ast
import re
from typing import List, Set, Tuple

from .astscan import Module
from .findings import Finding
from .knobs import KNOBS

CHECKER = "knob-registry"

# embedded tokens, word-bounded: "set GORDO_FOO=1 to ..." in a
# docstring mentions GORDO_FOO; a dangling "GORDO_" prefix fragment
# (string concatenation in tests) is not a knob name
_KNOB_RE = re.compile(r"\bGORDO_[A-Z0-9_]*[A-Z0-9]\b")


def _mentions(module: Module) -> List[Tuple[str, ast.Constant]]:
    out = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for name in _KNOB_RE.findall(node.value):
                out.append((name, node))
    return out


def collect_mentions(module: Module) -> Set[str]:
    return {name for name, _ in _mentions(module)}


def check(module: Module) -> List[Finding]:
    findings: List[Finding] = []
    flagged: Set[str] = set()
    for name, node in _mentions(module):
        if name in KNOBS or name in flagged:
            continue
        flagged.add(name)  # one finding per knob per file
        findings.append(
            Finding(
                checker=CHECKER, code="unregistered-knob",
                file=module.relpath, line=node.lineno, key=name,
                message=(
                    f"{name} is not declared in analysis/knobs.py — "
                    "undeclared knobs are invisible to the generated "
                    "README table and rot undocumented"
                ),
                hint=(
                    "add a Knob entry (name, default, parser, one-line "
                    "doc) to analysis/knobs.py, then regenerate the "
                    "README table"
                ),
            )
        )
    return findings


def stale_knobs(all_mentions: Set[str]) -> List[Finding]:
    """Registered knobs no code or doc mentions any more."""
    findings = []
    for name in sorted(set(KNOBS) - all_mentions):
        findings.append(
            Finding(
                checker=CHECKER, code="stale-knob",
                file="gordo_components_tpu/analysis/knobs.py", line=1,
                key=name,
                message=(
                    f"{name} is registered but mentioned nowhere in the "
                    "tree — delete the entry or the dead knob it "
                    "documents"
                ),
                hint="remove the Knob entry and regenerate the README table",
            )
        )
    return findings
