"""Shared AST plumbing for the checkers: parsed modules, function
indexes, dotted-name rendering, and the escape-hatch comment grammar.

Escape hatches are line comments of the form::

    # lint: allow-blocking(reason the analyzer cannot know)

The reason is mandatory — an empty one is itself a finding, because a
bare suppression is exactly the un-checkable prose this package exists
to replace.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow-([a-z-]+)\(([^)]*)\)")


@dataclass
class Suppression:
    code: str    # e.g. "blocking"
    reason: str
    line: int


@dataclass
class Module:
    """One parsed source file plus the lookups every checker needs."""

    path: str           # absolute
    relpath: str        # repo-relative (finding coordinates)
    source: str
    tree: ast.Module
    # line -> suppressions declared on that line
    suppressions: Dict[int, List[Suppression]] = field(default_factory=dict)
    # function/method name -> def node (methods keyed both bare and
    # "Class.method"; last definition wins, which matches runtime)
    functions: Dict[str, ast.AST] = field(default_factory=dict)

    def allows(self, code: str, line: int) -> Optional[Suppression]:
        for suppression in self.suppressions.get(line, ()):
            if suppression.code == code:
                return suppression
        return None


def parse_module(path: str, relpath: str) -> Optional[Module]:
    try:
        with open(path, "r", encoding="utf-8") as handle:
            source = handle.read()
        tree = ast.parse(source, filename=relpath)
    except (OSError, SyntaxError):
        return None
    module = Module(path=path, relpath=relpath, source=source, tree=tree)
    for i, text in enumerate(source.splitlines(), start=1):
        for match in _ALLOW_RE.finditer(text):
            module.suppressions.setdefault(i, []).append(
                Suppression(
                    code=match.group(1), reason=match.group(2).strip(),
                    line=i,
                )
            )
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module.functions[node.name] = node
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    module.functions[f"{node.name}.{item.name}"] = item
    return module


def dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain
    (``jax.device_get`` / ``self._session.post``); '' when the
    expression is not a plain chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # e.g. ``self._http().get`` — render the callee chain with ()
        inner = dotted(node.func)
        parts.append(f"{inner}()" if inner else "()")
    else:
        return ""
    return ".".join(reversed(parts))


def attr_chain_names(node: ast.AST) -> Iterator[str]:
    """Every attribute/name identifier appearing in an expression —
    how ``with self._dispatch_lock or contextlib.nullcontext():``
    still resolves to ``_dispatch_lock``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            yield sub.attr
        elif isinstance(sub, ast.Name):
            yield sub.id


def local_functions(node: ast.AST) -> Dict[str, ast.AST]:
    """Defs nested directly inside ``node``'s body (closures handed to
    Thread(target=...) and friends)."""
    out: Dict[str, ast.AST] = {}
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[sub.name] = sub
    return out


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def resolve_target(
    module: Module, scope: ast.AST, expr: ast.AST
) -> Tuple[str, Optional[ast.AST]]:
    """Resolve a callable expression (a ``target=`` argument, a
    submitted coroutine call) to a function node in this module when
    possible. Returns (display name, node-or-None).

    SOUND resolution only: bare names and ``self.method`` — an
    attribute on any other receiver (``session.close``,
    ``loop.run_forever``) could be anything, and guessing by suffix
    produces false positives. Innermost scope wins (closures shadow
    module-level defs)."""
    if isinstance(expr, ast.Call):  # submitted coroutine: f(...)
        expr = expr.func
    name = dotted(expr)
    if not name:
        if isinstance(expr, ast.Lambda):
            return "<lambda>", expr
        return "<expr>", None
    parts = name.split(".")
    if len(parts) > 2 or (len(parts) == 2 and parts[0] != "self"):
        return name, None
    short = parts[-1]
    node = local_functions(scope).get(short) or module.functions.get(short)
    return name, node
