"""Findings and the grandfather baseline.

A finding is one violation at one source location. Its ``ident`` is
deliberately LINE-FREE — ``checker:file:code:key`` — so a baseline
entry keeps matching while unrelated edits move the code around, and
stops matching the moment the underlying violation is actually fixed
(at which point the stale entry itself becomes a finding: the baseline
must shrink, never silently rot).

The gate is therefore "no NEW violations": everything the checkers
find must either be fixed or carry a ``lint_baseline.json`` entry with
a human-written reason.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

SEVERITIES = ("error", "warning")


@dataclass
class Finding:
    checker: str      # e.g. "lock-discipline"
    code: str         # e.g. "blocking-under-lock"
    file: str         # repo-relative path
    line: int
    message: str
    key: str = ""     # stable discriminator (lock pair, metric name, ...)
    severity: str = "error"
    hint: str = ""    # fix-it suggestion

    @property
    def ident(self) -> str:
        return f"{self.checker}:{self.file}:{self.code}:{self.key}"

    def render(self) -> str:
        text = (
            f"{self.file}:{self.line} {self.severity} "
            f"{self.checker}[{self.code}] {self.message}"
        )
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclass
class Baseline:
    """``lint_baseline.json``: grandfathered findings, each with a
    reason. Matching is by line-free ident; entries that match nothing
    are stale and reported as findings themselves."""

    entries: Dict[str, str] = field(default_factory=dict)  # ident -> reason
    path: Optional[str] = None

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as handle:
                raw = json.load(handle)
        except FileNotFoundError:
            return cls(path=path)
        entries: Dict[str, str] = {}
        for entry in raw.get("findings", []):
            entries[str(entry["id"])] = str(entry.get("reason", ""))
        return cls(entries=entries, path=path)

    def save(self, path: Optional[str] = None) -> None:
        target = path or self.path
        assert target, "baseline has no path"
        payload: Dict[str, Any] = {
            "version": 1,
            "findings": [
                {"id": ident, "reason": reason}
                for ident, reason in sorted(self.entries.items())
            ],
        }
        with open(target, "w", encoding="utf-8") as handle:
            json.dump(payload, handle, indent=2)
            handle.write("\n")

    def split(self, findings: List[Finding]):
        """Partition findings into (fresh, suppressed) and compute the
        stale baseline idents (entries matching no current finding).

        A suppressing entry must also be JUSTIFIED: ``--write-baseline``
        stubs reasons as ``TODO: justify``, and an entry still carrying
        a stub (or an empty reason) is itself a finding — the baseline
        may only hold keeps a human has written a reason for, so stubs
        expire instead of quietly becoming permanent."""
        fresh: List[Finding] = []
        suppressed: List[Finding] = []
        seen = set()
        for finding in findings:
            if finding.ident in self.entries:
                suppressed.append(finding)
                seen.add(finding.ident)
            else:
                fresh.append(finding)
        for ident in sorted(seen):
            reason = self.entries.get(ident, "").strip()
            if not reason or reason.upper().startswith("TODO"):
                fresh.append(
                    Finding(
                        checker="baseline",
                        code="unjustified-keep",
                        file=self.path or "lint_baseline.json",
                        line=1,
                        key=ident,
                        message=(
                            f"baseline entry {ident!r} suppresses a "
                            "finding without a written reason "
                            f"({reason or 'empty'!r})"
                        ),
                        hint=(
                            "replace the stub with WHY this violation "
                            "is a deliberate keep, or fix the violation "
                            "and delete the entry"
                        ),
                    )
                )
        stale = sorted(set(self.entries) - seen)
        for ident in stale:
            fresh.append(
                Finding(
                    checker="baseline",
                    code="stale-entry",
                    file=self.path or "lint_baseline.json",
                    line=1,
                    key=ident,
                    message=(
                        f"baseline entry {ident!r} matches no current "
                        "finding — the violation it grandfathers is gone"
                    ),
                    hint="delete the entry from lint_baseline.json",
                )
            )
        return fresh, suppressed
