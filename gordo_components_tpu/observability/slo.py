"""SLO engine: declared objectives, evaluated by multi-window burn rate.

PRs 1/5 collect the raw signal (labeled histograms, per-request stage
timelines); nothing DERIVES from it — "is the fleet meeting its latency
objective, and how fast is it eating the error budget" still required a
human with a calculator. This module is that derived layer, and the
signal ROADMAP item 5's adaptive controller will read:

- an :class:`Objective` declares either a **latency** target ("≥ 99% of
  ``/anomaly`` requests under 250 ms", read from the already-collected
  histogram buckets — the threshold snaps to the nearest bucket bound,
  reported as ``effective_threshold_s``) or an **availability** target
  ("error ratio < 0.1%", read from status-labeled counters);
- the :class:`SLOEvaluator` keeps a bounded ring of cumulative
  ``(t, good, total)`` samples per objective and computes the **burn
  rate** — bad-ratio ÷ error-budget — over a fast (~5 m) and a slow
  (~1 h) window. Burn 1.0 = exactly spending the budget; the classic
  multi-window thresholds (fast ≈ 14.4, slow ≈ 6) page on budget-gone-
  in-hours, not on one slow request;
- every evaluation publishes ``gordo_slo_*`` series into the SAME
  registry the raw signal lives in, so one scrape carries both; a
  threshold CROSSING (edge, not level) increments
  ``gordo_slo_breaches_total`` and records a synthetic errored timeline
  into the flight recorder — ``/debug/requests`` shows *when the budget
  started burning* next to the requests that burned it;
- :func:`attribute_stages` answers "which span stage ate the SLO": over
  the recorder's violating requests, the share of time per leaf stage.

Evaluation is SCRAPE-DRIVEN, not threaded: ``maybe_tick`` piggybacks on
``/metrics`` and ``/slo`` reads (min-interval-gated), so the engine
costs nothing while nobody is looking and needs no supervisor thread.
The clock is injectable end to end — the burn-rate tests run years of
window arithmetic in microseconds, with zero real sleeps.
"""

from __future__ import annotations

import logging
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis import lockcheck
from . import flightrec
from . import ledger as control_ledger
from .registry import REGISTRY, Histogram, Registry
from .spans import Timeline

logger = logging.getLogger(__name__)

_M_ATTAINMENT = REGISTRY.gauge(
    "gordo_slo_attainment",
    "Good-event fraction since boot per objective (1.0 = every request "
    "met the objective)",
    labels=("name",),
)
_M_TARGET = REGISTRY.gauge(
    "gordo_slo_target",
    "Declared good-event-fraction objective (the SLO itself)",
    labels=("name",),
)
_M_BURN_RATE = REGISTRY.gauge(
    "gordo_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 = spending "
    "exactly the declared budget; fast/slow window sizes are knobs)",
    labels=("name", "window"),
)
_M_BREACHES = REGISTRY.counter(
    "gordo_slo_breaches_total",
    "Burn-rate threshold CROSSINGS (edge-triggered) per objective and "
    "window — each one also lands in the flight recorder",
    labels=("name", "window"),
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def enabled() -> bool:
    """GORDO_SLO=0 disables the evaluator (endpoints answer disabled)."""
    return os.environ.get("GORDO_SLO", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


@dataclass(frozen=True)
class Objective:
    """One declared objective over already-collected registry series.

    ``kind="latency"``: ``metric`` names a histogram; good events are
    observations ≤ ``threshold_s`` (snapped to a bucket bound) in series
    matching ``label_filter``.

    ``kind="availability"``: good = ``metric``/``label_filter`` counter
    sum minus ``bad_filter``-matching counts of ``bad_metric`` (default:
    same family); total = all ``label_filter`` matches (plus the bad
    family's matches when it is a different family).

    Filter values: exact string, tuple/set of options, or a predicate
    callable — enough to say ``status startswith "5"`` declaratively in
    code without a mini-language.
    """

    name: str
    kind: str                      # "latency" | "availability"
    metric: str
    target: float                  # good fraction objective in (0, 1]
    threshold_s: Optional[float] = None
    label_filter: Optional[Dict[str, Any]] = None
    bad_metric: Optional[str] = None
    bad_filter: Optional[Dict[str, Any]] = None
    description: str = ""


def _value_matches(have: str, want: Any) -> bool:
    if callable(want):
        return bool(want(have))
    if isinstance(want, (tuple, list, set, frozenset)):
        return have in want
    return have == str(want)


def _matches(
    labelnames: Tuple[str, ...],
    values: Tuple[str, ...],
    label_filter: Optional[Dict[str, Any]],
) -> bool:
    if not label_filter:
        return True
    labels = dict(zip(labelnames, values))
    for key, want in label_filter.items():
        have = labels.get(key)
        if have is None or not _value_matches(have, want):
            return False
    return True


class SLOEvaluator:
    """Windowed burn-rate evaluation over a registry's cumulative series.

    One instance per process role (server / router), sharing the
    process registry. ``clock`` is any monotonic float source — tests
    inject a fake; ``recorder`` defaults to the process flight recorder.
    """

    def __init__(
        self,
        objectives: List[Objective],
        registry: Registry = REGISTRY,
        fast_window: Optional[float] = None,
        slow_window: Optional[float] = None,
        fast_burn: Optional[float] = None,
        slow_burn: Optional[float] = None,
        min_interval: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        recorder: Optional[flightrec.FlightRecorder] = None,
        breach_hook: Optional[Callable[[Dict[str, Any]], Any]] = None,
    ):
        self.objectives = list(objectives)
        self.registry = registry
        self.fast_window = (
            fast_window if fast_window is not None
            else _env_float("GORDO_SLO_FAST_WINDOW", 300.0)
        )
        self.slow_window = (
            slow_window if slow_window is not None
            else _env_float("GORDO_SLO_SLOW_WINDOW", 3600.0)
        )
        self.fast_burn = (
            fast_burn if fast_burn is not None
            else _env_float("GORDO_SLO_FAST_BURN", 14.4)
        )
        self.slow_burn = (
            slow_burn if slow_burn is not None
            else _env_float("GORDO_SLO_SLOW_BURN", 6.0)
        )
        self.min_interval = (
            min_interval if min_interval is not None
            else _env_float("GORDO_SLO_EVAL_INTERVAL", 10.0)
        )
        self._clock = clock
        self._recorder = recorder
        # §28: called once per breach EDGE with the crossing dict —
        # the incident correlator's entry point (set post-construction
        # by server/router wiring; never called under the SLO lock)
        self.breach_hook = breach_hook
        self._lock = lockcheck.named_lock("observability.slo")
        # per objective: ring of (t, good, total) cumulative samples,
        # pruned past the slow window — bounded by construction
        self._history: Dict[str, List[Tuple[float, float, float]]] = {
            objective.name: [] for objective in self.objectives
        }
        self._last_tick: Optional[float] = None
        self._breached: Dict[Tuple[str, str], bool] = {}
        self._breach_counts: Dict[Tuple[str, str], int] = {}
        self.ticks = 0
        for objective in self.objectives:
            _M_TARGET.labels(objective.name).set(objective.target)
        # baseline sample: burn rates are deltas, and the first tick
        # needs something to delta against
        self.tick()

    # -- cumulative totals off the registry ----------------------------------
    def _metric(self, name: str):
        for metric in self.registry.metrics():
            if metric.name == name:
                return metric
        return None

    def _latency_totals(self, objective: Objective) -> Tuple[float, float]:
        metric = self._metric(objective.metric)
        if not isinstance(metric, Histogram):
            return 0.0, 0.0
        good = total = 0.0
        threshold = objective.threshold_s or 0.0
        for values, data in metric.collect().items():
            if not _matches(
                metric.labelnames, values, objective.label_filter
            ):
                continue
            cumulative = 0.0
            for le, cum in data["buckets"]:
                if le >= threshold - 1e-12:
                    cumulative = cum
                    break
            good += cumulative
            total += data["count"]
        return good, total

    def effective_threshold(self, objective: Objective) -> Optional[float]:
        """The bucket bound the threshold snapped UP to (counts below it
        are observable; anything between it and the raw threshold is
        not) — reported so the objective is honest about its resolution."""
        metric = self._metric(objective.metric)
        if not isinstance(metric, Histogram) or objective.threshold_s is None:
            return objective.threshold_s
        for le in metric.buckets:
            if le >= objective.threshold_s - 1e-12:
                return None if math.isinf(le) else le
        return None

    def _availability_totals(
        self, objective: Objective
    ) -> Tuple[float, float]:
        metric = self._metric(objective.metric)
        if metric is None:
            return 0.0, 0.0
        base = 0.0
        for values, value in metric.collect().items():
            if _matches(metric.labelnames, values, objective.label_filter):
                base += value
        bad_name = objective.bad_metric or objective.metric
        bad_metric = self._metric(bad_name)
        bad = 0.0
        if bad_metric is not None:
            for values, value in bad_metric.collect().items():
                if _matches(
                    bad_metric.labelnames, values, objective.bad_filter
                ):
                    bad += value
        if bad_name == objective.metric:
            # bad is a SUBSET of the base counts
            total = base
            good = max(0.0, base - bad)
        else:
            # separate failure family (e.g. unroutable): base counts are
            # the good ones, the other family adds the bad
            total = base + bad
            good = base
        return good, total

    def _totals(self, objective: Objective) -> Tuple[float, float]:
        if objective.kind == "latency":
            return self._latency_totals(objective)
        return self._availability_totals(objective)

    # -- evaluation ----------------------------------------------------------
    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Scrape-path entry: tick when ``min_interval`` has elapsed."""
        now = self._clock() if now is None else now
        with self._lock:
            due = (
                self._last_tick is None
                or now - self._last_tick >= self.min_interval
            )
        if due:
            self.tick(now)
        return due

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation: sample cumulative totals, compute windowed
        burn rates, publish gauges, fire edge-triggered crossings."""
        now = self._clock() if now is None else now
        crossings: List[Dict[str, Any]] = []
        with self._lock:
            lockcheck.assert_guard("observability.slo")
            self._last_tick = now
            self.ticks += 1
            for objective in self.objectives:
                good, total = self._totals(objective)
                history = self._history[objective.name]
                history.append((now, good, total))
                horizon = now - self.slow_window * 1.5
                while len(history) > 1 and history[0][0] < horizon:
                    history.pop(0)
                attainment = good / total if total > 0 else 1.0
                _M_ATTAINMENT.labels(objective.name).set(attainment)
                for window_name, window, threshold in (
                    ("fast", self.fast_window, self.fast_burn),
                    ("slow", self.slow_window, self.slow_burn),
                ):
                    burn = self._burn_locked(objective, window, now)
                    _M_BURN_RATE.labels(
                        objective.name, window_name
                    ).set(burn)
                    key = (objective.name, window_name)
                    above = burn >= threshold
                    if above and not self._breached.get(key, False):
                        self._breach_counts[key] = (
                            self._breach_counts.get(key, 0) + 1
                        )
                        _M_BREACHES.labels(*key).inc()
                        crossings.append({
                            "objective": objective.name,
                            "window": window_name,
                            "burn_rate": round(burn, 3),
                            "threshold": threshold,
                        })
                    self._breached[key] = above
        for crossing in crossings:
            self._record_crossing(crossing)
            # §28: the breach edge itself is a control event (outside
            # the SLO lock — the ledger fsyncs), then the incident
            # correlator snapshots its report
            control_ledger.emit(
                actor="slo", action="breach",
                target=crossing["objective"],
                after={"burn_rate": crossing["burn_rate"],
                       "window": crossing["window"]},
                reason="burn {} >= {} ({} window)".format(
                    crossing["burn_rate"], crossing["threshold"],
                    crossing["window"],
                ),
            )
            if self.breach_hook is not None:
                try:
                    self.breach_hook(crossing)
                except Exception:
                    logger.exception(
                        "slo: breach hook failed for %s", crossing
                    )
        return {"ticks": self.ticks, "crossings": crossings}

    def _burn_locked(
        self, objective: Objective, window: float, now: float
    ) -> float:
        """Burn rate = bad-ratio over the window ÷ error budget. The
        window's baseline is the OLDEST sample still inside it (short
        uptimes measure what they have, like Prometheus's increase())."""
        history = self._history[objective.name]
        if not history:
            return 0.0
        start = now - window
        # baseline = the newest sample at-or-before the window start
        # (Prometheus increase() semantics); all-inside-window uptimes
        # fall back to the oldest sample — measure what exists
        baseline = history[0]
        for sample in history:
            if sample[0] <= start + 1e-9:
                baseline = sample
            else:
                break
        good_now, total_now = history[-1][1], history[-1][2]
        delta_total = total_now - baseline[2]
        if delta_total <= 0:
            return 0.0
        delta_good = good_now - baseline[1]
        bad_ratio = min(1.0, max(0.0, 1.0 - delta_good / delta_total))
        budget = 1.0 - objective.target
        if budget <= 0:
            return math.inf if bad_ratio > 0 else 0.0
        return bad_ratio / budget

    def _record_crossing(self, crossing: Dict[str, Any]) -> None:
        recorder = (
            self._recorder
            if self._recorder is not None
            else flightrec.RECORDER
        )
        logger.warning(
            "SLO burn-rate crossing: objective %(objective)s %(window)s "
            "window at %(burn_rate).1fx (threshold %(threshold).1fx)",
            crossing,
        )
        # synthetic errored timeline: the crossing shows up in
        # /debug/requests' error ring next to the requests that burned
        # the budget, and survives fast healthy traffic (error ring)
        timeline = Timeline(
            f"slo-{crossing['objective']}-{crossing['window']}"
            f"-{int(time.time() * 1000)}",
            endpoint="slo",
        )
        timeline.add_event("slo_burn_crossing", **crossing)
        timeline.finish(
            status="slo_breach",
            error=(
                f"SLO {crossing['objective']}: {crossing['window']}-window "
                f"burn {crossing['burn_rate']}x >= "
                f"{crossing['threshold']}x"
            ),
        )
        recorder.record(timeline)

    # -- views ---------------------------------------------------------------
    def burn_snapshot(
        self, now: Optional[float] = None
    ) -> Dict[str, Dict[str, Any]]:
        """Lightweight per-objective burn view for programmatic consumers
        (the autopilot's signals layer): fast/slow window burn and
        since-boot attainment, no recorder scan, no attribution."""
        now = self._clock() if now is None else now
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for objective in self.objectives:
                history = self._history[objective.name]
                good, total = (
                    (history[-1][1], history[-1][2])
                    if history else (0.0, 0.0)
                )
                out[objective.name] = {
                    "kind": objective.kind,
                    "fast": self._burn_locked(
                        objective, self.fast_window, now
                    ),
                    "slow": self._burn_locked(
                        objective, self.slow_window, now
                    ),
                    "attainment": good / total if total > 0 else None,
                }
        return out

    def snapshot(
        self, recorder: Optional[flightrec.FlightRecorder] = None
    ) -> Dict[str, Any]:
        """The ``/slo`` body: per-objective attainment, windowed burn
        rates, breach counts — plus per-stage budget attribution when a
        recorder is available."""
        now = self._clock()
        out: Dict[str, Any] = {
            "enabled": True,
            "ticks": self.ticks,
            "windows": {
                "fast": {
                    "seconds": self.fast_window,
                    "burn_threshold": self.fast_burn,
                },
                "slow": {
                    "seconds": self.slow_window,
                    "burn_threshold": self.slow_burn,
                },
            },
            "objectives": [],
        }
        with self._lock:
            for objective in self.objectives:
                history = self._history[objective.name]
                good, total = (
                    (history[-1][1], history[-1][2])
                    if history else (0.0, 0.0)
                )
                windows = {}
                for window_name, window, threshold in (
                    ("fast", self.fast_window, self.fast_burn),
                    ("slow", self.slow_window, self.slow_burn),
                ):
                    burn = self._burn_locked(objective, window, now)
                    key = (objective.name, window_name)
                    windows[window_name] = {
                        "burn_rate": round(burn, 4),
                        "breached": self._breached.get(key, False),
                        "breaches": self._breach_counts.get(key, 0),
                    }
                entry = {
                    "name": objective.name,
                    "kind": objective.kind,
                    "metric": objective.metric,
                    "target": objective.target,
                    "attainment": (
                        round(good / total, 6) if total > 0 else None
                    ),
                    "good": good,
                    "total": total,
                    "windows": windows,
                    "description": objective.description,
                }
                if objective.kind == "latency":
                    entry["threshold_s"] = objective.threshold_s
                    entry["effective_threshold_s"] = (
                        self.effective_threshold(objective)
                    )
                out["objectives"].append(entry)
        recorder = (
            recorder if recorder is not None else self._recorder
        ) or flightrec.RECORDER
        out["attribution"] = {
            objective.name: attribute_stages(recorder, objective)
            for objective in self.objectives
            if objective.kind == "latency"
        }
        return out


# parent stages contain their children's time — attributing to them
# would always blame the wrapper (same rule as Timeline.dominant_stage,
# route included once stitching makes it a parent)
_PARENT_STAGES = ("score", "route")


def _row_in_objective(row: Dict[str, Any], objective: Objective) -> bool:
    """Whether a recorded-request summary row is the kind of traffic the
    objective declares over — without this, a deliberately-slow /reload
    sitting in the slow reservoir would count as a latency violation
    forever. ``endpoint`` filters match the row's endpoint meta; a
    ``stage`` filter requires the named stage in the row's timeline
    (the router's route objective)."""
    for key, want in (objective.label_filter or {}).items():
        if key == "stage":
            stages = row.get("stages_ms") or {}
            if not any(_value_matches(name, want) for name in stages):
                return False
            continue
        if not _value_matches(str(row.get(key, "")), want):
            return False
    return True


def attribute_stages(
    recorder: flightrec.FlightRecorder, objective: Objective
) -> Dict[str, Any]:
    """Which span stage ate the SLO: over the recorder's requests that
    VIOLATED the latency objective, each leaf stage's share of total
    stage time. The flight recorder's slow reservoir makes this robust
    to ring churn — the pathological traces are exactly the kept ones."""
    if objective.threshold_s is None:
        return {"violations": 0, "stages": {}}
    threshold_ms = objective.threshold_s * 1000.0
    rows = recorder.summaries(limit=100)
    seen = set()
    totals: Dict[str, float] = {}
    violations = 0
    for row in rows.get("requests", []) + rows.get("slow", []):
        trace_id = row.get("trace_id")
        if trace_id in seen:
            continue
        seen.add(trace_id)
        if row.get("duration_ms", 0.0) <= threshold_ms:
            continue
        if not _row_in_objective(row, objective):
            continue
        violations += 1
        for stage_name, ms in (row.get("stages_ms") or {}).items():
            if stage_name in _PARENT_STAGES:
                continue
            totals[stage_name] = totals.get(stage_name, 0.0) + ms
    grand = sum(totals.values())
    stages = {
        name: {
            "ms": round(ms, 3),
            "share": round(ms / grand, 4) if grand > 0 else 0.0,
        }
        for name, ms in sorted(
            totals.items(), key=lambda kv: -kv[1]
        )
    }
    dominant = next(iter(stages), None)
    return {
        "violations": violations,
        "dominant_stage": dominant,
        "stages": stages,
    }


# -- default objective sets ---------------------------------------------------


def latency_knobs() -> Tuple[float, float]:
    """``(threshold_seconds, target_fraction)`` as the knobs resolve —
    THE one place the latency-objective defaults live (bench history
    rows and custom objective builders read these instead of
    re-hardcoding the literals)."""
    threshold_s = _env_float("GORDO_SLO_LATENCY_MS", 250.0) / 1000.0
    target = _env_float("GORDO_SLO_LATENCY_TARGET", 0.99)
    return threshold_s, target


def availability_target() -> float:
    return _env_float("GORDO_SLO_AVAILABILITY_TARGET", 0.999)


def knob_summary() -> Dict[str, Any]:
    """The resolved GORDO_SLO_* knob values, for effective-env blocks."""
    threshold_s, target = latency_knobs()
    return {
        "enabled": enabled(),
        "latency_ms": threshold_s * 1000.0,
        "latency_target": target,
        "availability_target": availability_target(),
        "fast_window": _env_float("GORDO_SLO_FAST_WINDOW", 300.0),
        "slow_window": _env_float("GORDO_SLO_SLOW_WINDOW", 3600.0),
    }


def server_objectives() -> List[Objective]:
    """The worker defaults: scoring latency + scoring availability over
    the histograms/counters the server already records (§7)."""
    threshold_s, target = latency_knobs()
    availability = availability_target()
    scoring = ("anomaly", "prediction")
    return [
        Objective(
            name="scoring-latency",
            kind="latency",
            metric="gordo_server_request_duration_seconds",
            target=target,
            threshold_s=threshold_s,
            label_filter={"endpoint": scoring},
            description=(
                f"{target:.0%} of scoring requests under "
                f"{threshold_s * 1000:.0f} ms"
            ),
        ),
        Objective(
            name="scoring-availability",
            kind="availability",
            metric="gordo_server_requests_total",
            target=availability,
            label_filter={"endpoint": scoring},
            bad_filter={
                "endpoint": scoring,
                "status": lambda status: status.startswith("5"),
            },
            description=(
                f"error ratio under {1 - availability:.2%} on scoring "
                "endpoints"
            ),
        ),
    ]


def tenant_objectives(tenants: Any = ()) -> List[Objective]:
    """Per-class and per-declared-tenant availability objectives over
    the bounded ``gordo_tenant_requests_total`` family (§25): bad events
    are overload sheds and server errors at the admission seam — quota
    rejections are deliberately NOT bad (a tenant spending its own
    declared budget is the system working). Cardinality is bounded by
    construction: three classes plus the closed declared table.

    ``tenants`` duck-types ``qos.TenantSpec`` (``.name``/``.klass``) so
    this module never imports the resilience layer."""
    # class targets step down the ladder: bulk is the class the shed
    # actuator squeezes on purpose, so holding it to the interactive
    # availability target would page on intended behavior
    class_targets = {
        "interactive": availability_target(),
        "standard": 0.99,
        "bulk": 0.95,
    }
    bad_outcomes = ("shed", "error")
    out = [
        Objective(
            name=f"class-{klass}-availability",
            kind="availability",
            metric="gordo_tenant_requests_total",
            target=target,
            label_filter={"class": klass},
            bad_filter={"class": klass, "outcome": bad_outcomes},
            description=(
                f"shed+error ratio under {1 - target:.2%} for the "
                f"{klass} class at the admission seam"
            ),
        )
        for klass, target in class_targets.items()
    ]
    for spec in tenants:
        name = getattr(spec, "name", None)
        if not name or name == "default":
            continue
        target = class_targets.get(
            getattr(spec, "klass", "standard"), 0.99
        )
        out.append(
            Objective(
                name=f"tenant-{name}-availability",
                kind="availability",
                metric="gordo_tenant_requests_total",
                target=target,
                label_filter={"tenant": name},
                bad_filter={"tenant": name, "outcome": bad_outcomes},
                description=(
                    f"shed+error ratio under {1 - target:.2%} for "
                    f"tenant {name}"
                ),
            )
        )
    return out


def router_objectives() -> List[Objective]:
    """The router defaults: end-to-end route latency (the ``route``
    stage wraps placement + forward + re-route walks) and fleet
    routability (forwarded vs candidate-exhausted)."""
    threshold_s, target = latency_knobs()
    availability = availability_target()
    return [
        Objective(
            name="route-latency",
            kind="latency",
            metric="gordo_stage_seconds",
            target=target,
            threshold_s=threshold_s,
            label_filter={"stage": "route"},
            description=(
                f"{target:.0%} of routed requests under "
                f"{threshold_s * 1000:.0f} ms end to end"
            ),
        ),
        Objective(
            name="route-availability",
            kind="availability",
            metric="gordo_router_requests_total",
            target=availability,
            label_filter={"outcome": "ok"},
            bad_metric="gordo_router_unroutable_total",
            description=(
                f"unroutable ratio under {1 - availability:.2%}"
            ),
        ),
    ]
