"""Request tracing: contextvar trace/span IDs propagated over HTTP.

The reference correlates nothing across its client → per-model Flask pod
hop; debugging a slow prediction means grepping two pods' logs by
timestamp. Here one header — ``X-Gordo-Trace-Id`` — rides every client
request, the server adopts (or mints) it per request and echoes it in the
response, and a ``logging`` record factory stamps the current trace id
onto EVERY log record emitted on that request's thread: client retry
warnings, server access lines, and engine dispatch logs all carry the
same id without any call site threading it by hand.

``contextvars`` (not thread-locals) so the ids flow correctly through
both the threaded WSGI server and the client's asyncio task fan-out —
each in-flight chunk request holds its own trace id.
"""

from __future__ import annotations

import contextlib
import logging
import time
import uuid
from contextvars import ContextVar
from typing import Iterator, Optional

TRACE_HEADER = "X-Gordo-Trace-Id"

_trace_id: ContextVar[str] = ContextVar("gordo_trace_id", default="")
_span_id: ContextVar[str] = ContextVar("gordo_span_id", default="")

logger = logging.getLogger(__name__)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def get_trace_id() -> str:
    """The current context's trace id ('' when none is active)."""
    return _trace_id.get()


def set_trace_id(trace_id: str):
    """Bind ``trace_id`` to the current context; returns the reset token."""
    return _trace_id.set(trace_id)


def reset_trace_id(token) -> None:
    _trace_id.reset(token)


def current_or_new() -> str:
    """The active trace id, or a fresh one (NOT bound — callers starting a
    new trace should bind via :func:`trace` / :func:`set_trace_id`)."""
    return _trace_id.get() or new_trace_id()


@contextlib.contextmanager
def trace(trace_id: Optional[str] = None) -> Iterator[str]:
    """Bind a trace id (given or fresh) for the duration of the block."""
    tid = trace_id or new_trace_id()
    token = _trace_id.set(tid)
    try:
        yield tid
    finally:
        _trace_id.reset(token)


@contextlib.contextmanager
def span(name: str) -> Iterator[str]:
    """A named timed unit inside the current trace: binds a fresh span id,
    logs the duration at DEBUG, and observes it into the registry
    (``gordo_span_seconds{name}``). Cheap enough for request paths — one
    contextvar set/reset, one histogram observe, one lazy DEBUG line."""
    from .registry import REGISTRY

    sid = uuid.uuid4().hex[:8]
    token = _span_id.set(sid)
    started = time.perf_counter()
    try:
        yield sid
    finally:
        elapsed = time.perf_counter() - started
        _span_id.reset(token)
        REGISTRY.histogram(
            "gordo_span_seconds",
            "Duration of named trace spans",
            labels=("name",),
        ).labels(name).observe(elapsed)
        logger.debug("span %s (%s): %.3f ms", name, sid, elapsed * 1000)


def get_span_id() -> str:
    return _span_id.get()


_factory_installed = False


def install_log_record_factory() -> None:
    """Stamp ``record.trace_id`` / ``record.span_id`` onto every log record
    from the active context. Idempotent; wraps (never replaces) whatever
    factory is already installed, so it composes with other libraries'
    factories and with repeated ``configure_logging`` calls."""
    global _factory_installed
    if _factory_installed:
        return
    _factory_installed = True
    previous = logging.getLogRecordFactory()

    def factory(*args, **kwargs):
        record = previous(*args, **kwargs)
        record.trace_id = _trace_id.get()
        record.span_id = _span_id.get()
        return record

    logging.setLogRecordFactory(factory)
