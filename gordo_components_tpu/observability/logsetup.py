"""Process logging setup: text or JSON lines, trace-id-stamped.

The CLI's former ``logging.basicConfig`` call, grown into the one place
log shape is decided. ``--log-format json`` emits one JSON object per
record (machine-parseable by the log pipeline the reference delegated to
Kubernetes), with the active trace/span ids as first-class fields; the
text format keeps the exact pre-existing line shape so operator muscle
memory and log scrapers survive.
"""

from __future__ import annotations

import json
import logging

from .tracing import install_log_record_factory

TEXT_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


class JsonFormatter(logging.Formatter):
    """One JSON object per record; trace/span ids included when active."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", "")
        if trace_id:
            payload["trace_id"] = trace_id
        span_id = getattr(record, "span_id", "")
        if span_id:
            payload["span_id"] = span_id
        if record.exc_info:
            payload["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


def configure_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Install root logging at ``level`` in ``fmt`` ('text' | 'json') and
    the trace-id record factory (every record carries ``trace_id`` /
    ``span_id`` attributes from then on, whatever the handler).

    ``basicConfig`` WITHOUT ``force``, exactly like the CLI call this
    grew from: a no-op when the root logger already has handlers (a test
    runner's capture, an embedding app's own setup) — clobbering those
    would reroute their records into our stream."""
    if fmt not in ("text", "json"):
        raise ValueError(f"log format must be 'text' or 'json', got {fmt!r}")
    install_log_record_factory()
    handler = logging.StreamHandler()
    handler.setFormatter(
        JsonFormatter() if fmt == "json" else logging.Formatter(TEXT_FORMAT)
    )
    logging.basicConfig(level=level.upper(), handlers=[handler])
