"""Fleet telemetry warehouse: durable, bounded, scrape-driven metrics
history plus the measured-cost ledger (docs/ARCHITECTURE.md §24).

Everything the observability plane had before this module is
point-in-time: a scrape sees current counter totals, the SLO evaluator
keeps minutes of burn samples, the flight recorder keeps a ring. Nothing
answers "what was the request rate over the last hour" after a restart,
and nothing records what a machine *costs* to serve. ROADMAP items 3
and 5 both block on exactly that history — the layout compiler needs
machines × observed rate × bytes × latency as its input, and Automap
(PAPERS.md) argues those layout decisions must come from measured cost.

Design, by deliberate precedent:

- **Tick, don't thread** (``slo.py`` / autopilot): ``maybe_tick`` runs on
  the scrape path with an injectable clock pair (``clock`` monotonic for
  intervals, ``wall`` for durable timestamps). An unwatched server does
  no telemetry work.
- **Deltas, not totals**: each tick appends one JSONL record holding
  counter *increments*, gauge values, and per-bucket histogram
  *increments* since the previous tick. Deltas make history
  restart-proof (a counter reset cannot produce a negative window) and
  make the router's fleet merge exact (increments are additive).
- **WAL durability** (``store/journal.py``): every record is flushed
  and fsync'd; reload tolerates a torn final line (crash mid-append)
  silently and skips corrupt mid-file lines loudly. Less history is a
  degraded answer, never an error.
- **Bounded everything**: segments rotate at ``GORDO_TELEMETRY_SEGMENT_KB``
  and the oldest are deleted past the ``GORDO_TELEMETRY_MB`` byte
  budget; machine-labeled series collapse through the registry's §22
  top-K bound before they are written, so warehouse growth tracks the
  budget, never fleet size.

Window queries (rate-over-window, percentile-from-bucket-increments)
are served from an in-memory index rebuilt from the segments on boot —
after a restart, ``/telemetry?window=...`` still sees pre-restart
history. ``build_export`` renders the ledger + traffic view as the
versioned layout-input document (``gordo-layout-input/v1``) that
ROADMAP item 5's layout compiler takes as its input contract.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis import lockcheck
from . import traffic as traffic_mod
from .registry import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    _label_key,
    bound_machine_cardinality,
)

logger = logging.getLogger(__name__)

EXPORT_SCHEMA = "gordo-layout-input/v1"

enabled = traffic_mod.enabled  # one knob (GORDO_TELEMETRY) rules both

_M_TICKS = REGISTRY.counter(
    "gordo_telemetry_ticks_total",
    "Telemetry warehouse snapshot ticks taken",
)
_M_ROTATIONS = REGISTRY.counter(
    "gordo_telemetry_segment_rotations_total",
    "Telemetry warehouse segment files rotated (opened after the "
    "previous segment crossed GORDO_TELEMETRY_SEGMENT_KB)",
)
_M_BYTES = REGISTRY.gauge(
    "gordo_telemetry_warehouse_bytes",
    "Bytes currently held by the telemetry warehouse across all "
    "segments (bounded by GORDO_TELEMETRY_MB)",
)
_M_SEGMENTS = REGISTRY.gauge(
    "gordo_telemetry_segments",
    "Telemetry warehouse segment files currently on disk",
)
_M_APPEND_SECONDS = REGISTRY.histogram(
    "gordo_telemetry_append_seconds",
    "Wall seconds to serialize + fsync one telemetry record",
)


def tick_interval() -> float:
    """``GORDO_TELEMETRY_INTERVAL``: minimum seconds between warehouse
    ticks (scrape-driven; scraping faster than this is free)."""
    try:
        return float(os.environ.get("GORDO_TELEMETRY_INTERVAL", "15"))
    except ValueError:
        return 15.0


def byte_budget() -> int:
    """``GORDO_TELEMETRY_MB``: hard byte budget across all warehouse
    segments; the oldest segments are deleted to stay under it."""
    try:
        mb = float(os.environ.get("GORDO_TELEMETRY_MB", "64"))
    except ValueError:
        mb = 64.0
    return max(1 << 16, int(mb * (1 << 20)))


def segment_bytes() -> int:
    """``GORDO_TELEMETRY_SEGMENT_KB``: rotate the active segment once it
    crosses this many KiB (retention granularity: the budget deletes
    whole segments)."""
    try:
        kb = float(os.environ.get("GORDO_TELEMETRY_SEGMENT_KB", "256"))
    except ValueError:
        kb = 256.0
    return max(1 << 12, int(kb * 1024))


def _le_list(bounds: Sequence[float]) -> List[Optional[float]]:
    """Histogram bucket bounds as strict-JSON values: +Inf becomes None
    (json.dumps would emit the non-standard ``Infinity`` literal)."""
    return [None if b == float("inf") else b for b in bounds]


def _bucket_percentile(
    le: Sequence[Optional[float]], deltas: Sequence[float], q: float
) -> Optional[float]:
    """Linear-interpolated percentile from per-bucket increment counts —
    the standard Prometheus ``histogram_quantile`` estimate. The +Inf
    bucket has no upper bound, so a quantile landing there reports the
    last finite bound (an honest floor, like Prometheus)."""
    total = float(sum(deltas))
    if total <= 0:
        return None
    target = q * total
    acc = 0.0
    lower = 0.0
    for bound, n in zip(le, deltas):
        if acc + n >= target and n > 0:
            if bound is None:
                return lower
            return lower + (bound - lower) * ((target - acc) / n)
        acc += n
        if bound is not None:
            lower = bound
    return lower


class TelemetryWarehouse:
    """Append-only JSONL metric history + cost ledger for one process.

    ``directory=None`` runs memory-only (same queries, no durability) —
    the mode a bare ``ServingEngine`` test gets. All byte accounting,
    rotation, and budget trimming is identical either way; memory-only
    simply never touches disk.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        registry: Registry = REGISTRY,
        accountant: Optional[traffic_mod.TrafficAccountant] = None,
        cost_sampler: Optional[Callable[[], Dict[str, Any]]] = None,
        worker: str = "",
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        min_interval: Optional[float] = None,
        budget: Optional[int] = None,
        segment_limit: Optional[int] = None,
    ):
        self.directory = directory
        self.registry = registry
        self.accountant = (
            accountant if accountant is not None else traffic_mod.ACCOUNTANT
        )
        self.cost_sampler = cost_sampler
        self.worker = worker
        self._clock = clock
        self._wall = wall
        self.min_interval = (
            min_interval if min_interval is not None else tick_interval()
        )
        self.budget = budget if budget is not None else byte_budget()
        self.segment_limit = (
            segment_limit if segment_limit is not None else segment_bytes()
        )
        self._lock = lockcheck.named_lock("observability.telemetry")
        # (segment_seq, record_bytes, record) oldest-first; the query
        # index and the byte ledger share one list so budget trims are
        # exact on both sides
        self._index: List[Tuple[int, int, Dict[str, Any]]] = []
        self._seg_bytes: Dict[int, int] = {}  # on-disk bytes per segment
        self._seg_seq = 0
        self._active_fh = None
        self._active_bytes = 0
        self._last_tick: Optional[float] = None
        self._tick_pending = False
        self._last_wall: Optional[float] = None
        self._prev_counters: Dict[str, Dict[Tuple[str, ...], float]] = {}
        self._prev_hist: Dict[
            str, Dict[Tuple[str, ...], Tuple[Tuple[int, ...], float, int]]
        ] = {}
        self._costs: Dict[str, Any] = {}
        self.ticks = 0
        self.rotations = 0
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            with self._lock:
                self._reload()
        # baseline tick: establishes delta baselines and timestamps so
        # the first real tick reports honest increments (slo.py pattern)
        self.tick()

    # -- durable segments -----------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        assert self.directory is not None
        return os.path.join(self.directory, f"seg-{seq:08d}.jsonl")

    def _reload(self) -> None:
        """Rebuild the in-memory index from on-disk segments, WAL-style:
        a torn FINAL line (crash mid-append) resumes silently one record
        short; corrupt mid-file lines are skipped loudly."""
        assert self.directory is not None
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("seg-") and n.endswith(".jsonl")
        )
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                seq = int(name[len("seg-"):-len(".jsonl")])
            except ValueError:
                logger.warning("telemetry: ignoring alien file %s", path)
                continue
            self._seg_seq = max(self._seg_seq, seq + 1)
            try:
                with open(path, "r") as fh:
                    lines = fh.readlines()
            except OSError as exc:
                logger.warning("telemetry: unreadable segment %s: %s",
                               path, exc)
                continue
            kept = 0
            for i, line in enumerate(lines):
                if not line.strip():
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    final = (name == names[-1] and i == len(lines) - 1)
                    if final:
                        logger.info(
                            "telemetry: ignoring torn final line in %s "
                            "(crash mid-append)", path,
                        )
                    else:
                        logger.warning(
                            "telemetry: skipping corrupt line %d in %s",
                            i + 1, path,
                        )
                    continue
                nbytes = len(line.encode("utf-8"))
                self._index.append((seq, nbytes, record))
                kept += 1
            self._seg_bytes[seq] = os.path.getsize(path)
            logger.info("telemetry: reloaded %d record(s) from %s",
                        kept, path)
        self._trim_locked()

    def _append_locked(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, separators=(",", ":")) + "\n"
        nbytes = len(line.encode("utf-8"))
        if self.directory is not None:
            started = time.perf_counter()
            if self._active_fh is None:
                seq = self._seg_seq
                self._seg_seq += 1
                self._active_fh = open(self._seg_path(seq), "a")
                self._active_seq = seq
                self._active_bytes = 0
                self._seg_bytes[seq] = 0
            self._active_fh.write(line)
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            _M_APPEND_SECONDS.observe(time.perf_counter() - started)
            self._active_bytes += nbytes
            self._seg_bytes[self._active_seq] += nbytes
            self._index.append((self._active_seq, nbytes, record))
            if self._active_bytes >= self.segment_limit:
                self._active_fh.close()
                self._active_fh = None
                self.rotations += 1
                _M_ROTATIONS.inc()
        else:
            # memory-only: same ledger, records ARE the segments
            seq = self._seg_seq
            self._index.append((seq, nbytes, record))
            self._seg_bytes[seq] = self._seg_bytes.get(seq, 0) + nbytes
            if self._seg_bytes[seq] >= self.segment_limit:
                self._seg_seq += 1
        self._trim_locked()

    def _trim_locked(self) -> None:
        """Enforce the byte budget by deleting whole oldest segments
        (never the active one — a budget smaller than one segment still
        keeps the tail of live history)."""
        while len(self._seg_bytes) > 1 and self.total_bytes() > self.budget:
            oldest = min(self._seg_bytes)
            active = getattr(self, "_active_seq", None)
            if self._active_fh is not None and oldest == active:
                break
            del self._seg_bytes[oldest]
            self._index = [
                entry for entry in self._index if entry[0] != oldest
            ]
            if self.directory is not None:
                try:
                    os.unlink(self._seg_path(oldest))
                except OSError as exc:
                    logger.warning(
                        "telemetry: could not delete segment %d: %s",
                        oldest, exc,
                    )

    def total_bytes(self) -> int:
        return sum(self._seg_bytes.values())

    def close(self) -> None:
        with self._lock:
            lockcheck.assert_guard("observability.telemetry")
            if self._active_fh is not None:
                self._active_fh.close()
                self._active_fh = None

    # -- tick: registry deltas + cost sample into one record ------------------
    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Scrape-path entry: tick when ``min_interval`` has elapsed.
        The interval check and the claim happen in ONE critical section
        (``_tick_pending``), so concurrent scrapes (/metrics and
        /telemetry racing) cannot both pass the check and double-tick —
        the loser returns False instead of appending a zero-dt record
        and double-folding the accountant EWMAs."""
        now = self._clock() if now is None else now
        with self._lock:
            last = self._last_tick
            if self._tick_pending or (
                last is not None and now - last < self.min_interval
            ):
                return False
            self._tick_pending = True
        try:
            self.tick(now)
        finally:
            with self._lock:
                self._tick_pending = False
        return True

    def tick(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        wall_now = self._wall()
        # fold traffic EWMAs first: the accountant's lock (rank 95) nests
        # above this warehouse's (67), and the ledger sampled below
        # should see rates from THIS tick's fold
        self.accountant.tick(now)
        costs = {}
        if self.cost_sampler is not None:
            try:
                costs = self.cost_sampler() or {}
            except Exception as exc:  # lint: allow-swallow(a broken ledger sampler must not take down the scrape path; the gap is visible as an empty costs block)
                logger.warning("telemetry: cost sampler failed: %s", exc)
        with self._lock:
            lockcheck.assert_guard("observability.telemetry")
            last = self._last_tick
            self._last_tick = now
            self._last_wall = wall_now
            if costs:
                self._costs = costs
            record = self._snapshot_deltas_locked(
                wall_now, 0.0 if last is None else max(0.0, now - last)
            )
            if costs:
                record["costs"] = costs
            if last is not None:
                # the baseline tick only establishes prev-values; an
                # empty zero-dt record would pollute window coverage
                self._append_locked(record)
                self.ticks += 1
        if last is not None:
            _M_TICKS.inc()
        _M_BYTES.set(self.total_bytes())
        _M_SEGMENTS.set(len(self._seg_bytes))

    def _snapshot_deltas_locked(
        self, wall_now: float, dt: float
    ) -> Dict[str, Any]:
        counters: Dict[str, Dict[str, float]] = {}
        gauges: Dict[str, Dict[str, float]] = {}
        hists: Dict[str, Dict[str, Any]] = {}
        for metric in self.registry.metrics():
            if isinstance(metric, Counter):
                collected = metric.collect()
                prev = self._prev_counters.get(metric.name, {})
                deltas = {}
                for key, value in collected.items():
                    before = prev.get(key, 0.0)
                    # a shrunk counter means the series was reset
                    # (fresh Registry in tests); its full value is the
                    # honest increment
                    d = value - before if value >= before else value
                    if d > 0:
                        deltas[key] = d
                self._prev_counters[metric.name] = collected
                if deltas:
                    counters[metric.name] = {
                        _label_key(metric.labelnames, k): v
                        for k, v in bound_machine_cardinality(
                            metric, deltas
                        ).items()
                    }
            elif isinstance(metric, Gauge):
                collected = bound_machine_cardinality(
                    metric, metric.collect()
                )
                if collected:
                    gauges[metric.name] = {
                        _label_key(metric.labelnames, k): v
                        for k, v in collected.items()
                    }
            elif isinstance(metric, Histogram):
                collected = metric.collect()
                prev = self._prev_hist.get(metric.name, {})
                keep_prev: Dict[
                    str, Tuple[Tuple[int, ...], float, int]
                ] = {}
                series_deltas: Dict[str, Dict[str, Any]] = {}
                for key, data in collected.items():
                    cumulative = tuple(n for _, n in data["buckets"])
                    keep_prev[key] = (
                        cumulative, data["sum"], data["count"]
                    )
                    pcum, psum, pcount = prev.get(
                        key, ((0,) * len(cumulative), 0.0, 0)
                    )
                    if len(pcum) != len(cumulative):
                        pcum, psum, pcount = (0,) * len(cumulative), 0.0, 0
                    if data["count"] < pcount:  # series reset
                        pcum, psum, pcount = (0,) * len(cumulative), 0.0, 0
                    dcount = data["count"] - pcount
                    if dcount <= 0:
                        continue
                    # per-bucket (non-cumulative) increments
                    per_bucket, last_c, last_p = [], 0, 0
                    for c, p in zip(cumulative, pcum):
                        per_bucket.append((c - last_c) - (p - last_p))
                        last_c, last_p = c, p
                    series_deltas[key] = {
                        "d": per_bucket,
                        "sum": data["sum"] - psum,
                        "n": dcount,
                    }
                self._prev_hist[metric.name] = keep_prev
                if series_deltas:
                    bounded = self._bound_hist_deltas(
                        metric, series_deltas
                    )
                    hists[metric.name] = {
                        "le": _le_list(metric.buckets),
                        "s": {
                            _label_key(metric.labelnames, k): v
                            for k, v in bounded.items()
                        },
                    }
        record: Dict[str, Any] = {"v": 1, "t": wall_now, "dt": dt}
        if self.worker:
            record["w"] = self.worker
        if counters:
            record["c"] = counters
        if gauges:
            record["g"] = gauges
        if hists:
            record["h"] = hists
        return record

    def _bound_hist_deltas(
        self, metric: Histogram, series_deltas: Dict[Any, Dict[str, Any]]
    ) -> Dict[Any, Dict[str, Any]]:
        """Apply the §22 machine-cardinality bound to per-tick histogram
        increments by dressing them in ``collect()``'s shape (cumulative
        pairs + empty samples) so ``bound_machine_cardinality`` merges
        them with the exact same top-K + ``other`` semantics, then
        undressing back to per-bucket increments."""
        from .registry import MACHINE_LABEL

        if MACHINE_LABEL not in metric.labelnames:
            return series_deltas
        dressed = {}
        for key, payload in series_deltas.items():
            acc, cumulative = 0.0, []
            for bound, n in zip(metric.buckets, payload["d"]):
                acc += n
                cumulative.append((bound, acc))
            dressed[key] = {
                "buckets": cumulative,
                "sum": payload["sum"],
                "count": payload["n"],
                "samples": [],
                "exemplars": {},
            }
        bounded = bound_machine_cardinality(metric, dressed)
        out = {}
        for key, data in bounded.items():
            per_bucket, last = [], 0.0
            for _, acc in data["buckets"]:
                per_bucket.append(acc - last)
                last = acc
            out[key] = {
                "d": per_bucket, "sum": data["sum"], "n": data["count"],
            }
        return out

    # -- window queries --------------------------------------------------------
    def _window_records(
        self, window: float, now_wall: Optional[float] = None
    ) -> Tuple[List[Dict[str, Any]], float]:
        now_wall = self._wall() if now_wall is None else now_wall
        cutoff = now_wall - window
        records = [r for _, _, r in self._index if r.get("t", 0) > cutoff]
        covered = float(sum(r.get("dt", 0.0) for r in records))
        return records, covered

    def rate(
        self, metric: str, window: float,
        now_wall: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Per-second increase rate of counter family ``metric`` over the
        trailing ``window`` seconds: summed per-tick deltas over covered
        tick time (Prometheus ``rate()`` over an increment store —
        counter resets cannot bite because increments were computed at
        write time)."""
        with self._lock:
            records, covered = self._window_records(window, now_wall)
        series: Dict[str, float] = {}
        for record in records:
            for key, delta in (record.get("c", {}).get(metric) or {}).items():
                series[key] = series.get(key, 0.0) + delta
        if covered <= 0:
            return {"total": 0.0, "series": {}, "coverage_s": 0.0}
        return {
            "total": sum(series.values()) / covered,
            "series": {k: v / covered for k, v in sorted(series.items())},
            "coverage_s": covered,
        }

    def histogram_window(
        self, metric: str, window: float,
        now_wall: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Merged per-bucket increments for histogram family ``metric``
        over the window (all series of the family summed), plus the
        interpolated p50/p90/p99 — the exact merge unit the router
        aggregates across workers."""
        with self._lock:
            records, covered = self._window_records(window, now_wall)
        le: Optional[List[Optional[float]]] = None
        deltas: Optional[List[float]] = None
        total_sum, total_n = 0.0, 0
        for record in records:
            payload = record.get("h", {}).get(metric)
            if not payload:
                continue
            if le is None:
                le = list(payload["le"])
                deltas = [0.0] * len(le)
            if list(payload["le"]) != le:
                continue  # bucket bounds changed across a restart
            for series in payload["s"].values():
                for i, d in enumerate(series["d"]):
                    deltas[i] += d
                total_sum += series["sum"]
                total_n += series["n"]
        if le is None or total_n <= 0:
            return None
        return {
            "le": le,
            "d": deltas,
            "sum": total_sum,
            "count": total_n,
            "coverage_s": covered,
            "p50": _bucket_percentile(le, deltas, 0.50),
            "p90": _bucket_percentile(le, deltas, 0.90),
            "p99": _bucket_percentile(le, deltas, 0.99),
        }

    def window_view(
        self, window: float, now_wall: Optional[float] = None
    ) -> Dict[str, Any]:
        """Every counter family's windowed rate + every histogram
        family's windowed buckets/percentiles, in ONE pass over the
        window's records (the per-request /telemetry path must not walk
        the index once per family)."""
        with self._lock:
            records, covered = self._window_records(window, now_wall)
        rate_series: Dict[str, Dict[str, float]] = {}
        hist_acc: Dict[str, Dict[str, Any]] = {}
        for record in records:
            for name, series in record.get("c", {}).items():
                into = rate_series.setdefault(name, {})
                for key, delta in series.items():
                    into[key] = into.get(key, 0.0) + delta
            for name, payload in record.get("h", {}).items():
                into = hist_acc.get(name)
                if into is None:
                    into = hist_acc[name] = {
                        "le": list(payload["le"]),
                        "d": [0.0] * len(payload["le"]),
                        "sum": 0.0,
                        "count": 0,
                    }
                if list(payload["le"]) != into["le"]:
                    continue  # bucket bounds changed across a restart
                for series in payload["s"].values():
                    for i, d in enumerate(series["d"]):
                        into["d"][i] += d
                    into["sum"] += series["sum"]
                    into["count"] += series["n"]
        view: Dict[str, Any] = {
            "window_s": window,
            "records": len(records),
            "coverage_s": covered,
            "rates": {},
            "histograms": {},
        }
        for name in sorted(rate_series):
            series = rate_series[name]
            if covered <= 0:
                continue
            view["rates"][name] = {
                "total": sum(series.values()) / covered,
                "series": {
                    k: v / covered for k, v in sorted(series.items())
                },
                "coverage_s": covered,
            }
        for name in sorted(hist_acc):
            merged = hist_acc[name]
            if merged["count"] <= 0:
                continue
            merged["coverage_s"] = covered
            for q, key in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
                merged[key] = _bucket_percentile(
                    merged["le"], merged["d"], q
                )
            view["histograms"][name] = merged
        return view

    # -- the /telemetry payload ------------------------------------------------
    def view(
        self, window: float = 300.0, now_wall: Optional[float] = None
    ) -> Dict[str, Any]:
        with self._lock:
            oldest = self._index[0][2]["t"] if self._index else None
            newest = self._index[-1][2]["t"] if self._index else None
            warehouse = {
                "dir": self.directory,
                "segments": len(self._seg_bytes),
                "bytes": self.total_bytes(),
                "budget_bytes": self.budget,
                "segment_limit_bytes": self.segment_limit,
                "records": len(self._index),
                "oldest_t": oldest,
                "newest_t": newest,
                "ticks": self.ticks,
                "rotations": self.rotations,
            }
            costs = dict(self._costs)
        return {
            "v": 1,
            "enabled": True,
            "worker": self.worker,
            "now": self._wall() if now_wall is None else now_wall,
            "interval_s": self.min_interval,
            "warehouse": warehouse,
            "window": self.window_view(window, now_wall),
            "traffic": self.accountant.snapshot(),
            "costs": costs,
        }


# -- router-side aggregation (aggregate.py's scrape-of-scrapes, in JSON) ------

def _merge_costs(costs_list: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Recursively merge per-worker cost ledgers: numeric leaves SUM
    (bytes, counts, seconds totals are additive across workers) except
    latency/percentile fields, which take MAX — summing two workers'
    p99s would fabricate a latency nobody measured; the worst worker is
    the honest fleet scalar (the registry's gauge rule)."""

    def is_latency_key(key: str) -> bool:
        return (
            "latency" in key
            or key.endswith(("_p50", "_p90", "_p99"))
            or key in ("p50", "p90", "p99")
        )

    def merge(into: Dict[str, Any], other: Dict[str, Any]) -> None:
        for key, value in other.items():
            current = into.get(key)
            if isinstance(value, dict):
                if not isinstance(current, dict):
                    current = into[key] = {}
                merge(current, value)
            elif isinstance(value, bool):
                into[key] = bool(current) or value
            elif isinstance(value, (int, float)):
                base = current if isinstance(current, (int, float)) else 0
                into[key] = (
                    max(base, value) if is_latency_key(key)
                    else base + value
                )
            elif current is None:
                into[key] = value

    out: Dict[str, Any] = {}
    for costs in costs_list:
        merge(out, costs or {})
    return out


def merge_views(views: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-worker ``/telemetry`` payloads (keyed by worker name)
    into one fleet view with the same top-level shape, so the CLI and
    export renderer cannot tell a router from a worker. Increments are
    additive: rates and histogram bucket deltas SUM, percentiles are
    recomputed from the merged buckets."""
    ordered = [views[name] for name in sorted(views)]
    warehouse = {
        "segments": 0, "bytes": 0, "records": 0, "ticks": 0,
        "rotations": 0, "oldest_t": None, "newest_t": None,
    }
    window: Dict[str, Any] = {
        "window_s": 0.0, "records": 0, "coverage_s": 0.0,
        "rates": {}, "histograms": {},
    }
    for v in ordered:
        w = v.get("warehouse") or {}
        for key in ("segments", "bytes", "records", "ticks", "rotations"):
            warehouse[key] += int(w.get(key) or 0)
        for key, pick in (("oldest_t", min), ("newest_t", max)):
            if w.get(key) is not None:
                warehouse[key] = (
                    w[key] if warehouse[key] is None
                    else pick(warehouse[key], w[key])
                )
        wv = v.get("window") or {}
        window["window_s"] = max(window["window_s"],
                                 float(wv.get("window_s") or 0.0))
        window["records"] += int(wv.get("records") or 0)
        window["coverage_s"] = max(window["coverage_s"],
                                   float(wv.get("coverage_s") or 0.0))
        for name, rate in (wv.get("rates") or {}).items():
            into = window["rates"].setdefault(
                name, {"total": 0.0, "series": {}, "coverage_s": 0.0}
            )
            into["total"] += float(rate.get("total") or 0.0)
            into["coverage_s"] = max(into["coverage_s"],
                                     float(rate.get("coverage_s") or 0.0))
            for key, r in (rate.get("series") or {}).items():
                into["series"][key] = into["series"].get(key, 0.0) + r
        for name, merged in (wv.get("histograms") or {}).items():
            into = window["histograms"].get(name)
            if into is None:
                window["histograms"][name] = {
                    "le": list(merged["le"]),
                    "d": list(merged["d"]),
                    "sum": float(merged.get("sum") or 0.0),
                    "count": int(merged.get("count") or 0),
                    "coverage_s": float(merged.get("coverage_s") or 0.0),
                }
                continue
            if list(merged["le"]) != into["le"]:
                continue  # mixed bucket bounds across workers: keep first
            into["d"] = [a + b for a, b in zip(into["d"], merged["d"])]
            into["sum"] += float(merged.get("sum") or 0.0)
            into["count"] += int(merged.get("count") or 0)
            into["coverage_s"] = max(into["coverage_s"],
                                     float(merged.get("coverage_s") or 0.0))
    for merged in window["histograms"].values():
        for q, key in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
            merged[key] = _bucket_percentile(merged["le"], merged["d"], q)
    return {
        "v": 1,
        "enabled": True,
        "workers": sorted(views),
        "now": max(
            (float(v.get("now") or 0.0) for v in ordered), default=0.0
        ),
        "interval_s": max(
            (float(v.get("interval_s") or 0.0) for v in ordered),
            default=0.0,
        ),
        "warehouse": warehouse,
        "window": window,
        "traffic": traffic_mod.merge_snapshots(
            [v.get("traffic") or {} for v in ordered]
        ),
        "costs": _merge_costs([v.get("costs") or {} for v in ordered]),
    }


# -- the measured-cost ledger sample ------------------------------------------

def sample_costs(engine: Any, compile_store: Any = None) -> Dict[str, Any]:
    """One ledger sample from a live engine (+ optional compile-cache
    store): what bench_serving only measures offline, read from the
    serving process itself. Duck-typed on purpose — observability must
    not import the server package (the dependency points the other way).
    """
    costs: Dict[str, Any] = {}
    if engine is not None:
        ledger = engine.cost_ledger()
        costs["engine"] = ledger
    if compile_store is not None:
        by_precision: Dict[str, float] = {}
        seconds_total = 0.0
        bytes_total = 0
        keys = 0
        for entry in compile_store.entries():
            keys += 1
            bytes_total += int(entry.get("bytes") or 0)
            seconds = float(entry.get("compile_seconds") or 0.0)
            seconds_total += seconds
            rung = str(entry.get("precision") or "")
            if rung:
                by_precision[rung] = by_precision.get(rung, 0.0) + seconds
        costs["compile"] = {
            "keys": keys,
            "bytes_total": bytes_total,
            "seconds_total": seconds_total,
            "by_precision": dict(sorted(by_precision.items())),
        }
    return costs


# -- the layout-input export (ROADMAP item 5's input contract) ----------------

def parse_window(value: Any) -> Optional[float]:
    """Parse a ``?window=`` / ``--window`` horizon into seconds. Accepts
    bare seconds (``"600"``, ``600``) and the warehouse horizon labels
    (``"1m"``, ``"10m"``, ``"1h"`` — :data:`traffic.HORIZONS`, plus the
    general ``<n>[s|m|h]`` suffix forms). Returns None on junk so
    callers can fall back to their default instead of 500ing."""
    if value is None:
        return None
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value) if value > 0 else None
    text = str(value).strip().lower()
    if not text:
        return None
    scale = 1.0
    if text[-1] in ("s", "m", "h"):
        scale = {"s": 1.0, "m": 60.0, "h": 3600.0}[text[-1]]
        text = text[:-1]
    try:
        seconds = float(text) * scale
    except ValueError:
        return None
    return seconds if seconds > 0 else None


def resolve_horizon(window_s: Optional[float]) -> str:
    """The warehouse EWMA horizon label closest (in log-space) to the
    requested window — the layout compiler plans on this horizon's
    rates. No window requested → the middle horizon (``10m``): long
    enough to smooth burstiness, short enough to track a shifting
    fleet."""
    horizons = traffic_mod.HORIZONS
    if window_s is None or window_s <= 0:
        return horizons[min(1, len(horizons) - 1)][0]
    import math

    return min(
        horizons,
        key=lambda pair: abs(math.log(pair[1]) - math.log(window_s)),
    )[0]


def build_export(
    view: Dict[str, Any], window: Optional[float] = None
) -> Dict[str, Any]:
    """Render a ``/telemetry`` view (single worker or merged fleet) as
    the versioned layout-input document: machines × observed rate ×
    bytes × latency per rung. ``window`` selects the representative
    EWMA horizon (resolved to the nearest warehouse horizon and echoed
    as ``horizon``; each machine additionally carries the resolved
    scalar ``rate``). This is a CONTRACT — bump :data:`EXPORT_SCHEMA`
    on any shape change (the horizon/rate fields were ADDITIVE, so v1
    stands)."""
    traffic_view = view.get("traffic") or {}
    costs = view.get("costs") or {}
    engine_costs = costs.get("engine") or {}
    rung_costs = engine_costs.get("rungs") or {}
    window_view = view.get("window") or {}

    horizon = resolve_horizon(window)
    machines = [
        {
            "machine": m["machine"],
            "count": m["count"],
            "error": m["error"],
            "rates": dict(m.get("rates") or {}),
            "rate": float((m.get("rates") or {}).get(horizon) or 0.0),
        }
        for m in traffic_view.get("machines", ())
    ]
    # per-rung observed rates: traffic groups summed over shape buckets
    rung_rates: Dict[str, Dict[str, float]] = {}
    rung_counts: Dict[str, float] = {}
    for group in traffic_view.get("groups", ()):
        rung = group.get("precision") or ""
        if not rung:
            continue
        rates = rung_rates.setdefault(rung, {})
        for label, r in (group.get("rates") or {}).items():
            rates[label] = rates.get(label, 0.0) + float(r)
        rung_counts[rung] = (
            rung_counts.get(rung, 0.0) + float(group.get("count") or 0.0)
        )
    compile_by_rung = (costs.get("compile") or {}).get("by_precision") or {}
    rungs: Dict[str, Any] = {}
    for rung in sorted(set(rung_costs) | set(rung_rates)):
        entry = dict(rung_costs.get(rung) or {})
        requests = float(entry.get("requests") or 0.0)
        seconds = float(entry.get("dispatch_seconds_total") or 0.0)
        rungs[rung] = {
            "machines": int(entry.get("machines") or 0),
            "buckets": int(entry.get("buckets") or 0),
            "device_bytes": int(entry.get("device_bytes") or 0),
            "requests": requests,
            "count": rung_counts.get(rung, 0.0),
            "rates": rung_rates.get(rung, {}),
            "dispatch_seconds_total": seconds,
            "latency_s": seconds / requests if requests > 0 else None,
            "compile_seconds": float(compile_by_rung.get(rung) or 0.0),
        }
    total = traffic_view.get("total") or {}
    workers = view.get("workers")
    if workers is None:
        workers = [view.get("worker") or ""]
    return {
        "schema": EXPORT_SCHEMA,
        "generated_t": float(view.get("now") or 0.0),
        "window_s": float(
            window if window is not None
            else (window_view.get("window_s") or 0.0)
        ),
        "horizon": horizon,
        "source": {
            "workers": list(workers),
            "interval_s": float(view.get("interval_s") or 0.0),
            "coverage_s": float(window_view.get("coverage_s") or 0.0),
            "sketch_capacity": int(traffic_view.get("capacity") or 0),
        },
        "machines": machines,
        "rungs": rungs,
        "tiers": {
            "host_cache": dict(
                (engine_costs.get("host_cache") or {})
            ),
            "spill": dict((engine_costs.get("spill") or {})),
        },
        "totals": {
            "count": float(total.get("count") or 0.0),
            "rates": dict(total.get("rates") or {}),
            "machines_tracked": len(machines),
        },
    }


def validate_layout_input(doc: Any) -> List[str]:
    """Schema check for the layout-input document, dependency-free (no
    jsonschema in the image). Returns a list of problems — empty means
    the document honours the v1 contract."""
    problems: List[str] = []

    def num(value: Any) -> bool:
        return isinstance(value, (int, float)) and not isinstance(value, bool)

    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("schema") != EXPORT_SCHEMA:
        problems.append(
            f"schema: expected {EXPORT_SCHEMA!r}, got {doc.get('schema')!r}"
        )
    for key in ("generated_t", "window_s"):
        if not num(doc.get(key)):
            problems.append(f"{key}: missing or not a number")
    if doc.get("horizon") is not None and not isinstance(
        doc.get("horizon"), str
    ):
        problems.append("horizon: not a string")
    source = doc.get("source")
    if not isinstance(source, dict) or not isinstance(
        source.get("workers"), list
    ):
        problems.append("source.workers: missing or not a list")
    machines = doc.get("machines")
    if not isinstance(machines, list):
        problems.append("machines: missing or not a list")
    else:
        for i, m in enumerate(machines):
            if not isinstance(m, dict) or not isinstance(
                m.get("machine"), str
            ):
                problems.append(f"machines[{i}].machine: missing or not a "
                                "string")
                continue
            for key in ("count", "error"):
                if not num(m.get(key)) or m[key] < 0:
                    problems.append(
                        f"machines[{i}].{key}: missing or negative"
                    )
            rates = m.get("rates")
            if not isinstance(rates, dict) or not all(
                num(r) for r in rates.values()
            ):
                problems.append(f"machines[{i}].rates: not a map of numbers")
            if m.get("rate") is not None and not num(m.get("rate")):
                problems.append(f"machines[{i}].rate: not a number")
    rungs = doc.get("rungs")
    if not isinstance(rungs, dict):
        problems.append("rungs: missing or not a map")
    else:
        for rung, entry in rungs.items():
            if not isinstance(entry, dict):
                problems.append(f"rungs[{rung}]: not an object")
                continue
            for key in ("machines", "device_bytes", "requests",
                        "compile_seconds"):
                if not num(entry.get(key)):
                    problems.append(
                        f"rungs[{rung}].{key}: missing or not a number"
                    )
            if entry.get("latency_s") is not None and not num(
                entry.get("latency_s")
            ):
                problems.append(f"rungs[{rung}].latency_s: not a number")
    tiers = doc.get("tiers")
    if not isinstance(tiers, dict) or not isinstance(
        tiers.get("host_cache"), dict
    ) or not isinstance(tiers.get("spill"), dict):
        problems.append("tiers: missing host_cache/spill objects")
    totals = doc.get("totals")
    if not isinstance(totals, dict) or not num(totals.get("count")):
        problems.append("totals.count: missing or not a number")
    return problems
