"""Incident correlator: SLO breach edges become durable root-cause
reports (docs/ARCHITECTURE.md §28).

When a burn-rate crossing fires (§18 edge trigger), this module
snapshots everything an operator needs to answer "what changed":

- every control-ledger event in a lookback window (the §28 ledger is
  the shared journal all five control loops emit into),
- metric deltas from the telemetry warehouse's window queries (§24) —
  the recent window vs the lookback baseline, largest movers first,
- the active FleetSpec revision (§26) and layout-plan fingerprint
  (§27) at breach time, and
- a **ranked root-cause candidate list**: each ledger event scored by
  temporal proximity × target overlap × action weight, so a fault plan
  becoming active or a breaker opening outranks an innocent autopilot
  hold that happened to land nearby.

Reports are durable JSON documents (``gordo-incident/v1``, one file per
incident, atomic tmp+rename+fsync) with a bounded keep — the newest
``GORDO_INCIDENT_KEEP`` survive. A per-objective cooldown
(``GORDO_INCIDENT_COOLDOWN``) stops a flapping objective from writing
a report per tick.

Lock discipline (§17): ``on_breach`` gathers ledger events, warehouse
views, and spec/layout revisions WITHOUT holding the incident lock —
those providers take their own locks (ranks 16/67/69). The rank-65
incident lock guards only the in-memory report ring and cooldown map.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis import lockcheck
from . import ledger as ledger_mod
from .registry import REGISTRY

logger = logging.getLogger(__name__)

SCHEMA = "gordo-incident/v1"

_M_REPORTS = REGISTRY.counter(
    "gordo_incident_reports_total",
    "Durable incident reports written on SLO breach edges",
)
_M_SUPPRESSED = REGISTRY.counter(
    "gordo_incident_suppressed_total",
    "Breach edges that did NOT open a report (per-objective cooldown)",
)
_M_OPEN = REGISTRY.gauge(
    "gordo_incident_reports",
    "Incident reports currently retained (bounded by "
    "GORDO_INCIDENT_KEEP)",
)

# relative blame priors per ledger action: how likely this *kind* of
# change is to break an SLO, before proximity/overlap evidence. Fault
# plans and failure-path transitions sit on top; read-mostly or
# self-reporting actions at the bottom. Unknown actions get 1.0.
ACTION_WEIGHTS: Dict[str, float] = {
    "inject-plan": 5.0,    # faults: deliberately breaking the data plane
    "breaker-open": 4.0,
    "quarantine": 4.0,
    "rollback": 3.5,       # something was already bad enough to revert
    "shed-level": 3.0,
    "apply-plan": 2.5,     # layout: residency/pins just moved
    "canary": 2.5,
    "repair": 2.0,
    "sweep": 2.0,
    "commit": 2.0,         # spec revision edge
    "clear-plan": 2.0,
    "recover": 1.5,
    "breaker-close": 1.0,
    "decision": 1.0,       # autopilot up/down/hold inside bounds
    "enable": 0.8,
    "disable": 0.8,
    "breach": 0.0,         # SLO events describe the symptom, not a cause
}

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def lookback_seconds() -> float:
    """``GORDO_INCIDENT_LOOKBACK``: seconds of ledger history and
    warehouse baseline captured in each incident report."""
    try:
        return float(os.environ.get("GORDO_INCIDENT_LOOKBACK", "600"))
    except ValueError:
        return 600.0


def cooldown_seconds() -> float:
    """``GORDO_INCIDENT_COOLDOWN``: minimum seconds between reports for
    the SAME objective (a flapping burn rate writes one report, not one
    per tick)."""
    try:
        return float(os.environ.get("GORDO_INCIDENT_COOLDOWN", "120"))
    except ValueError:
        return 120.0


def keep_reports() -> int:
    """``GORDO_INCIDENT_KEEP``: newest reports retained (older report
    files are deleted with their ring entries)."""
    try:
        return max(1, int(os.environ.get("GORDO_INCIDENT_KEEP", "32")))
    except ValueError:
        return 32


def _tokens(text: str) -> set:
    return set(_TOKEN_RE.findall(str(text).lower()))


def rank_candidates(
    events: List[Dict[str, Any]],
    crossing: Dict[str, Any],
    breach_ts: float,
) -> List[Dict[str, Any]]:
    """Score every ledger event as a root-cause candidate.

    score = action_weight × temporal proximity × target overlap.
    Temporal proximity decays hyperbolically with age (an event 1 min
    old scores ~3× one 5 min old); overlap multiplies 1.5 when the
    event's target/reason shares a token with the breached objective.
    SLO breach events themselves (weight 0) never make the list.
    """
    objective_tokens = _tokens(crossing.get("objective", ""))
    candidates: List[Dict[str, Any]] = []
    for event in events:
        weight = ACTION_WEIGHTS.get(str(event.get("action")), 1.0)
        if weight <= 0.0:
            continue
        ts = event.get("ts")
        if not isinstance(ts, (int, float)) or ts > breach_ts + 1.0:
            continue
        age = max(0.0, breach_ts - ts)
        temporal = 1.0 / (1.0 + age / 60.0)
        event_tokens = (
            _tokens(event.get("target", ""))
            | _tokens(event.get("reason", ""))
            | _tokens(event.get("action", ""))
        )
        overlap = 1.5 if objective_tokens & event_tokens else 1.0
        score = weight * temporal * overlap
        candidates.append({
            "score": round(score, 4),
            "seq": event.get("seq"),
            "ts": ts,
            "actor": event.get("actor"),
            "action": event.get("action"),
            "target": event.get("target"),
            "reason": event.get("reason", ""),
            "age_s": round(age, 1),
        })
    candidates.sort(key=lambda c: (-c["score"], -(c["ts"] or 0.0)))
    return candidates


def metric_deltas(
    warehouse: Any,
    lookback: float,
    now: Optional[float] = None,
    top: int = 12,
) -> Dict[str, Any]:
    """Largest counter-rate movers: recent short window vs the full
    lookback baseline, from ONE warehouse each (its own lock, not
    ours). Degrades to an empty dict on any failure — less context is a
    degraded report, never a failed one."""
    if warehouse is None:
        return {}
    try:
        recent_w = max(30.0, lookback / 5.0)
        baseline = warehouse.window_view(lookback, now)
        recent = warehouse.window_view(recent_w, now)
        movers: List[Dict[str, Any]] = []
        base_rates = baseline.get("rates") or {}
        for name, rate in (recent.get("rates") or {}).items():
            recent_total = float(rate.get("total") or 0.0)
            base_total = float(
                (base_rates.get(name) or {}).get("total") or 0.0
            )
            if recent_total == 0.0 and base_total == 0.0:
                continue
            ratio = (
                recent_total / base_total if base_total > 0 else float("inf")
            )
            movers.append({
                "metric": name,
                "recent_rate": round(recent_total, 4),
                "baseline_rate": round(base_total, 4),
                "ratio": (
                    round(ratio, 3) if ratio != float("inf") else None
                ),
            })
        movers.sort(
            key=lambda m: -abs((m["ratio"] or 1e9) - 1.0)
        )
        return {
            "recent_window_s": recent_w,
            "baseline_window_s": lookback,
            "movers": movers[:top],
        }
    except Exception:
        logger.exception("incidents: warehouse delta query failed")
        return {}


class IncidentCorrelator:
    """Breach-edge → durable incident report, for one process.

    ``directory=None`` keeps reports memory-only (tests). Providers are
    injected callables so server and router wire their own: a telemetry
    warehouse (or None), a FleetSpec-revision callable, a layout-
    fingerprint callable.
    """

    def __init__(
        self,
        ledger: Optional[ledger_mod.ControlLedger] = None,
        directory: Optional[str] = None,
        warehouse: Any = None,
        spec_revision: Optional[Callable[[], Any]] = None,
        layout_fingerprint: Optional[Callable[[], Any]] = None,
        role: str = "",
        lookback: Optional[float] = None,
        cooldown: Optional[float] = None,
        keep: Optional[int] = None,
        wall: Callable[[], float] = time.time,
    ):
        self._ledger = ledger
        self.directory = directory
        self.warehouse = warehouse
        self.spec_revision = spec_revision
        self.layout_fingerprint = layout_fingerprint
        self.role = role
        self.lookback = lookback if lookback is not None else lookback_seconds()
        self.cooldown = cooldown if cooldown is not None else cooldown_seconds()
        self.keep = keep if keep is not None else keep_reports()
        self._wall = wall
        self._lock = lockcheck.named_lock("observability.incident")
        self._reports: List[Dict[str, Any]] = []  # oldest-first ring
        self._last_fired: Dict[str, float] = {}
        self._counter = 0
        self.suppressed = 0
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            with self._lock:
                self._reload_locked()

    # -- durability -----------------------------------------------------------
    def _report_path(self, incident_id: str) -> str:
        assert self.directory is not None
        return os.path.join(self.directory, f"incident-{incident_id}.json")

    def _reload_locked(self) -> None:
        """Reload durable reports (newest ``keep``), tolerating corrupt
        files loudly — a half-written report from a crash mid-rename
        cannot exist (atomic rename), but a truncated disk can."""
        assert self.directory is not None
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("incident-") and n.endswith(".json")
        )
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                with open(path, "r") as fh:
                    report = json.load(fh)
            except (OSError, ValueError) as exc:
                logger.warning("incidents: skipping unreadable %s: %s",
                               path, exc)
                continue
            self._reports.append(report)
            self._counter = max(
                self._counter, int(report.get("n", 0)) + 1
            )
        self._reports.sort(key=lambda r: r.get("ts", 0.0))
        self._trim_locked()
        _M_OPEN.set(float(len(self._reports)))

    def _write_report(self, report: Dict[str, Any]) -> None:
        if self.directory is None:
            return
        path = self._report_path(report["id"])
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, separators=(",", ":"))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def _trim_locked(self) -> None:
        while len(self._reports) > self.keep:
            oldest = self._reports.pop(0)
            if self.directory is not None:
                try:
                    os.unlink(self._report_path(oldest["id"]))
                except OSError:
                    pass

    # -- the breach hook ------------------------------------------------------
    def on_breach(
        self, crossing: Dict[str, Any], now: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """SLOEvaluator breach-edge hook. NEVER raises into the SLO
        tick; returns the report (or None when suppressed/failed)."""
        try:
            return self._on_breach(crossing, now)
        except Exception:
            logger.exception("incidents: report for %s failed", crossing)
            return None

    def _on_breach(
        self, crossing: Dict[str, Any], now: Optional[float]
    ) -> Optional[Dict[str, Any]]:
        now = self._wall() if now is None else now
        objective = str(crossing.get("objective", ""))
        with self._lock:
            lockcheck.assert_guard("observability.incident")
            last = self._last_fired.get(objective)
            if last is not None and now - last < self.cooldown:
                self.suppressed += 1
                _M_SUPPRESSED.inc()
                return None
            # claim the slot BEFORE the (slow) gather, so a concurrent
            # breach of the same objective cannot double-report
            self._last_fired[objective] = now
            self._counter += 1
            n = self._counter
        # gather lock-free: each provider takes its own lock
        ledger = self._ledger if self._ledger is not None else ledger_mod.LEDGER
        events = ledger.recent(window=self.lookback, now=now)
        candidates = rank_candidates(events, crossing, now)
        deltas = metric_deltas(self.warehouse, self.lookback, now)
        revision = None
        if self.spec_revision is not None:
            try:
                revision = self.spec_revision()
            except Exception:
                logger.exception("incidents: spec revision probe failed")
        layout = None
        if self.layout_fingerprint is not None:
            try:
                layout = self.layout_fingerprint()
            except Exception:
                logger.exception("incidents: layout probe failed")
        incident_id = "{}-{:04d}".format(int(now), n)
        report = {
            "schema": SCHEMA,
            "id": incident_id,
            "n": n,
            "ts": round(now, 3),
            "role": self.role,
            "trigger": dict(crossing),
            "lookback_s": self.lookback,
            "spec_revision": revision,
            "layout": layout,
            "events": events,
            "candidates": candidates,
            "metric_deltas": deltas,
        }
        self._write_report(report)
        with self._lock:
            self._reports.append(report)
            self._trim_locked()
            retained = len(self._reports)
        _M_REPORTS.inc()
        _M_OPEN.set(float(retained))
        top = candidates[0] if candidates else None
        logger.warning(
            "INCIDENT %s: %s/%s burn breach — top candidate: %s",
            incident_id, objective, crossing.get("window"),
            ("{actor}/{action} {target} (score {score})".format(**top)
             if top else "none"),
        )
        return report

    # -- queries --------------------------------------------------------------
    @staticmethod
    def summarize(report: Dict[str, Any]) -> Dict[str, Any]:
        top = (report.get("candidates") or [None])[0]
        return {
            "id": report.get("id"),
            "ts": report.get("ts"),
            "role": report.get("role", ""),
            "objective": (report.get("trigger") or {}).get("objective"),
            "window": (report.get("trigger") or {}).get("window"),
            "burn_rate": (report.get("trigger") or {}).get("burn_rate"),
            "events": len(report.get("events") or ()),
            "top_candidate": top,
        }

    def list(self) -> List[Dict[str, Any]]:
        """Newest-first summaries."""
        with self._lock:
            reports = list(self._reports)
        return [self.summarize(r) for r in reversed(reports)]

    def get(self, incident_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            for report in self._reports:
                if report.get("id") == incident_id:
                    return report
        return None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": True,
                "durable": self.directory is not None,
                "reports": len(self._reports),
                "suppressed": self.suppressed,
                "lookback_s": self.lookback,
                "cooldown_s": self.cooldown,
                "keep": self.keep,
            }
