"""Unified control ledger: one durable, causally-ordered journal of
every control-plane decision (docs/ARCHITECTURE.md §28).

Five autonomous loops now mutate the serving fleet — autopilot (§20),
fleet reconciler (§26), layout compiler (§27), QoS shedder (§25), and
the canary→sweep rollout (§16) — plus quarantine/breaker transitions
and operator spec commits. Each journals privately (decision ring,
repair ring, spec journal, rollout history), so answering "what changed
before this SLO burned" means hand-correlating five formats. This
module is the single shared journal they all emit into, with one event
schema (``gordo-control-event/v1``) and the same durability contract as
the telemetry warehouse (§24): fsync'd JSONL segments, whole-segment
deletion under a byte budget, torn-FINAL-line tolerance on reload.

Rules of the road:

- **Emit never raises and never blocks the data plane.** ``emit`` is
  called from inside control loops (some under their own locks); any
  failure increments a drop counter and returns ``None``. Writers
  holding HOT locks (admission gate, breaker) must NOT emit inline —
  they stash the transition and emit after release (an fsync under a
  hot lock is a traffic stall).
- **The ledger lock is a leaf** (rank 69 in §17's hierarchy): ``emit``
  acquires nothing else inside it, so every control-plane writer can
  call it while holding its own lock without ordering hazards.
- **Bounded** by ``GORDO_LEDGER_MB`` / ``GORDO_LEDGER_SEGMENT_KB``;
  ``directory=None`` runs memory-only (tests, bare engines) with
  identical accounting.

``seq`` is a per-process monotonic sequence number restored across
restarts from the reloaded tail — readers can detect loss (a gap) and
order events causally even when wall clocks step.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis import lockcheck
from .registry import REGISTRY

logger = logging.getLogger(__name__)

SCHEMA = "gordo-control-event/v1"

# the closed actor vocabulary (also the metric label domain — bounded
# by construction). Every control-plane writer appears exactly once.
ACTORS = (
    "autopilot",    # §20 decision journal (scale up/down/hold, enable/disable)
    "reconciler",   # §26 repair attempts (respawn, pin, rebuild, adopt…)
    "fleet-spec",   # §26 spec commits + rollbacks (revision edges)
    "rollout",      # §16 canary / sweep / rollback steps
    "layout",       # §27 plan applies / reverts on a worker
    "qos",          # §25 shed-level movements
    "quarantine",   # §10 machine quarantine / recovery
    "breaker",      # §9 circuit state transitions
    "slo",          # §18 burn-rate breach edges
    "faults",       # §10 GORDO_FAULTS plans becoming active (the smoke's seam)
    "operator",     # direct CLI / curl actions that bypass a loop
)

# every event carries exactly these keys (validate_event enforces it)
_REQUIRED = ("schema", "seq", "ts", "actor", "action", "target")
_OPTIONAL = ("before", "after", "reason", "trace_id", "revision")

_M_EVENTS = REGISTRY.counter(
    "gordo_incident_ledger_events_total",
    "Control-ledger events appended, by emitting control-plane actor",
    labels=("actor",),
)
_M_DROPS = REGISTRY.counter(
    "gordo_incident_ledger_drops_total",
    "Control-ledger events dropped (emit failed; the ledger never "
    "raises into a control loop)",
)
_M_BYTES = REGISTRY.gauge(
    "gordo_incident_ledger_bytes",
    "Bytes currently held by the control ledger across all segments "
    "(bounded by GORDO_LEDGER_MB)",
)


def enabled() -> bool:
    """``GORDO_LEDGER``: set to ``0`` to disable all ledger writes
    (events are counted as drops so the silence is visible)."""
    return os.environ.get("GORDO_LEDGER", "1") not in ("0", "false", "no")


def byte_budget() -> int:
    """``GORDO_LEDGER_MB``: hard byte budget across all ledger
    segments; the oldest segments are deleted to stay under it."""
    try:
        mb = float(os.environ.get("GORDO_LEDGER_MB", "16"))
    except ValueError:
        mb = 16.0
    return max(1 << 16, int(mb * (1 << 20)))


def segment_bytes() -> int:
    """``GORDO_LEDGER_SEGMENT_KB``: rotate the active ledger segment
    once it crosses this many KiB (retention granularity: the budget
    deletes whole segments)."""
    try:
        kb = float(os.environ.get("GORDO_LEDGER_SEGMENT_KB", "128"))
    except ValueError:
        kb = 128.0
    return max(1 << 12, int(kb * 1024))


def validate_event(event: Any) -> List[str]:
    """Schema check for one ``gordo-control-event/v1`` document.
    Returns a list of human-readable problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    if event.get("schema") != SCHEMA:
        problems.append(f"schema is {event.get('schema')!r}, want {SCHEMA!r}")
    for key in _REQUIRED:
        if key not in event:
            problems.append(f"missing required key {key!r}")
    if not isinstance(event.get("seq"), int):
        problems.append("seq must be an integer")
    if not isinstance(event.get("ts"), (int, float)):
        problems.append("ts must be a number (unix seconds)")
    actor = event.get("actor")
    if actor not in ACTORS:
        problems.append(f"actor {actor!r} not in the declared vocabulary")
    if not isinstance(event.get("action"), str) or not event.get("action"):
        problems.append("action must be a non-empty string")
    if not isinstance(event.get("target"), str):
        problems.append("target must be a string (may be empty)")
    for key in set(event) - set(_REQUIRED) - set(_OPTIONAL):
        problems.append(f"unknown key {key!r}")
    return problems


class ControlLedger:
    """Append-only JSONL event journal for one process.

    Same durable-segment mechanics as the telemetry warehouse (§24):
    ``directory=None`` runs memory-only; otherwise every event is
    flushed + fsync'd before ``emit`` returns, segments rotate at
    ``segment_limit`` and whole oldest segments are deleted past
    ``budget`` (never the active one).
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        wall: Callable[[], float] = time.time,
        budget: Optional[int] = None,
        segment_limit: Optional[int] = None,
    ):
        self.directory = directory
        self._wall = wall
        self.budget = budget if budget is not None else byte_budget()
        self.segment_limit = (
            segment_limit if segment_limit is not None else segment_bytes()
        )
        self._lock = lockcheck.named_lock("observability.ledger")
        # (segment_seq, record_bytes, event) oldest-first — query index
        # and byte ledger share one list so budget trims are exact
        self._index: List[Tuple[int, int, Dict[str, Any]]] = []
        self._seg_bytes: Dict[int, int] = {}
        self._seg_seq = 0
        self._active_fh = None
        self._active_bytes = 0
        self._seq = 0  # next event sequence number (monotonic, durable)
        self.events = 0
        self.drops = 0
        self.rotations = 0
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            with self._lock:
                self._reload()

    # -- durable segments -----------------------------------------------------
    def _seg_path(self, seq: int) -> str:
        assert self.directory is not None
        return os.path.join(self.directory, f"seg-{seq:08d}.jsonl")

    def _reload(self) -> None:
        """Rebuild the in-memory index from on-disk segments, WAL-style:
        a torn FINAL line (crash mid-append) resumes silently one event
        short; corrupt mid-file lines are skipped loudly. ``_seq``
        resumes past the highest durable sequence number."""
        assert self.directory is not None
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("seg-") and n.endswith(".jsonl")
        )
        for name in names:
            path = os.path.join(self.directory, name)
            try:
                seq = int(name[len("seg-"):-len(".jsonl")])
            except ValueError:
                logger.warning("ledger: ignoring alien file %s", path)
                continue
            self._seg_seq = max(self._seg_seq, seq + 1)
            try:
                with open(path, "r") as fh:
                    lines = fh.readlines()
            except OSError as exc:
                logger.warning("ledger: unreadable segment %s: %s",
                               path, exc)
                continue
            kept = 0
            for i, line in enumerate(lines):
                if not line.strip():
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    final = (name == names[-1] and i == len(lines) - 1)
                    if final:
                        logger.info(
                            "ledger: ignoring torn final line in %s "
                            "(crash mid-append)", path,
                        )
                    else:
                        logger.warning(
                            "ledger: skipping corrupt line %d in %s",
                            i + 1, path,
                        )
                    continue
                nbytes = len(line.encode("utf-8"))
                self._index.append((seq, nbytes, event))
                if isinstance(event.get("seq"), int):
                    self._seq = max(self._seq, event["seq"] + 1)
                kept += 1
            self._seg_bytes[seq] = os.path.getsize(path)
            logger.info("ledger: reloaded %d event(s) from %s", kept, path)
        self._trim_locked()

    def _append_locked(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, separators=(",", ":")) + "\n"
        nbytes = len(line.encode("utf-8"))
        if self.directory is not None:
            if self._active_fh is None:
                seq = self._seg_seq
                self._seg_seq += 1
                self._active_fh = open(self._seg_path(seq), "a")
                self._active_seq = seq
                self._active_bytes = 0
                self._seg_bytes[seq] = 0
            self._active_fh.write(line)
            self._active_fh.flush()
            os.fsync(self._active_fh.fileno())
            self._active_bytes += nbytes
            self._seg_bytes[self._active_seq] += nbytes
            self._index.append((self._active_seq, nbytes, event))
            if self._active_bytes >= self.segment_limit:
                self._active_fh.close()
                self._active_fh = None
                self.rotations += 1
        else:
            # memory-only: same ledger, records ARE the segments
            seq = self._seg_seq
            self._index.append((seq, nbytes, event))
            self._seg_bytes[seq] = self._seg_bytes.get(seq, 0) + nbytes
            if self._seg_bytes[seq] >= self.segment_limit:
                self._seg_seq += 1
        self._trim_locked()
        _M_BYTES.set(float(self.total_bytes()))

    def _trim_locked(self) -> None:
        """Enforce the byte budget by deleting whole oldest segments
        (never the active one)."""
        while len(self._seg_bytes) > 1 and self.total_bytes() > self.budget:
            oldest = min(self._seg_bytes)
            active = getattr(self, "_active_seq", None)
            if self._active_fh is not None and oldest == active:
                break
            del self._seg_bytes[oldest]
            self._index = [
                entry for entry in self._index if entry[0] != oldest
            ]
            if self.directory is not None:
                try:
                    os.unlink(self._seg_path(oldest))
                except OSError as exc:
                    logger.warning(
                        "ledger: could not delete segment %d: %s",
                        oldest, exc,
                    )

    def total_bytes(self) -> int:
        return sum(self._seg_bytes.values())

    # -- the one write path ---------------------------------------------------
    def emit(
        self,
        actor: str,
        action: str,
        target: str = "",
        before: Any = None,
        after: Any = None,
        reason: str = "",
        trace_id: str = "",
        revision: Any = None,
    ) -> Optional[Dict[str, Any]]:
        """Append one control event. NEVER raises — a failed append is
        counted as a drop and returns ``None`` (journaling must never
        break actuation, the §20 rule, fleet-wide now)."""
        if not enabled():
            self.drops += 1
            _M_DROPS.inc()
            return None
        try:
            with self._lock:
                lockcheck.assert_guard("observability.ledger")
                event: Dict[str, Any] = {
                    "schema": SCHEMA,
                    "seq": self._seq,
                    "ts": round(self._wall(), 3),
                    "actor": actor,
                    "action": action,
                    "target": str(target),
                }
                if before is not None:
                    event["before"] = before
                if after is not None:
                    event["after"] = after
                if reason:
                    event["reason"] = str(reason)
                if trace_id:
                    event["trace_id"] = str(trace_id)
                if revision is not None:
                    event["revision"] = revision
                self._seq += 1
                self._append_locked(event)
                self.events += 1
            _M_EVENTS.labels(actor if actor in ACTORS else "operator").inc()
            return event
        except Exception:
            self.drops += 1
            _M_DROPS.inc()
            logger.exception("ledger: dropped %s/%s event", actor, action)
            return None

    def _adopt(self, event: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Carry one pre-configure event into this ledger's sequence
        space (boot-buffer replay): payload and original ``ts`` kept,
        ``seq`` re-stamped past any durable history. Metric-silent —
        the event was already counted when first emitted."""
        try:
            with self._lock:
                lockcheck.assert_guard("observability.ledger")
                carried = dict(event)
                carried["seq"] = self._seq
                self._seq += 1
                self._append_locked(carried)
                self.events += 1
            return carried
        except Exception:
            self.drops += 1
            _M_DROPS.inc()
            return None

    # -- queries --------------------------------------------------------------
    def recent(
        self,
        window: Optional[float] = None,
        limit: Optional[int] = None,
        now: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Events inside the trailing ``window`` seconds (all retained
        history when ``None``), oldest-first, newest ``limit`` kept."""
        now = self._wall() if now is None else now
        with self._lock:
            events = [entry[2] for entry in self._index]
        if window is not None:
            horizon = now - window
            events = [
                e for e in events
                if isinstance(e.get("ts"), (int, float)) and e["ts"] >= horizon
            ]
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return events

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": enabled(),
                "durable": self.directory is not None,
                "events": self.events,
                "drops": self.drops,
                "rotations": self.rotations,
                "segments": len(self._seg_bytes),
                "bytes": self.total_bytes(),
                "next_seq": self._seq,
                "retained": len(self._index),
            }

    def close(self) -> None:
        with self._lock:
            if self._active_fh is not None:
                self._active_fh.close()
                self._active_fh = None


# process-global ledger: memory-only until a serving role calls
# configure() with its durable directory. Writers go through emit()
# below so reconfiguration swaps the sink under everyone at once.
LEDGER = ControlLedger()
_configure_lock = threading.Lock()


def configure(
    directory: Optional[str],
    wall: Callable[[], float] = time.time,
    budget: Optional[int] = None,
    segment_limit: Optional[int] = None,
) -> ControlLedger:
    """Point the process-global ledger at a durable directory (server /
    router boot). Idempotent for the same directory."""
    global LEDGER
    with _configure_lock:
        if LEDGER.directory == directory and directory is not None:
            return LEDGER
        old = LEDGER
        fresh = ControlLedger(
            directory=directory, wall=wall,
            budget=budget, segment_limit=segment_limit,
        )
        if old.directory is None:
            # events emitted before the serving role attached its durable
            # directory (e.g. a --faults plan activated at CLI-parse time)
            # must not vanish — the chaos drill that burns the SLO is the
            # correlator's strongest candidate. Durable→durable switches
            # do NOT replay: that history already lives in the old dir.
            for event in old.recent():
                fresh._adopt(event)
        LEDGER = fresh
        old.close()
        return LEDGER


def emit(
    actor: str,
    action: str,
    target: str = "",
    before: Any = None,
    after: Any = None,
    reason: str = "",
    trace_id: str = "",
    revision: Any = None,
) -> Optional[Dict[str, Any]]:
    """Module-level emit: every control-plane writer calls this; it
    forwards to whatever ledger configure() last installed."""
    return LEDGER.emit(
        actor, action, target=target, before=before, after=after,
        reason=reason, trace_id=trace_id, revision=revision,
    )
