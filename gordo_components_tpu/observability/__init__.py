"""Unified observability: metrics registry, Prometheus exposition, and
end-to-end request tracing.

Three small modules every layer shares:

- :mod:`.registry` — process-wide labeled Counter/Gauge/Histogram
  primitives (``REGISTRY`` is the one instance telemetry records to).
- :mod:`.exposition` — Prometheus text-format v0.0.4 rendering +
  validation (``GET /metrics?format=prometheus``).
- :mod:`.tracing` — contextvar trace/span ids propagated via the
  ``X-Gordo-Trace-Id`` header and stamped onto every log record.
- :mod:`.spans` — per-request stage timelines (queue_wait / dispatch /
  device_execute / fetch / ...) with explicit span-context capture
  across the engine's collector threads and the client's asyncio
  fan-out; Chrome trace-event (Perfetto) export per trace.
- :mod:`.flightrec` — the always-on bounded flight recorder behind
  ``/debug/requests`` (``RECORDER`` is the process instance).
- :mod:`.stitch` — cross-process trace stitching: the worker stamps its
  timeline onto the response (negotiated, size-capped), the router
  merges it under its ``route`` span with clock alignment.
- :mod:`.aggregate` — scrape-of-scrapes: merge N worker expositions
  into one fleet exposition (counters summed, histogram buckets
  merged, gauges per-worker-labeled, exemplars preserved).
- :mod:`.slo` — declared latency/availability objectives evaluated by
  multi-window burn rate over the collected histograms
  (``gordo_slo_*`` series, ``/slo``).
- :mod:`.logsetup` — text/JSON logging configuration for the CLI.
"""

from .exposition import CONTENT_TYPE, parse_prometheus_text, render_prometheus
from .flightrec import RECORDER, FlightRecorder
from .logsetup import configure_logging
from .registry import REGISTRY, Counter, Gauge, Histogram, Registry, get_registry
from .spans import SpanContext, Timeline
from .tracing import (
    TRACE_HEADER,
    current_or_new,
    get_trace_id,
    install_log_record_factory,
    new_trace_id,
    span,
    trace,
)

__all__ = [
    "CONTENT_TYPE",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "RECORDER",
    "REGISTRY",
    "Registry",
    "SpanContext",
    "TRACE_HEADER",
    "Timeline",
    "configure_logging",
    "current_or_new",
    "get_registry",
    "get_trace_id",
    "install_log_record_factory",
    "new_trace_id",
    "parse_prometheus_text",
    "render_prometheus",
    "span",
    "trace",
]
