"""Per-request span timelines: stage-level latency attribution.

PR 1's tracing gives every request ONE id; PR 4's pipelined data plane
split serving into overlapping stages (admission gate, bucket queue,
leader dispatch, device execution, collector fetch, wire encode) that run
on THREE different threads — so when a request's p99 moves, the flat
histograms can say *that* it was slow but not *where*. A
:class:`Timeline` is the per-request answer: named stage spans with
start/duration, point events (deadline expiry, breaker rejection, shed),
and a Chrome trace-event export that loads straight into Perfetto.

Context model: the handler thread binds its timeline to a contextvar
(:func:`begin`), so same-thread code records via :func:`stage` without
plumbing. The PR 4 collector threads and the client's asyncio fan-out do
NOT inherit that contextvar — work crossing those seams carries an
explicit :class:`SpanContext` (:func:`capture` at enqueue,
:func:`bind` / :func:`record_into` on the far side), which also restores
the trace id for log records emitted over there (the PR 4 regression:
collector-side log lines carried no ``X-Gordo-Trace-Id``).

Overhead contract: a stage is one ``perf_counter`` pair, one histogram
observe (``gordo_stage_seconds{stage}``), and — when a timeline is bound
— one lock-guarded list append. No timeline bound (recorder disabled,
CLI batch jobs) ⇒ the append vanishes and only the histogram remains.
"""

from __future__ import annotations

import contextlib
import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

from . import tracing
from .registry import REGISTRY

# the canonical stage names (docs/ARCHITECTURE.md §13); stage() accepts
# any name — this tuple is the shared vocabulary, not an enum
STAGES = (
    "route",           # router: placement decision + worker forward
                       # (re-route walks included)
    "admission",       # admission-gate wait (server)
    "queue_wait",      # bucket pending queue until a leader dispatches it
    "megabatch",       # leader's bounded fill window collecting concurrent
                       # submits across machines into one fused dispatch
    "dispatch",        # pre-dispatch seams + async enqueue (leader thread)
    "device_execute",  # enqueue -> fetch-begin (device compute overlap)
    "fetch",           # jax.device_get: remaining compute + D2H copy
    "score",           # whole engine/host scoring call (parent span)
    "encode",          # response wire encoding (npz / fast JSON)
    "chunk_fetch",     # client: one chunk's HTTP round-trip
    "decode",          # client: response body -> arrays
)

_M_STAGE_SECONDS = REGISTRY.histogram(
    "gordo_stage_seconds",
    "Duration of named request stages (the aggregate twin of the "
    "per-request timelines in /debug/requests)",
    labels=("stage",),
)
# bound-series cache: stage() / record_into() run several times per
# request, and labels() re-validates + re-tuples per call otherwise
_BOUND_STAGE: Dict[str, Any] = {}


def _stage_series(name: str):
    bound = _BOUND_STAGE.get(name)
    if bound is None:
        bound = _BOUND_STAGE[name] = _M_STAGE_SECONDS.labels(name)
    return bound

_timeline: ContextVar[Optional["Timeline"]] = ContextVar(
    "gordo_timeline", default=None
)


class Span:
    __slots__ = ("name", "start", "duration", "thread", "process", "attrs")

    def __init__(self, name: str, start: float, duration: float,
                 thread: str, attrs: Dict[str, Any], process: str = ""):
        self.name = name
        self.start = start  # seconds since timeline start
        self.duration = duration
        self.thread = thread
        # "" = this process; anything else is a STITCHED lane — a remote
        # process's span merged in by the router (observability.stitch)
        self.process = process
        self.attrs = attrs


class Timeline:
    """One request's stage spans + point events.

    Thread-safe appends: the handler thread, the bucket leader (which may
    be ANOTHER request's handler draining the queue), and the collector
    thread all record into one request's timeline concurrently.
    """

    __slots__ = ("trace_id", "meta", "started_wall", "started", "finished",
                 "status", "error", "spans", "events", "_lock")

    def __init__(self, trace_id: str, **meta: Any):
        self.trace_id = trace_id
        self.meta = {k: v for k, v in meta.items() if v is not None}
        self.started_wall = time.time()
        self.started = time.perf_counter()
        self.finished: Optional[float] = None  # perf_counter at finish
        self.status = ""   # e.g. HTTP status, "ok", "error"
        self.error = ""
        self.spans: List[Span] = []
        self.events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    # -- recording (any thread) ----------------------------------------------
    def add_span(self, name: str, started: float, duration: float,
                 **attrs: Any) -> None:
        """``started`` is an absolute ``time.perf_counter()`` reading (the
        recorder converts to timeline-relative) so cross-thread recorders
        never need the timeline's epoch."""
        if attrs:
            attrs = {k: v for k, v in attrs.items() if v not in (None, "")}
        span = Span(
            name,
            max(0.0, started - self.started),
            max(0.0, duration),
            threading.current_thread().name,
            attrs,
        )
        with self._lock:
            self.spans.append(span)

    def add_span_at(self, name: str, rel_start: float, duration: float,
                    thread: str = "", process: str = "",
                    **attrs: Any) -> None:
        """Append a span at an already-TIMELINE-RELATIVE start — how a
        stitched remote process's spans (whose perf_counter epoch means
        nothing here) land in this timeline after clock alignment."""
        if attrs:
            attrs = {k: v for k, v in attrs.items() if v not in (None, "")}
        span = Span(
            name, max(0.0, rel_start), max(0.0, duration),
            thread or threading.current_thread().name, attrs,
            process=process,
        )
        with self._lock:
            self.spans.append(span)

    def add_event(self, name: str, **attrs: Any) -> None:
        event = {
            "t": max(0.0, time.perf_counter() - self.started),
            "name": name,
            **{k: v for k, v in attrs.items() if v not in (None, "")},
        }
        with self._lock:
            self.events.append(event)

    def add_event_at(self, name: str, rel_t: float, process: str = "",
                     **attrs: Any) -> None:
        """Timeline-relative point event (the stitching twin of
        :meth:`add_span_at`)."""
        event = {
            "t": max(0.0, rel_t),
            "name": name,
            **({"process": process} if process else {}),
            **{k: v for k, v in attrs.items() if v not in (None, "")},
        }
        with self._lock:
            self.events.append(event)

    def finish(self, status: str = "", error: str = "") -> None:
        self.finished = time.perf_counter()
        if status:
            self.status = str(status)
        if error:
            self.error = str(error)

    # -- views ---------------------------------------------------------------
    @property
    def duration(self) -> float:
        end = self.finished if self.finished is not None else time.perf_counter()
        return max(0.0, end - self.started)

    def stage_seconds(self) -> Dict[str, float]:
        """Total seconds per stage name (repeated spans — chunked
        backfills, retries — sum)."""
        with self._lock:
            spans = list(self.spans)
        out: Dict[str, float] = {}
        for span in spans:
            out[span.name] = out.get(span.name, 0.0) + span.duration
        return out

    # parent stages CONTAIN other stages (score wraps the whole engine
    # call; route wraps every stitched worker stage), so counting them in
    # dominance would always blame the parent; they still appear in
    # stage_seconds for the full picture
    _PARENT_STAGES = frozenset({"score", "route"})

    def dominant_stage(self) -> str:
        stages = self.stage_seconds()
        leaves = {
            name: seconds for name, seconds in stages.items()
            if name not in self._PARENT_STAGES
        }
        # host-path machines record only the flat score span — fall back
        # to the parents rather than answering nothing
        stages = leaves or stages
        if not stages:
            return ""
        return max(stages.items(), key=lambda kv: kv[1])[0]

    def summary(self) -> Dict[str, Any]:
        """The /debug/requests listing row: everything an operator needs
        to pick which trace to open."""
        return {
            "trace_id": self.trace_id,
            "started": self.started_wall,
            "duration_ms": round(self.duration * 1000, 3),
            "status": self.status,
            "error": self.error,
            "dominant_stage": self.dominant_stage(),
            "stages_ms": {
                name: round(seconds * 1000, 3)
                for name, seconds in sorted(self.stage_seconds().items())
            },
            **self.meta,
        }

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            spans = list(self.spans)
            events = list(self.events)
        return {
            "trace_id": self.trace_id,
            "meta": dict(self.meta),
            "started": self.started_wall,
            "duration_ms": round(self.duration * 1000, 3),
            "status": self.status,
            "error": self.error,
            "dominant_stage": self.dominant_stage(),
            "stages_ms": {
                name: round(seconds * 1000, 3)
                for name, seconds in sorted(self.stage_seconds().items())
            },
            "spans": [
                {
                    "name": span.name,
                    "start_ms": round(span.start * 1000, 3),
                    "duration_ms": round(span.duration * 1000, 3),
                    "thread": span.thread,
                    **({"process": span.process} if span.process else {}),
                    **span.attrs,
                }
                for span in spans
            ],
            "events": events,
        }

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
        format): complete (``ph: "X"``) events in microseconds, one track
        per recording thread, instant (``ph: "i"``) events for the point
        events. STITCHED spans (``Span.process`` set — another process's
        timeline merged in by the router) render as their own process
        lane (pid 2+), so one export shows router and worker side by
        side. ``json.dumps`` of the result is directly loadable."""
        base_us = self.started_wall * 1e6
        with self._lock:
            spans = list(self.spans)
            events = list(self.events)
        # process lanes: "" (this process) is always pid 1; every
        # distinct stitched process label gets its own pid after it
        remote = sorted(
            {span.process for span in spans if span.process}
            | {e["process"] for e in events if e.get("process")}
        )
        pids = {"": 1, **{name: i + 2 for i, name in enumerate(remote)}}
        local_label = str(
            self.meta.get("service") or f"gordo trace {self.trace_id}"
        )
        trace_events: List[Dict[str, Any]] = []
        for process, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            trace_events.append({
                "ph": "M",
                "pid": pid,
                "name": "process_name",
                "args": {"name": process or local_label},
            })
        threads = sorted({(span.process, span.thread) for span in spans})
        tids = {key: i + 1 for i, key in enumerate(threads)}
        for (process, thread), tid in sorted(
            tids.items(), key=lambda kv: kv[1]
        ):
            trace_events.append({
                "ph": "M", "pid": pids[process], "tid": tid,
                "name": "thread_name", "args": {"name": thread},
            })
        for span in spans:
            trace_events.append({
                "ph": "X",
                "pid": pids[span.process],
                "tid": tids.get((span.process, span.thread), 0),
                "name": span.name,
                "cat": "stage",
                "ts": base_us + span.start * 1e6,
                "dur": span.duration * 1e6,
                "args": dict(span.attrs),
            })
        for event in events:
            args = {
                k: v for k, v in event.items()
                if k not in ("t", "name", "process")
            }
            trace_events.append({
                "ph": "i",
                "pid": pids.get(event.get("process", ""), 1),
                "tid": 0,
                "name": event["name"],
                "cat": "event",
                "ts": base_us + event["t"] * 1e6,
                "s": "p",  # process-scoped instant
                "args": args,
            })
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "trace_id": self.trace_id,
                "status": self.status,
                **{str(k): str(v) for k, v in self.meta.items()},
            },
        }


# -- context plumbing --------------------------------------------------------


class SpanContext(NamedTuple):
    """Explicit capture of (trace id, timeline) for crossing the seams
    contextvars do not survive: the engine's collector-thread handoff and
    the client's cross-thread asyncio submission."""

    trace_id: str
    timeline: Optional[Timeline]


EMPTY_CONTEXT = SpanContext("", None)


def capture() -> SpanContext:
    return SpanContext(tracing.get_trace_id(), _timeline.get())


@contextlib.contextmanager
def bind(ctx: SpanContext) -> Iterator[None]:
    """Re-bind a captured context on another thread/task: log records get
    the trace id back, and :func:`stage`/:func:`event` land in the right
    timeline. Safe with ``EMPTY_CONTEXT`` (binds nothing extra)."""
    trace_token = tracing.set_trace_id(ctx.trace_id) if ctx.trace_id else None
    timeline_token = _timeline.set(ctx.timeline)
    try:
        yield
    finally:
        _timeline.reset(timeline_token)
        if trace_token is not None:
            tracing.reset_trace_id(trace_token)


def current_timeline() -> Optional[Timeline]:
    return _timeline.get()


def begin(trace_id: str, **meta: Any):
    """Start a timeline and bind it to the current context. Returns
    ``(timeline, token)``; pass the token to :func:`end`."""
    timeline = Timeline(trace_id, **meta)
    return timeline, _timeline.set(timeline)


def end(token) -> None:
    """Unbind (the caller finishes/records the timeline itself — status
    is only known at the HTTP boundary)."""
    _timeline.reset(token)


@contextlib.contextmanager
def stage(name: str, **attrs: Any) -> Iterator[None]:
    """Record a named stage: always observes ``gordo_stage_seconds``,
    and appends a span when a timeline is bound."""
    timeline = _timeline.get()
    started = time.perf_counter()
    try:
        yield
    finally:
        duration = time.perf_counter() - started
        _stage_series(name).observe(duration)
        if timeline is not None:
            timeline.add_span(name, started, duration, **attrs)


def event(name: str, **attrs: Any) -> None:
    """Point event on the bound timeline (no-op without one)."""
    timeline = _timeline.get()
    if timeline is not None:
        timeline.add_event(name, **attrs)


def record_into(ctx: SpanContext, name: str, started: float,
                duration: float, **attrs: Any) -> None:
    """Record a span into a CAPTURED context's timeline from any thread —
    how the bucket leader and collector attribute dispatch/device/fetch
    time to each batched item's own request. Observes the aggregate
    histogram exactly once per call, like :func:`stage`."""
    _stage_series(name).observe(max(0.0, duration))
    if ctx.timeline is not None:
        ctx.timeline.add_span(name, started, duration, **attrs)


def event_into(ctx: SpanContext, name: str, **attrs: Any) -> None:
    if ctx.timeline is not None:
        ctx.timeline.add_event(name, **attrs)
