"""Scrape-of-scrapes: N per-process expositions merged into one.

The horizontal tier (ARCHITECTURE §16) put a registry in every worker
process — an operator (or the old watchman view) had to scrape N ports
and eyeball-sum them. ``merge_expositions`` folds the fleet into ONE
exposition the router serves at ``/metrics?format=prometheus&aggregate=1``:

- **counters** sum across sources per identical label set — the fleet
  total a recording rule would have computed anyway;
- **histograms** bucket-merge: per label set, each ``le`` bucket (and
  ``_sum`` / ``_count``) sums across sources, so fleet percentiles come
  from real merged buckets, not averaged averages. The ``+Inf == count``
  invariant holds by construction because every source satisfied it;
- **gauges** (and untyped) are NOT summable (a worker's queue depth
  summed across workers is a lie about every one of them): each source's
  series keeps its value and gains a ``worker=<source>`` label — §7's
  documented bounded-cardinality exception;
- **exemplars** survive: per merged bucket/counter the newest-timestamped
  exemplar among the sources wins, so the aggregate still links to a
  concrete trace in SOME worker's flight recorder.

Every input is parsed by the validating parser (a worker emitting a
malformed exposition fails ITS scrape loudly instead of corrupting the
fleet view), and the merged output re-parses under the same validator
before it is returned — the aggregator can never emit what it would
itself reject.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from .exposition import parse_prometheus_text
from .exposition import _fmt_value as _fmt_finite

WORKER_LABEL = "worker"


def _fmt_value(value: float) -> str:
    # the registry renderer never emits NaN, but a merged-in source may
    # (it is legal exposition) — and repr(nan) is not
    if isinstance(value, float) and math.isnan(value):
        return "NaN"
    return _fmt_finite(value)

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(name: str, types: Dict[str, str]) -> Tuple[str, str]:
    """``(family, suffix)`` — maps ``x_bucket``/``x_sum``/``x_count``
    back onto histogram family ``x`` when ``x`` is a declared histogram."""
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base, suffix
    return name, ""


def _labels_text(labels: Dict[str, str]) -> str:
    from .exposition import _escape_label

    if not labels:
        return ""
    pairs = [
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    ]
    return "{" + ",".join(pairs) + "}"


def _exemplar_text(exemplar: Dict[str, Any]) -> str:
    from .exposition import _escape_label

    pairs = ",".join(
        f'{k}="{_escape_label(v)}"'
        for k, v in sorted(exemplar["labels"].items())
    )
    out = f" # {{{pairs}}} {_fmt_value(exemplar['value'])}"
    if exemplar.get("timestamp") is not None:
        out += f" {exemplar['timestamp']:.3f}"
    return out


def _key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


class _Parsed:
    __slots__ = ("samples", "exemplars", "types", "helps")

    def __init__(self, text: str):
        self.samples, self.exemplars, self.types, self.helps = (
            parse_prometheus_text(text, return_meta=True)
        )


def merge_expositions(
    sources: Dict[str, str], exemplars: bool = False
) -> str:
    """Merge ``{source_label: exposition_text}`` into one exposition.

    ``source_label`` becomes the ``worker`` label value on gauge series
    (the router passes worker names plus ``"router"`` for its own
    registry). ``exemplars=False`` strips exemplar suffixes from the
    output (strict v0.0.4 for classic Prometheus parsers — mirrors the
    per-server ``&exemplars=1`` opt-in).

    Raises ``ValueError`` when any INPUT fails validation; families
    whose TYPE — or histogram bucket layout — disagrees across sources
    are skipped with a comment (one mid-upgrade worker must not take
    down the fleet scrape, and mismatched ``le`` sets cannot be summed
    per-bucket without producing non-monotone histograms). Families
    with no declared TYPE (legal v0.0.4) pass through worker-labeled.
    """
    parsed: Dict[str, _Parsed] = {
        label: _Parsed(text) for label, text in sources.items()
    }

    # family -> kind, with conflicts noted and skipped
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    conflicted: List[str] = []
    for label in sorted(parsed):
        for family, kind in parsed[label].types.items():
            if family in kinds and kinds[family] != kind:
                if family not in conflicted:
                    conflicted.append(family)
                continue
            kinds.setdefault(family, kind)
            if family not in helps and family in parsed[label].helps:
                helps[family] = parsed[label].helps[family]

    # collect every sample under its FAMILY (histogram suffixes folded)
    # family -> suffix -> series key -> merged value / per-source values
    summed: Dict[Tuple[str, str], Dict[Tuple, float]] = {}
    labeled: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    best_exemplars: Dict[Tuple[str, Tuple], Dict[str, Any]] = {}
    families_seen: Dict[str, bool] = {}
    # histogram bucket layouts per (family, series key) per source: two
    # sources exposing DIFFERENT le sets for one series (mid-rollout
    # version/knob skew) cannot be summed per-le without producing
    # non-monotone buckets — detect and skip the family loudly instead
    layouts: Dict[Tuple[str, Tuple], Dict[str, frozenset]] = {}

    for label in sorted(parsed):
        source = parsed[label]
        for name, rows in source.samples.items():
            family, suffix = _family_of(name, source.types)
            kind = kinds.get(family)
            if family in conflicted:
                continue
            families_seen[family] = True
            additive = kind in ("counter", "histogram")
            if suffix == "_bucket":
                per_series: Dict[Tuple, set] = {}
                for series_labels, _ in rows:
                    rest = {
                        k: v for k, v in series_labels.items() if k != "le"
                    }
                    per_series.setdefault(_key(rest), set()).add(
                        series_labels.get("le", "+Inf")
                    )
                for series_key, les in per_series.items():
                    layouts.setdefault((family, series_key), {})[label] = (
                        frozenset(les)
                    )
            for series_labels, value in rows:
                if additive:
                    bucket = summed.setdefault((family, suffix), {})
                    key = _key(series_labels)
                    if math.isnan(value):
                        continue  # NaN is not summable; drop the sample
                    bucket[key] = bucket.get(key, 0.0) + value
                else:
                    # gauge / untyped / summary: not summable — each
                    # source's series keeps its value via the worker
                    # label (existing worker labels win — the router's
                    # own per-worker series stay as recorded)
                    stamped = dict(series_labels)
                    stamped.setdefault(WORKER_LABEL, label)
                    labeled.setdefault(name, []).append(
                        (stamped, value)
                    )
        for name, rows in source.exemplars.items():
            family, suffix = _family_of(name, source.types)
            if family in conflicted:
                continue
            for series_labels, exemplar in rows:
                key = (name, _key(series_labels))
                held = best_exemplars.get(key)
                ts = exemplar.get("timestamp") or 0.0
                if held is None or ts >= (held.get("timestamp") or 0.0):
                    best_exemplars[key] = exemplar

    # bucket-layout disagreement per family (any series whose sources
    # expose different le sets): joins the conflicted list
    layout_conflicts = sorted({
        family
        for (family, _), per_source in layouts.items()
        if len(set(per_source.values())) > 1
    })
    for family in layout_conflicts:
        if family not in conflicted:
            conflicted.append(family)

    lines: List[str] = []
    for family in conflicted:
        reason = (
            "histogram bucket layouts disagree across sources"
            if family in layout_conflicts
            else "TYPE disagrees across sources"
        )
        lines.append(f"# aggregate: family {family} skipped — {reason}")
    for family in sorted(families_seen):
        if family in conflicted:
            continue
        kind = kinds.get(family)
        if family in helps and helps[family]:
            lines.append(f"# HELP {family} {helps[family]}")
        if kind is None:
            # untyped family (no # TYPE line — legal v0.0.4, includes a
            # summary's bare _sum/_count): worker-labeled passthrough
            for series_labels, value in sorted(
                labeled.get(family, []), key=lambda row: _key(row[0])
            ):
                lines.append(
                    f"{family}{_labels_text(series_labels)} "
                    f"{_fmt_value(value)}"
                )
            continue
        lines.append(f"# TYPE {family} {kind}")
        if kind == "histogram":
            _render_histogram(
                lines, family, summed, best_exemplars, exemplars
            )
        elif kind == "counter":
            rows = summed.get((family, ""), {})
            for key in sorted(rows):
                suffix_txt = ""
                if exemplars and (family, key) in best_exemplars:
                    suffix_txt = _exemplar_text(
                        best_exemplars[(family, key)]
                    )
                lines.append(
                    f"{family}{_labels_text(dict(key))} "
                    f"{_fmt_value(rows[key])}{suffix_txt}"
                )
        else:
            for series_labels, value in sorted(
                labeled.get(family, []),
                key=lambda row: _key(row[0]),
            ):
                lines.append(
                    f"{family}{_labels_text(series_labels)} "
                    f"{_fmt_value(value)}"
                )
    merged = "\n".join(lines) + "\n"
    # the aggregator must never emit what it would reject: re-validate
    parse_prometheus_text(merged, return_exemplars=True)
    return merged


def _render_histogram(
    lines: List[str],
    family: str,
    summed: Dict[Tuple[str, str], Dict[Tuple, float]],
    best_exemplars: Dict[Tuple[str, Tuple], Dict[str, Any]],
    exemplars: bool,
) -> None:
    buckets = summed.get((family, "_bucket"), {})
    sums = summed.get((family, "_sum"), {})
    counts = summed.get((family, "_count"), {})
    # group bucket series by their label set minus le, keep le order
    grouped: Dict[Tuple, List[Tuple[float, Tuple, str]]] = {}
    for key in buckets:
        labels = dict(key)
        le_text = labels.pop("le", "+Inf")
        le = (
            math.inf if le_text == "+Inf"
            else (-math.inf if le_text == "-Inf" else float(le_text))
        )
        grouped.setdefault(_key(labels), []).append((le, key, le_text))
    for series_key in sorted(grouped):
        for le, bucket_key, le_text in sorted(
            grouped[series_key], key=lambda row: row[0]
        ):
            labels = dict(series_key)
            labels["le"] = le_text
            suffix_txt = ""
            exemplar_key = (f"{family}_bucket", _key(labels))
            if exemplars and exemplar_key in best_exemplars:
                suffix_txt = _exemplar_text(best_exemplars[exemplar_key])
            lines.append(
                f"{family}_bucket{_labels_text(labels)} "
                f"{_fmt_value(buckets[bucket_key])}{suffix_txt}"
            )
        lines.append(
            f"{family}_sum{_labels_text(dict(series_key))} "
            f"{_fmt_value(sums.get(series_key, 0.0))}"
        )
        lines.append(
            f"{family}_count{_labels_text(dict(series_key))} "
            f"{_fmt_value(counts.get(series_key, 0.0))}"
        )


def scrape_sources(
    session: Any,
    targets: Dict[str, str],
    timeout: float = 10.0,
    exemplars: bool = True,
) -> Tuple[Dict[str, str], Dict[str, str]]:
    """Fetch each target's exposition; ``(texts, errors)`` keyed by
    source label. A worker that is down or answers garbage lands in
    ``errors`` and is excluded — the fleet view degrades, not dies."""
    texts: Dict[str, str] = {}
    errors: Dict[str, str] = {}
    suffix = "format=prometheus" + ("&exemplars=1" if exemplars else "")
    for label, base_url in targets.items():
        try:
            response = session.get(
                f"{base_url}/metrics?{suffix}", timeout=timeout
            )
            if response.status_code != 200:
                errors[label] = f"HTTP {response.status_code}"
                continue
            # validate NOW so a malformed worker is named, not merged
            parse_prometheus_text(response.text, return_exemplars=True)
            texts[label] = response.text
        except Exception as exc:  # transport or validation
            errors[label] = f"{type(exc).__name__}: {exc}"
    return texts, errors
