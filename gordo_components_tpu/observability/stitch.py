"""Cross-process trace stitching: one request, one merged timeline.

Since the horizontal tier (ARCHITECTURE §16) a scoring request crosses
two processes — the router's ``route`` span and the worker's
admission→…→encode stages used to live in two DISCONNECTED flight
recorders, findable only by grepping two ``/debug/requests`` views for
the same trace id. This module closes the seam:

- the WORKER, when (and only when) the request carries the negotiated
  ``X-Gordo-Timeline: 1`` header, stamps its completed span timeline
  into the response as a size-capped base64(JSON) header
  (:func:`encode_timeline`). Plain clients never pay the bytes — the
  router is the only caller that asks.
- the ROUTER decodes the header and merges the worker's spans into its
  own timeline UNDER the ``route`` stage (:func:`merge_remote`), each
  span tagged with the worker's process label so the Chrome/Perfetto
  export renders per-process lanes.
- timelines too big for the cap are announced via
  ``X-Gordo-Timeline-Truncated: <bytes>`` instead; the router records
  which worker holds the full timeline and PULLS it from that worker's
  ``/debug/requests/<trace_id>`` on first read (router.py).

Clock alignment: the two processes share no ``perf_counter`` epoch, so
remote spans are placed by wall-clock offset (``started_wall`` delta) —
and because wall clocks can skew across hosts, the placement is then
CLAMPED into the router's observed forward window (monotonic on the
router), which is the one interval the worker's activity provably
occupied. Same-host placement is exact; cross-host placement degrades
gracefully to "centered inside the forward window" instead of rendering
spans outside their parent.
"""

from __future__ import annotations

import base64
import binascii
import json
import os
from typing import Any, Dict, Optional, Tuple

from .spans import Timeline

# request: "1" asks the server to stamp its timeline on the response.
# response: the base64(compact-JSON) timeline itself.
TIMELINE_HEADER = "X-Gordo-Timeline"
# response: emitted INSTEAD of the timeline when it exceeds the size
# cap; the value is the encoded size, the signal for the pull fallback
TIMELINE_TRUNCATED_HEADER = "X-Gordo-Timeline-Truncated"


def max_bytes() -> int:
    """Size cap for the stitched response header (GORDO_TIMELINE_MAX_BYTES,
    default 8 KiB of base64). Headers ride every routed scoring response,
    so a megabatch-wide 200-span timeline must not bloat the hot path —
    past the cap the router pulls instead."""
    try:
        return max(256, int(os.environ.get("GORDO_TIMELINE_MAX_BYTES", 8192)))
    except (TypeError, ValueError):
        return 8192


def encode_timeline(
    timeline: Timeline, cap: Optional[int] = None
) -> Tuple[Optional[str], Optional[int]]:
    """``(header_value, None)`` within the cap, ``(None, encoded_size)``
    past it. base64 keeps the value a single clean ASCII token whatever
    ends up in span attrs or error strings."""
    payload = json.dumps(
        timeline.to_dict(), separators=(",", ":"), default=str
    )
    encoded = base64.b64encode(payload.encode("utf-8")).decode("ascii")
    limit = cap if cap is not None else max_bytes()
    if len(encoded) > limit:
        return None, len(encoded)
    return encoded, None


def decode_timeline(value: str) -> Dict[str, Any]:
    """Inverse of :func:`encode_timeline`; raises ``ValueError`` on
    anything that is not a base64 JSON timeline dict."""
    try:
        payload = base64.b64decode(value.encode("ascii"), validate=True)
        decoded = json.loads(payload.decode("utf-8"))
    except (binascii.Error, UnicodeDecodeError, ValueError) as exc:
        raise ValueError(f"unparseable stitched timeline: {exc}") from None
    if not isinstance(decoded, dict) or "spans" not in decoded:
        raise ValueError("stitched timeline carries no spans")
    return decoded


def align_offset(
    local_started_wall: float,
    remote: Dict[str, Any],
    window_start: float,
    window_end: float,
) -> float:
    """Local-timeline-relative second at which the remote timeline
    starts. Wall-clock delta when it lands inside the forward window
    (same host, or well-synced clocks); otherwise clamped/centered into
    the window — the monotonic bound the router actually observed."""
    duration = max(0.0, float(remote.get("duration_ms", 0.0)) / 1000.0)
    offset = float(remote.get("started", local_started_wall)) - \
        local_started_wall
    slack = 0.002  # scheduling noise either side
    if (
        offset < window_start - slack
        or offset + duration > window_end + slack
    ):
        # clock skew: fall back to the one provable interval. Center the
        # remote activity in the forward window (transport time splits
        # roughly evenly between the two directions).
        offset = window_start + max(
            0.0, (window_end - window_start - duration) / 2.0
        )
    return max(window_start, offset)


def merge_remote(
    timeline: Timeline,
    remote: Dict[str, Any],
    window_start: float,
    window_end: float,
    process: str,
) -> int:
    """Merge a decoded remote timeline into ``timeline`` as process-lane
    ``process``, aligned inside the ``[window_start, window_end]``
    forward window (both local-timeline-relative seconds). Returns the
    number of spans merged. Defensive: one malformed remote span never
    loses the rest."""
    offset = align_offset(
        timeline.started_wall, remote, window_start, window_end
    )
    merged = 0
    for span in remote.get("spans", ()):
        try:
            name = str(span["name"])
            start = offset + float(span.get("start_ms", 0.0)) / 1000.0
            duration = float(span.get("duration_ms", 0.0)) / 1000.0
        except (KeyError, TypeError, ValueError):
            continue
        attrs = {
            k: v for k, v in span.items()
            if k not in ("name", "start_ms", "duration_ms", "thread",
                         "process")
        }
        timeline.add_span_at(
            name, start, duration,
            thread=str(span.get("thread", "")) or "remote",
            process=process, **attrs,
        )
        merged += 1
    for event in remote.get("events", ()):
        try:
            name = str(event["name"])
            rel = offset + float(event.get("t", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        attrs = {
            k: v for k, v in event.items()
            if k not in ("name", "t", "process")
        }
        timeline.add_event_at(name, rel, process=process, **attrs)
    if merged:
        timeline.meta.setdefault("stitched", []).append(process)
    return merged
