"""Per-machine traffic accounting: the fleet's one authoritative answer
to "who is actually being served, and how fast" (docs/ARCHITECTURE.md
§24).

Before this module the question was answered twice, both times badly:
``registry.bound_machine_cardinality`` re-derived top-K-by-traffic from
whatever counter family it happened to be collapsing (per scrape, per
family — different families could disagree on who the heavy hitters
are), and nothing recorded request *rates* at all, only lifetime
totals. ROADMAP item 5's layout compiler needs observed per-machine
rates as an input; Automap (PAPERS.md) argues layout should follow
measured cost, and the measurement starts here.

Two bounded structures, one request-hot-path lock:

- :class:`SpaceSaving` — the classic top-K heavy-hitter sketch (Metwally
  et al.): at most ``capacity`` tracked keys whatever the fleet size,
  O(1) for tracked keys (the Zipf head — almost every request), O(log K)
  when an untracked key evicts the current minimum. The guarantees the
  §24 tests gate on: every key with true count > N/capacity is tracked,
  and ``estimate - error <= true_count <= estimate``.
- :class:`TrafficAccountant` — the sketch plus multi-horizon EWMA rates
  (1m/10m/1h) per tracked machine, per engine shape bucket, and per
  precision rung. Rate folding is TICK-driven (the telemetry warehouse's
  scrape-driven ``maybe_tick`` chain — no thread, injectable clock);
  ``note()`` on the scoring path only increments dicts.

The module-level :data:`ACCOUNTANT` is process-wide like ``REGISTRY``:
the engine records into it without plumbing, every server/warehouse in
the process reads the same accounting, and
``registry.bound_machine_cardinality`` takes its top-K set from it when
telemetry is on (render-time recount kept as fallback).
"""

from __future__ import annotations

import heapq
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis import lockcheck
from .registry import REGISTRY, set_traffic_topk_provider

# EWMA horizons: label -> seconds. The 1m rate answers "now", the 1h
# rate is what the layout compiler should plan on.
HORIZONS: Tuple[Tuple[str, float], ...] = (
    ("1m", 60.0), ("10m", 600.0), ("1h", 3600.0),
)

_M_TRACKED = REGISTRY.gauge(
    "gordo_telemetry_tracked_machines",
    "Machines currently tracked by the Space-Saving traffic sketch "
    "(bounded by GORDO_TELEMETRY_TOPK whatever the fleet size)",
)


def enabled() -> bool:
    """GORDO_TELEMETRY=0 disables traffic accounting and the telemetry
    warehouse (requests pay zero accounting cost)."""
    return os.environ.get("GORDO_TELEMETRY", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def sketch_capacity() -> int:
    """``GORDO_TELEMETRY_TOPK``: tracked-machine capacity of the traffic
    sketch (default 512 — comfortably above the default top-64 metric
    cardinality cap it feeds, so the kept set is never error-bound)."""
    try:
        return max(8, int(os.environ.get("GORDO_TELEMETRY_TOPK", "512")))
    except ValueError:
        return 512


class SpaceSaving:
    """Space-Saving top-K sketch: bounded counts with per-key error.

    NOT thread-safe on its own — the owning :class:`TrafficAccountant`
    (or a test) serializes access. ``_counts`` maps key -> [count,
    error]; ``_heap`` is a lazy min-heap of (count, key) used only to
    find the eviction victim (stale entries are skipped on pop, the
    standard lazy-deletion trick — amortized O(log K) per eviction).
    Evictions are the only place stale tuples get popped, so a fleet
    that never fills ``capacity`` would leak one tuple per offer;
    :meth:`_compact_heap` rebuilds the heap from live counts whenever
    it exceeds 4x capacity, keeping it bounded at amortized O(1).
    """

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._counts: Dict[str, List[float]] = {}
        self._heap: List[Tuple[float, str]] = []

    def _compact_heap(self) -> None:
        self._heap = [(v[0], k) for k, v in self._counts.items()]
        heapq.heapify(self._heap)

    def __len__(self) -> int:
        return len(self._counts)

    def __contains__(self, key: str) -> bool:
        return key in self._counts

    def offer(self, key: str, amount: float = 1.0) -> None:
        if len(self._heap) > 4 * self.capacity:
            self._compact_heap()
        entry = self._counts.get(key)
        if entry is not None:
            entry[0] += amount
            heapq.heappush(self._heap, (entry[0], key))
            return
        if len(self._counts) < self.capacity:
            self._counts[key] = [amount, 0.0]
            heapq.heappush(self._heap, (amount, key))
            return
        # evict the true minimum: pop until the heap top reflects a
        # live entry's CURRENT count (stale tuples from earlier
        # increments are skipped)
        while self._heap:
            count, victim = self._heap[0]
            live = self._counts.get(victim)
            if live is not None and live[0] == count:
                break
            heapq.heappop(self._heap)
        count, victim = heapq.heappop(self._heap)
        del self._counts[victim]
        # the newcomer inherits the victim's count as its error bound:
        # true_count <= estimate, estimate - error <= true_count
        self._counts[key] = [count + amount, count]
        heapq.heappush(self._heap, (count + amount, key))

    def estimate(self, key: str) -> Optional[Tuple[float, float]]:
        entry = self._counts.get(key)
        return None if entry is None else (entry[0], entry[1])

    def items(self) -> List[Tuple[str, float, float]]:
        """(key, estimated_count, error), heaviest first (count desc,
        then name — deterministic for tests and operators)."""
        return sorted(
            ((k, v[0], v[1]) for k, v in self._counts.items()),
            key=lambda row: (-row[1], row[0]),
        )

    def top(self, k: int) -> List[Tuple[str, float, float]]:
        return self.items()[: max(0, int(k))]

    def to_list(self) -> List[List[Any]]:
        """JSON-able serialization (the /telemetry aggregation wire
        shape): [[key, count, error], ...] heaviest first."""
        return [[k, c, e] for k, c, e in self.items()]

    @classmethod
    def merged(
        cls,
        lists: Sequence[Sequence[Sequence[Any]]],
        capacity: int,
        source_capacities: Optional[Sequence[Optional[int]]] = None,
    ) -> "SpaceSaving":
        """Merge serialized sketches (router aggregating per-worker
        accountants) with the mergeable-summaries rule: per key, SUM the
        estimates of sketches that track it, and for each sketch that
        does NOT, add that sketch's minimum count to both estimate and
        error — a key a full sketch dropped can have seen at most its
        minimum there. A sketch below its OWN capacity never evicted,
        so its missing-mass bound is exactly zero — "full" is judged
        against ``source_capacities[i]`` (the capacity that sketch
        actually ran with, which under heterogeneous GORDO_TELEMETRY_TOPK
        differs from the merge ``capacity``; unknown defaults to
        ``capacity``). This keeps the §24 contract sound across the
        merge: estimate - error <= true <= estimate."""
        parsed: List[Dict[str, Tuple[float, float]]] = [
            {
                str(row[0]): (float(row[1]), float(row[2]))
                for row in rows
            }
            for rows in lists
        ]
        caps: List[int] = [
            int(cap) if cap else capacity
            for cap in (source_capacities or [None] * len(parsed))
        ]
        caps += [capacity] * (len(parsed) - len(caps))
        missing_mass = [
            (min(c for c, _ in rows.values())
             if rows and len(rows) >= cap else 0.0)
            for rows, cap in zip(parsed, caps)
        ]
        combined: Dict[str, List[float]] = {}
        all_keys = set()
        for rows in parsed:
            all_keys.update(rows)
        for key in all_keys:
            entry = combined.setdefault(key, [0.0, 0.0])
            for rows, bound in zip(parsed, missing_mass):
                count, error = rows.get(key, (bound, bound))
                entry[0] += count
                entry[1] += error
        sketch = cls(capacity)
        kept = sorted(
            combined.items(), key=lambda kv: (-kv[1][0], kv[0])
        )[:capacity]
        # keys trimmed here were below every kept key on every worker;
        # their mass is bounded by the kept minimum by construction
        for key, (count, error) in kept:
            sketch._counts[key] = [count, error]
            heapq.heappush(sketch._heap, (count, key))
        return sketch


def _ewma_fold(
    rates: Dict[str, float], inst: float, alphas: Dict[str, float]
) -> Dict[str, float]:
    out = {}
    for label, alpha in alphas.items():
        prev = rates.get(label)
        out[label] = (
            inst if prev is None else prev + alpha * (inst - prev)
        )
    return out


class TrafficAccountant:
    """Bounded per-machine / per-bucket / per-rung traffic rates.

    ``note()`` is the request-path entry (dict increments under one HOT
    lock); ``tick(now)`` folds accumulated counts into EWMA rates at
    each horizon — driven by the telemetry warehouse's scrape-driven
    tick, so rates cost nothing while nobody scrapes. ``clock`` is
    injectable; tests run hours of horizon arithmetic in microseconds.
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        horizons: Tuple[Tuple[str, float], ...] = HORIZONS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.horizons = tuple(horizons)
        self._clock = clock
        self._lock = lockcheck.named_lock("observability.traffic")
        self._sketch = SpaceSaving(
            capacity if capacity is not None else sketch_capacity()
        )
        # counts since the last tick; _pending is pruned to the sketch's
        # tracked set every tick and hard-capped between ticks so an all-new-
        # machines flood cannot grow it past a few multiples of capacity
        self._pending: Dict[str, float] = {}
        self._group_pending: Dict[Tuple[str, str], float] = {}
        self._total_pending = 0.0
        self._total_count = 0.0
        self._rates: Dict[str, Dict[str, float]] = {}
        self._group_rates: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._group_counts: Dict[Tuple[str, str], float] = {}
        self._total_rates: Dict[str, float] = {}
        self._last_tick: Optional[float] = None
        self.ticks = 0

    # -- request path ---------------------------------------------------------
    def note(
        self, machine: str, bucket: str = "", precision: str = "",
        n: float = 1.0,
    ) -> None:
        """One served request for ``machine`` (scored by ``bucket`` at
        ``precision``). Dict increments only — rate math waits for the
        next tick."""
        group = (bucket, precision)
        with self._lock:
            lockcheck.assert_guard("observability.traffic")
            self._sketch.offer(machine, n)
            if (
                machine in self._pending
                or len(self._pending) < 8 * self._sketch.capacity
            ):
                self._pending[machine] = self._pending.get(machine, 0.0) + n
            self._group_pending[group] = (
                self._group_pending.get(group, 0.0) + n
            )
            self._group_counts[group] = (
                self._group_counts.get(group, 0.0) + n
            )
            self._total_pending += n
            self._total_count += n

    # -- tick-driven rate folding ---------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """Fold counts-since-last-tick into the EWMA rate table. The
        first tick only establishes the baseline timestamp."""
        now = self._clock() if now is None else now
        with self._lock:
            lockcheck.assert_guard("observability.traffic")
            last = self._last_tick
            self._last_tick = now
            if last is None or now <= last:
                self._pending.clear()
                self._group_pending.clear()
                self._total_pending = 0.0
                return
            dt = now - last
            alphas = {
                label: 1.0 - math.exp(-dt / horizon)
                for label, horizon in self.horizons
            }
            tracked = set(self._sketch._counts)
            for machine in tracked:
                inst = self._pending.get(machine, 0.0) / dt
                self._rates[machine] = _ewma_fold(
                    self._rates.get(machine, {}), inst, alphas
                )
            # machines evicted from the sketch drop their rate state —
            # both tables stay bounded by the sketch capacity
            for machine in list(self._rates):
                if machine not in tracked:
                    del self._rates[machine]
            for group in set(self._group_counts):
                inst = self._group_pending.get(group, 0.0) / dt
                self._group_rates[group] = _ewma_fold(
                    self._group_rates.get(group, {}), inst, alphas
                )
            self._total_rates = _ewma_fold(
                self._total_rates, self._total_pending / dt, alphas
            )
            self._pending.clear()
            self._group_pending.clear()
            self._total_pending = 0.0
            self.ticks += 1
            tracked_n = len(tracked)
        _M_TRACKED.set(tracked_n)

    # -- views ----------------------------------------------------------------
    def top(self, k: int) -> List[Tuple[str, float, float]]:
        with self._lock:
            return self._sketch.top(k)

    def topk_names(self, k: int) -> List[str]:
        """The sketch's current heaviest ``k`` machine names — what
        ``registry.bound_machine_cardinality`` keeps when telemetry is
        the authority."""
        return [name for name, _, _ in self.top(k)]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able full view (the worker's /telemetry ``traffic``
        block, and the unit the router merges)."""
        with self._lock:
            machines = [
                {
                    "machine": name,
                    "count": count,
                    "error": error,
                    "rates": dict(self._rates.get(name, {})),
                }
                for name, count, error in self._sketch.items()
            ]
            groups = [
                {
                    "bucket": bucket,
                    "precision": precision,
                    "count": count,
                    "rates": dict(
                        self._group_rates.get((bucket, precision), {})
                    ),
                }
                for (bucket, precision), count in sorted(
                    self._group_counts.items()
                )
            ]
            return {
                "capacity": self._sketch.capacity,
                "ticks": self.ticks,
                "total": {
                    "count": self._total_count,
                    "rates": dict(self._total_rates),
                },
                "machines": machines,
                "groups": groups,
            }

    def reset(self) -> None:
        """Tests only: drop all accounting (the module singleton is
        process-wide, and smoke phases must not see each other)."""
        with self._lock:
            lockcheck.assert_guard("observability.traffic")
            self._sketch = SpaceSaving(self._sketch.capacity)
            self._pending.clear()
            self._group_pending.clear()
            self._total_pending = 0.0
            self._total_count = 0.0
            self._rates.clear()
            self._group_rates.clear()
            self._group_counts.clear()
            self._total_rates = {}
            self._last_tick = None
            self.ticks = 0


def merge_snapshots(
    snapshots: Sequence[Dict[str, Any]], capacity: Optional[int] = None
) -> Dict[str, Any]:
    """Merge per-worker ``TrafficAccountant.snapshot()`` dicts into one
    fleet view (the router's /telemetry aggregation): sketch counts
    merge via :meth:`SpaceSaving.merged`, rates SUM per horizon (each
    worker's rate is its own served share — fleet rate is the sum),
    groups merge by (bucket, precision)."""
    capacity = capacity if capacity is not None else sketch_capacity()
    sketch = SpaceSaving.merged(
        [
            [[m["machine"], m["count"], m["error"]]
             for m in snap.get("machines", ())]
            for snap in snapshots
        ],
        capacity,
        # each worker's fullness is judged against ITS capacity, not
        # the router's — a smaller-TOPK worker can be full (and owe a
        # missing-mass bound) while looking sparse to the router
        source_capacities=[
            int(snap.get("capacity") or 0) or None for snap in snapshots
        ],
    )
    machine_rates: Dict[str, Dict[str, float]] = {}
    groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
    total_count = 0.0
    total_rates: Dict[str, float] = {}
    ticks = 0
    for snap in snapshots:
        ticks = max(ticks, int(snap.get("ticks") or 0))
        total = snap.get("total") or {}
        total_count += float(total.get("count") or 0.0)
        for label, rate in (total.get("rates") or {}).items():
            total_rates[label] = total_rates.get(label, 0.0) + float(rate)
        for m in snap.get("machines", ()):
            rates = machine_rates.setdefault(m["machine"], {})
            for label, rate in (m.get("rates") or {}).items():
                rates[label] = rates.get(label, 0.0) + float(rate)
        for g in snap.get("groups", ()):
            key = (g.get("bucket", ""), g.get("precision", ""))
            into = groups.setdefault(
                key, {"bucket": key[0], "precision": key[1],
                      "count": 0.0, "rates": {}}
            )
            into["count"] += float(g.get("count") or 0.0)
            for label, rate in (g.get("rates") or {}).items():
                into["rates"][label] = (
                    into["rates"].get(label, 0.0) + float(rate)
                )
    return {
        "capacity": capacity,
        "ticks": ticks,
        "total": {"count": total_count, "rates": total_rates},
        "machines": [
            {
                "machine": name,
                "count": count,
                "error": error,
                "rates": machine_rates.get(name, {}),
            }
            for name, count, error in sketch.items()
        ],
        "groups": [groups[key] for key in sorted(groups)],
    }


# THE process-wide accountant (REGISTRY pattern): the engine records
# into it without plumbing; servers, warehouses, and the registry's
# cardinality bound all read the same accounting. Tests construct their
# own TrafficAccountant for isolation.
ACCOUNTANT = TrafficAccountant()


def note(
    machine: str, bucket: str = "", precision: str = "", n: float = 1.0
) -> None:
    """Scoring-path entry: account one request when telemetry is on
    (the disabled path is one env read — the overhead gate's floor)."""
    if not enabled():
        return
    ACCOUNTANT.note(machine, bucket=bucket, precision=precision, n=n)


def _topk_provider(cap: int) -> Optional[List[str]]:
    """Satellite hook: nominate the sketch's heaviest machines as the
    kept set for metric cardinality bounding. None (telemetry off, or
    an empty sketch) falls back to the registry's per-family recount."""
    if not enabled():
        return None
    names = ACCOUNTANT.topk_names(cap)
    return names or None


set_traffic_topk_provider(_topk_provider)
