"""Prometheus text-format (v0.0.4) rendering and validation.

``render_prometheus`` turns a :class:`~.registry.Registry` into the
exposition text a Prometheus scraper ingests (``# HELP`` / ``# TYPE``
comments, cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count`` triples
for histograms, escaped label values). ``parse_prometheus_text`` is the
inverse validator — used by ``tools/scrape_metrics.py`` and the tests so
a malformed exposition fails loudly instead of silently dropping series
at the scraper.

No ``prometheus_client`` dependency: the format is a few dozen lines and
this image must not grow packages (repo constraint), exactly like the
werkzeug-not-flask decision in ``server/server.py``.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Tuple

from .registry import Histogram, Registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                        # optional label body
    r"\s+(-?[0-9.eE+-]+|[+-]Inf|NaN)"       # value
    r"(?:\s+(-?[0-9]+))?$"                  # optional timestamp
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labelnames, values, extra: Tuple[str, str] = None) -> str:
    pairs = [
        f'{name}="{_escape_label(value)}"'
        for name, value in zip(labelnames, values)
    ]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(registry: Registry) -> str:
    """The registry as Prometheus text exposition format v0.0.4."""
    lines: List[str] = []
    for metric in registry.metrics():
        if metric.help:
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if isinstance(metric, Histogram):
            for values, data in sorted(metric.collect().items()):
                for le, cumulative in data["buckets"]:
                    labels = _fmt_labels(
                        metric.labelnames, values, extra=("le", _fmt_value(le))
                    )
                    lines.append(
                        f"{metric.name}_bucket{labels} {cumulative}"
                    )
                labels = _fmt_labels(metric.labelnames, values)
                lines.append(
                    f"{metric.name}_sum{labels} {_fmt_value(data['sum'])}"
                )
                lines.append(f"{metric.name}_count{labels} {data['count']}")
        else:
            for values, value in sorted(metric.collect().items()):
                labels = _fmt_labels(metric.labelnames, values)
                lines.append(f"{metric.name}{labels} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def _parse_label_body(body: str, lineno: int) -> Dict[str, str]:
    if not body:
        return {}
    labels: Dict[str, str] = {}
    # tolerate a trailing comma (the format allows it); everything else in
    # the body must be name="value" pairs — leftovers mean a malformed line
    rest = _LABEL_RE.sub("", body).replace(",", "").strip()
    if rest:
        raise ValueError(f"line {lineno}: malformed label body {body!r}")
    for match in _LABEL_RE.finditer(body):
        labels[match.group(1)] = _unescape_label(match.group(2))
    return labels


def _unescape_label(raw: str) -> str:
    """Single left-to-right scan: sequential str.replace would corrupt a
    literal backslash followed by 'n' (``\\\\n`` must decode to ``\\`` +
    ``n``, not a newline)."""
    out: List[str] = []
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_value(raw: str, lineno: int) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"line {lineno}: unparseable value {raw!r}") from None


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Parse + validate exposition text; ``{name: [(labels, value), ...]}``.

    Raises ``ValueError`` (with the offending line number) on any line
    that is neither a well-formed comment nor a well-formed sample, on a
    ``# TYPE`` naming an unknown metric type, and on a histogram whose
    ``+Inf`` bucket disagrees with its ``_count`` — the inconsistencies a
    real scraper rejects or silently mis-ingests.
    """
    samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment — legal, ignored
            if not _NAME_RE.match(parts[2]):
                raise ValueError(
                    f"line {lineno}: bad metric name in comment: {parts[2]!r}"
                )
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in ("counter", "gauge", "histogram", "summary",
                                "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown metric type {kind!r}"
                    )
                types[parts[2]] = kind
            continue
        match = _SAMPLE_RE.match(line)
        if not match:
            raise ValueError(f"line {lineno}: malformed sample line {line!r}")
        name, body, raw_value = match.group(1), match.group(2), match.group(3)
        labels = _parse_label_body(body or "", lineno)
        value = _parse_value(raw_value, lineno)
        samples.setdefault(name, []).append((labels, value))

    # histogram consistency: the +Inf bucket IS the count
    for name, kind in types.items():
        if kind != "histogram":
            continue
        counts = {  # series key (minus le) -> count value
            _series_key(labels): value
            for labels, value in samples.get(f"{name}_count", [])
        }
        inf_buckets: Dict[Any, float] = {}
        for labels, value in samples.get(f"{name}_bucket", []):
            if labels.get("le") == "+Inf":
                rest = {k: v for k, v in labels.items() if k != "le"}
                inf_buckets[_series_key(rest)] = value
        for key, count in counts.items():
            if key not in inf_buckets:
                raise ValueError(
                    f"histogram {name}: series {key or '(unlabeled)'} has "
                    "no +Inf bucket"
                )
            if inf_buckets[key] != count:
                raise ValueError(
                    f"histogram {name}: +Inf bucket {inf_buckets[key]} != "
                    f"count {count} for series {key or '(unlabeled)'}"
                )
    return samples


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))
