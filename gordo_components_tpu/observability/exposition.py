"""Prometheus text-format (v0.0.4) rendering and validation.

``render_prometheus`` turns a :class:`~.registry.Registry` into the
exposition text a Prometheus scraper ingests (``# HELP`` / ``# TYPE``
comments, cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count`` triples
for histograms, escaped label values). ``parse_prometheus_text`` is the
inverse validator — used by ``tools/scrape_metrics.py`` and the tests so
a malformed exposition fails loudly instead of silently dropping series
at the scraper.

Exemplars: histogram bucket samples may carry an OpenMetrics-style
exemplar suffix — `` # {trace_id="3f2a..."} 0.042 1690000000.123`` —
linking the aggregate bucket to a concrete request timeline in
``/debug/requests``. The renderer emits one per bucket when the
observation ran under a bound trace id; the parser validates the syntax
(label grammar, the 128-char OpenMetrics label budget, bucket/counter
placement only) and fails loudly on malformed exemplars so the
exposition stays ingestible by Prometheus/OpenMetrics scrapers.

No ``prometheus_client`` dependency: the format is a few dozen lines and
this image must not grow packages (repo constraint), exactly like the
werkzeug-not-flask decision in ``server/server.py``.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Tuple

from .registry import Histogram, Registry, bound_machine_cardinality

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                        # optional label body
    r"\s+(-?[0-9.eE+-]+|[+-]Inf|NaN)"       # value
    r"(?:\s+(-?[0-9]+))?$"                  # optional timestamp
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
# exemplar suffix (OpenMetrics): `<sample> # {labels} value [timestamp]`.
# The greedy prefix makes the LAST ` # {` on the line the exemplar
# boundary, so escaped label values earlier in the line cannot split it.
_EXEMPLAR_RE = re.compile(
    r"^(?P<sample>.*\S)\s+#\s+\{(?P<labels>.*)\}"
    r"\s+(?P<value>-?[0-9.eE+-]+|[+-]Inf|NaN)"
    r"(?:\s+(?P<ts>[0-9]+(?:\.[0-9]+)?))?$"
)
# OpenMetrics: an exemplar's label names + values must fit 128 runes
_EXEMPLAR_LABEL_BUDGET = 128


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _fmt_labels(labelnames, values, extra: Tuple[str, str] = None) -> str:
    pairs = [
        f'{name}="{_escape_label(value)}"'
        for name, value in zip(labelnames, values)
    ]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_exemplar(exemplar) -> str:
    """`` # {trace_id="..."} value timestamp`` (OpenMetrics exemplar)."""
    trace_id, value, ts = exemplar
    return (
        f' # {{trace_id="{_escape_label(trace_id)}"}} '
        f"{_fmt_value(value)} {ts:.3f}"
    )


def render_prometheus(registry: Registry, exemplars: bool = False) -> str:
    """The registry as Prometheus text exposition format v0.0.4.

    ``exemplars=True`` additionally renders OpenMetrics-style exemplars
    on histogram buckets whose last traced observation is known. That is
    an OPT-IN extension (``?exemplars=1`` on the server): the classic
    Prometheus text parser selected by the v0.0.4 content type rejects
    the `` # {...}`` suffix outright, so the default scrape must stay
    strict — exemplar output is for gordo's own tooling
    (``tools/scrape_metrics.py``, trace debugging) and
    OpenMetrics-capable ingesters."""
    lines: List[str] = []
    for metric in registry.metrics():
        if metric.help:
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if isinstance(metric, Histogram):
            # §22: machine-labeled families render top-K + "other", so
            # exposition size is bounded at ANY fleet size
            collected = bound_machine_cardinality(metric, metric.collect())
            for values, data in sorted(collected.items()):
                series_exemplars = data.get("exemplars") or {}
                for i, (le, cumulative) in enumerate(data["buckets"]):
                    labels = _fmt_labels(
                        metric.labelnames, values, extra=("le", _fmt_value(le))
                    )
                    suffix = ""
                    if exemplars and i in series_exemplars:
                        suffix = _fmt_exemplar(series_exemplars[i])
                    lines.append(
                        f"{metric.name}_bucket{labels} {cumulative}{suffix}"
                    )
                labels = _fmt_labels(metric.labelnames, values)
                lines.append(
                    f"{metric.name}_sum{labels} {_fmt_value(data['sum'])}"
                )
                lines.append(f"{metric.name}_count{labels} {data['count']}")
        else:
            collected = bound_machine_cardinality(metric, metric.collect())
            for values, value in sorted(collected.items()):
                labels = _fmt_labels(metric.labelnames, values)
                lines.append(f"{metric.name}{labels} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def _parse_label_body(body: str, lineno: int) -> Dict[str, str]:
    if not body:
        return {}
    labels: Dict[str, str] = {}
    # tolerate a trailing comma (the format allows it); everything else in
    # the body must be name="value" pairs — leftovers mean a malformed line
    rest = _LABEL_RE.sub("", body).replace(",", "").strip()
    if rest:
        raise ValueError(f"line {lineno}: malformed label body {body!r}")
    for match in _LABEL_RE.finditer(body):
        labels[match.group(1)] = _unescape_label(match.group(2))
    return labels


def _unescape_label(raw: str) -> str:
    """Single left-to-right scan: sequential str.replace would corrupt a
    literal backslash followed by 'n' (``\\\\n`` must decode to ``\\`` +
    ``n``, not a newline)."""
    out: List[str] = []
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch == "\\" and i + 1 < len(raw):
            nxt = raw[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_value(raw: str, lineno: int) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"line {lineno}: unparseable value {raw!r}") from None


def _parse_exemplar(line: str, lineno: int, types: Dict[str, str]):
    """Detach and validate a trailing exemplar; returns ``(sample_part,
    exemplar_dict_or_None)``.

    A line only counts as carrying an exemplar when the exemplar suffix
    matches AND what precedes it is itself a well-formed sample — a
    quoted label value containing `` # `` is a legal plain sample, not a
    malformed exemplar. Once a line IS an exemplar, every defect in it
    (bad label grammar, over-budget label set, placement on anything but
    a histogram bucket or counter) fails loudly — a scraper would either
    reject it or silently drop the series."""
    if " # " not in line:
        return line, None
    match = _EXEMPLAR_RE.match(line)
    if match is not None:
        sample_part = match.group("sample")
        sample_match = _SAMPLE_RE.match(sample_part)
        if sample_match is not None:
            try:
                _parse_label_body(sample_match.group(2) or "", lineno)
            except ValueError:
                sample_match = None  # not a valid sample prefix after all
        if sample_match is not None:
            labels = _parse_label_body(match.group("labels"), lineno)
            if not labels:
                raise ValueError(
                    f"line {lineno}: exemplar must carry at least one label"
                )
            budget = sum(len(k) + len(v) for k, v in labels.items())
            if budget > _EXEMPLAR_LABEL_BUDGET:
                raise ValueError(
                    f"line {lineno}: exemplar label set is {budget} runes "
                    f"(OpenMetrics caps it at {_EXEMPLAR_LABEL_BUDGET})"
                )
            value = _parse_value(match.group("value"), lineno)
            ts = float(match.group("ts")) if match.group("ts") else None
            # placement: OpenMetrics allows exemplars on histogram
            # buckets and counters only — anywhere else is malformed
            name = sample_match.group(1)
            base = (
                name[: -len("_bucket")] if name.endswith("_bucket") else None
            )
            bucket_ok = base is not None and types.get(base) == "histogram"
            counter_ok = types.get(name) == "counter"
            if not (bucket_ok or counter_ok):
                raise ValueError(
                    f"line {lineno}: exemplar on {name!r}, which is "
                    "neither a histogram bucket nor a counter"
                )
            return sample_part, {
                "labels": labels, "value": value, "timestamp": ts,
            }
    # no well-formed exemplar: hand the whole line to the plain sample
    # parser (which fails loudly itself if the line is genuinely broken)
    return line, None


def parse_prometheus_text(
    text: str, return_exemplars: bool = False, return_meta: bool = False
) -> Any:
    """Parse + validate exposition text; ``{name: [(labels, value), ...]}``
    (with ``return_exemplars=True``: ``(samples, exemplars)`` where
    ``exemplars`` maps name → ``[(labels, exemplar_dict), ...]``; with
    ``return_meta=True``: ``(samples, exemplars, types, helps)`` — the
    full family metadata the scrape-of-scrapes aggregator re-renders
    from).

    Raises ``ValueError`` (with the offending line number) on any line
    that is neither a well-formed comment nor a well-formed sample, on a
    ``# TYPE`` naming an unknown metric type, on a malformed or misplaced
    exemplar, and on a histogram whose ``+Inf`` bucket disagrees with its
    ``_count`` — the inconsistencies a real scraper rejects or silently
    mis-ingests.
    """
    samples: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    exemplars: Dict[str, List[Tuple[Dict[str, str], Dict[str, Any]]]] = {}
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment — legal, ignored
            if not _NAME_RE.match(parts[2]):
                raise ValueError(
                    f"line {lineno}: bad metric name in comment: {parts[2]!r}"
                )
            if parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            if parts[1] == "TYPE":
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in ("counter", "gauge", "histogram", "summary",
                                "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown metric type {kind!r}"
                    )
                types[parts[2]] = kind
            continue
        line, exemplar = _parse_exemplar(line, lineno, types)
        match = _SAMPLE_RE.match(line)
        if not match:
            raise ValueError(f"line {lineno}: malformed sample line {line!r}")
        name, body, raw_value = match.group(1), match.group(2), match.group(3)
        labels = _parse_label_body(body or "", lineno)
        value = _parse_value(raw_value, lineno)
        samples.setdefault(name, []).append((labels, value))
        if exemplar is not None:
            exemplars.setdefault(name, []).append((labels, exemplar))

    # histogram consistency: the +Inf bucket IS the count
    for name, kind in types.items():
        if kind != "histogram":
            continue
        counts = {  # series key (minus le) -> count value
            _series_key(labels): value
            for labels, value in samples.get(f"{name}_count", [])
        }
        inf_buckets: Dict[Any, float] = {}
        for labels, value in samples.get(f"{name}_bucket", []):
            if labels.get("le") == "+Inf":
                rest = {k: v for k, v in labels.items() if k != "le"}
                inf_buckets[_series_key(rest)] = value
        for key, count in counts.items():
            if key not in inf_buckets:
                raise ValueError(
                    f"histogram {name}: series {key or '(unlabeled)'} has "
                    "no +Inf bucket"
                )
            if inf_buckets[key] != count:
                raise ValueError(
                    f"histogram {name}: +Inf bucket {inf_buckets[key]} != "
                    f"count {count} for series {key or '(unlabeled)'}"
                )
    if return_meta:
        return samples, exemplars, types, helps
    if return_exemplars:
        return samples, exemplars
    return samples


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))
