"""Flight recorder: an always-on bounded buffer of completed request
timelines.

Post-hoc diagnosability is the point: when an operator asks "why did
trace 3f2a... take 900 ms at 04:12", the histograms have already averaged
the answer away. The recorder keeps (1) a ring of the last ``keep``
completed timelines, (2) a reservoir of the ``slow_keep`` SLOWEST
requests seen since boot, and (3) a ring of the last ``error_keep``
errored/shed requests — so a burst of fast healthy traffic can never
flush the one pathological trace you care about out of memory.

Memory contract: everything is bounded. A timeline is a few hundred
bytes (spans are ``__slots__`` objects); at the defaults (256 + 32 + 64
timelines) the recorder holds well under a megabyte regardless of
uptime. Recording is one lock + deque append + (rarely) an O(slow_keep)
insertion — measured within noise of a disabled recorder at saturation
(``tools/perf_smoke.py`` gates this).

``GORDO_FLIGHTREC=0`` disables recording (the perf-comparison mode and
the escape hatch); ``GORDO_FLIGHTREC_KEEP`` / ``_SLOW_KEEP`` /
``_ERROR_KEEP`` size the buffers.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .spans import Timeline


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    def __init__(
        self,
        keep: Optional[int] = None,
        slow_keep: Optional[int] = None,
        error_keep: Optional[int] = None,
        enabled: Optional[bool] = None,
    ):
        self.keep = keep if keep is not None else _env_int(
            "GORDO_FLIGHTREC_KEEP", 256
        )
        self.slow_keep = slow_keep if slow_keep is not None else _env_int(
            "GORDO_FLIGHTREC_SLOW_KEEP", 32
        )
        self.error_keep = error_keep if error_keep is not None else _env_int(
            "GORDO_FLIGHTREC_ERROR_KEEP", 64
        )
        self._enabled = (
            enabled
            if enabled is not None
            else os.environ.get("GORDO_FLIGHTREC", "1") != "0"
        )
        self._lock = threading.Lock()
        self._ring: "deque[Timeline]" = deque(maxlen=self.keep)
        # slowest-since-boot reservoir, kept sorted ascending by duration
        # (insertion is bisect-free: slow_keep is tiny)
        self._slow: List[Timeline] = []
        self._errors: "deque[Timeline]" = deque(maxlen=self.error_keep)
        self.recorded = 0

    # -- enablement ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """Runtime toggle (perf comparisons, tests). Does not clear."""
        self._enabled = bool(enabled)

    # -- recording -----------------------------------------------------------
    def record(self, timeline: Timeline) -> None:
        if not self._enabled:
            return
        if timeline.finished is None:
            timeline.finish()
        duration = timeline.duration
        with self._lock:
            self.recorded += 1
            self._ring.append(timeline)
            if timeline.error:
                self._errors.append(timeline)
            if len(self._slow) < self.slow_keep:
                self._slow.append(timeline)
                self._slow.sort(key=lambda t: t.duration)
            elif self._slow and duration > self._slow[0].duration:
                self._slow[0] = timeline
                self._slow.sort(key=lambda t: t.duration)

    # -- views ---------------------------------------------------------------
    def _all(self) -> List[Timeline]:
        """Ring + reservoirs, deduped by identity, newest ring entries
        first (callers hold no lock; the copies are taken under it)."""
        with self._lock:
            ring = list(self._ring)
            slow = list(self._slow)
            errors = list(self._errors)
        seen: set = set()
        out: List[Timeline] = []
        for timeline in reversed(ring):
            if id(timeline) not in seen:
                seen.add(id(timeline))
                out.append(timeline)
        for timeline in sorted(slow, key=lambda t: -t.duration) + list(errors):
            if id(timeline) not in seen:
                seen.add(id(timeline))
                out.append(timeline)
        return out

    def get(self, trace_id: str) -> Optional[Timeline]:
        for timeline in self._all():
            if timeline.trace_id == trace_id:
                return timeline
        return None

    def slowest(self) -> Optional[Timeline]:
        with self._lock:
            return self._slow[-1] if self._slow else None

    def summaries(self, limit: int = 50) -> Dict[str, Any]:
        """The /debug/requests body: recent rows, the slow reservoir, and
        recent errors — each a :meth:`Timeline.summary` dict."""
        with self._lock:
            ring = list(self._ring)
            slow = list(self._slow)
            errors = list(self._errors)
            recorded = self.recorded
        slowest = slow[-1] if slow else None
        limit = max(0, limit)
        # limit bounds ALL three views: a watchman polling ?limit=1 per
        # status tick must not make the server serialize the full slow +
        # error reservoirs (~100 summary builds) just to read "slowest"
        return {
            "enabled": self._enabled,
            "recorded": recorded,
            "kept": len(ring),
            "slowest": slowest.summary() if slowest is not None else None,
            "requests": [
                t.summary() for t in list(reversed(ring))[:limit]
            ],
            "slow": [t.summary() for t in sorted(
                slow, key=lambda t: -t.duration
            )[:limit]],
            "errors": [t.summary() for t in list(reversed(errors))[:limit]],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()
            self._errors.clear()
            self.recorded = 0


# THE process-wide recorder (like observability.REGISTRY): the server
# records into it, /debug/requests reads from it, tests may clear() it.
RECORDER = FlightRecorder()
