"""Process-wide labeled metric primitives: Counter / Gauge / Histogram.

The reference has no metrics layer at all (SURVEY.md §6: debugging was
kubectl logs); before this module the rebuild's only telemetry was the
server's ad-hoc ``_Latency`` ring buffer and ``PhaseTimer`` durations that
died with the build process. This registry is the ONE place every layer
(client, server, engine, builder, fleet, watchman, bench) records to, so a
single ``GET /metrics`` — JSON or Prometheus text — sees the whole process.

Design (deliberately mirrors the retired ``_Latency``): lock-LIGHT, not
lock-free — one ``threading.Lock`` per metric, held only for dict/list
mutation; percentile math runs on a snapshot copied under the lock. A
histogram keeps both cumulative buckets (Prometheus exposition) and a
bounded rolling sample window (the JSON p50/p99 view a long-lived server
can afford — unbounded per-request history is exactly what ``_Latency``'s
``keep`` cap existed to prevent).

Get-or-create semantics: ``registry.counter(name, ...)`` returns the
existing metric when one is already registered under ``name`` (many
ModelServer instances in one test process must share series, not crash),
and raises on kind/label mismatch so two call sites can never silently
write incompatible series under one name.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

INF = float("inf")

# -- bounded machine cardinality (ARCHITECTURE §22) ---------------------------
# The one label dimension that scales with FLEET SIZE, not with code: a
# 100k-machine fleet must not be able to melt the scrape path (100k text
# lines per family) or the §18 aggregator. Families labeled by machine
# collapse at exposition/snapshot time to the top-K machines by traffic
# plus ONE `machine="other"` aggregate; the in-memory series stay exact
# (a future scoped query could still read them), only the rendered view
# is bounded.
MACHINE_LABEL = "machine"
MACHINE_OTHER = "other"

# The ONE authoritative top-K-by-traffic selection (§24): when the
# telemetry traffic sketch is live, it nominates the kept machines for
# every family, so a scrape shows one consistent survivor set instead of
# per-family re-derivations that can disagree. observability.traffic
# installs the provider at import time (a callable cap -> names); the
# hook keeps the dependency pointed traffic -> registry, never back.
_traffic_topk_provider = None


def set_traffic_topk_provider(provider) -> None:
    global _traffic_topk_provider
    _traffic_topk_provider = provider


def machine_cardinality_cap() -> int:
    """``GORDO_METRICS_MACHINE_CARDINALITY``: distinct machine label
    values rendered per family before top-K + ``other`` collapse
    (default 64; ``0`` disables the bound)."""
    try:
        return int(
            os.environ.get("GORDO_METRICS_MACHINE_CARDINALITY", "64")
        )
    except ValueError:
        return 64


def _merge_histogram_data(into: Dict[str, Any], data: Dict[str, Any]) -> None:
    """le-wise bucket merge (+sum/count) of two ``Histogram.collect``
    series — bucket bounds agree by construction (same metric)."""
    into["buckets"] = [
        (le, acc + other_acc)
        for (le, acc), (_, other_acc) in zip(into["buckets"], data["buckets"])
    ]
    into["sum"] += data["sum"]
    into["count"] += data["count"]
    into["samples"] = (into["samples"] + data["samples"])[-1000:]
    for i, exemplar in (data.get("exemplars") or {}).items():
        current = into["exemplars"].get(i)
        if current is None or exemplar[2] >= current[2]:  # newest wins
            into["exemplars"][i] = exemplar


def bound_machine_cardinality(
    metric: "_Metric", collected: Dict[Tuple[str, ...], Any]
) -> Dict[Tuple[str, ...], Any]:
    """Collapse ``collected`` (a ``metric.collect()`` mapping) so at most
    top-K distinct machine label values survive; the rest aggregate into
    ``machine="other"`` — counters SUM (total traffic is additive),
    gauges take MAX (summing per-machine durations would fabricate a
    value no machine ever reported; the worst straggler is the honest
    scalar), histograms merge le-wise. Ranking is by counter/gauge value
    or histogram count — "traffic", so the named survivors are the ones
    an operator would ask about."""
    if MACHINE_LABEL not in metric.labelnames:
        return collected
    cap = machine_cardinality_cap()
    if cap <= 0:
        return collected
    idx = metric.labelnames.index(MACHINE_LABEL)
    is_hist = isinstance(metric, Histogram)

    def weight(data: Any) -> float:
        return float(data["count"]) if is_hist else float(data)

    totals: Dict[str, float] = {}
    for key, data in collected.items():
        totals[key[idx]] = totals.get(key[idx], 0.0) + weight(data)
    if len(totals) <= cap:
        return collected
    keep: Optional[set] = None
    if _traffic_topk_provider is not None:
        try:
            nominated = _traffic_topk_provider(cap)
        except Exception:  # lint: allow-swallow(a broken traffic sketch must not break metric rendering; the recount below is the documented fallback)
            nominated = None
        if nominated:
            # the sketch ranks by TOTAL traffic across all families;
            # only machines present in THIS family's series can be kept,
            # and any remaining slots fall back to the per-family
            # recount so the cap is always filled
            keep = set(nominated) & set(totals)
            if len(keep) > cap:
                keep = set(
                    sorted(keep, key=lambda m: (-totals[m], m))[:cap]
                )
            elif len(keep) < cap:
                for m in sorted(totals, key=lambda m: (-totals[m], m)):
                    if len(keep) >= cap:
                        break
                    keep.add(m)
    if keep is None:
        keep = set(sorted(totals, key=lambda m: (-totals[m], m))[:cap])
    # "other" is a RESERVED label value once collapse is in play: a real
    # machine named "other" kept verbatim would collide with the
    # synthetic aggregate (counter sums merging into its kept entry,
    # histogram merges mutating its un-copied collect() data) — fold it
    # into the aggregate instead, where its traffic is at least honest
    keep.discard(MACHINE_OTHER)
    out: Dict[Tuple[str, ...], Any] = {}
    for key, data in collected.items():
        if key[idx] in keep:
            out[key] = data
            continue
        okey = key[:idx] + (MACHINE_OTHER,) + key[idx + 1:]
        current = out.get(okey)
        if current is None:
            if is_hist:
                data = {
                    "buckets": list(data["buckets"]),
                    "sum": data["sum"],
                    "count": data["count"],
                    "samples": list(data["samples"]),
                    "exemplars": dict(data.get("exemplars") or {}),
                }
            out[okey] = data
        elif is_hist:
            _merge_histogram_data(current, data)
        elif isinstance(metric, Counter):
            out[okey] = current + data
        else:
            out[okey] = max(current, data)
    return out


_get_trace_id = None


def _current_trace_id() -> str:
    # lazy-bound import: tracing lazily imports this module inside
    # span(), so a top-level import here would be circular; resolved
    # once, then one contextvar read per call (this sits on the
    # histogram observe hot path)
    global _get_trace_id
    if _get_trace_id is None:
        from .tracing import get_trace_id

        _get_trace_id = get_trace_id
    return _get_trace_id()

# latency-oriented default buckets (seconds): sub-ms device dispatches up
# through multi-second compiles land in distinct buckets
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, INF,
)


def _label_key(labelnames: Sequence[str], values: Sequence[str]) -> str:
    """Canonical series key, rendered prometheus-style so the JSON snapshot
    and the text exposition agree on identity: ``a="x",b="y"`` ('' when
    unlabeled)."""
    return ",".join(f'{n}="{v}"' for n, v in zip(labelnames, values))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _check_values(self, values: Tuple[str, ...]) -> Tuple[str, ...]:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label value(s) "
                f"{self.labelnames}, got {len(values)}"
            )
        return tuple(str(v) for v in values)


class Counter(_Metric):
    """Monotonically increasing float per label set."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, *values: str) -> "_BoundCounter":
        return _BoundCounter(self, self._check_values(values))

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _inc(self, values: Tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._values[values] = self._values.get(values, 0.0) + amount

    def collect(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)


class _BoundCounter:
    __slots__ = ("_metric", "_values")

    def __init__(self, metric: Counter, values: Tuple[str, ...]):
        self._metric = metric
        self._values = values

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._values, amount)


class Gauge(_Metric):
    """Last-written float per label set (set/inc/dec)."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, *values: str) -> "_BoundGauge":
        return _BoundGauge(self, self._check_values(values))

    def set(self, value: float) -> None:
        self._set((), value)

    def inc(self, amount: float = 1.0) -> None:
        self._inc((), amount)

    def _set(self, values: Tuple[str, ...], value: float) -> None:
        with self._lock:
            self._values[values] = float(value)

    def _inc(self, values: Tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[values] = self._values.get(values, 0.0) + amount

    def collect(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._values)


class _BoundGauge:
    __slots__ = ("_metric", "_values")

    def __init__(self, metric: Gauge, values: Tuple[str, ...]):
        self._metric = metric
        self._values = values

    def set(self, value: float) -> None:
        self._metric._set(self._values, value)

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._values, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._metric._inc(self._values, -amount)


def _percentile(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over the bounded sample window — THE one
    rule (``Histogram.stats`` and the snapshot's collapsed series must
    agree)."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    n = len(ordered)
    return ordered[min(n - 1, int(round(q * (n - 1))))]


class _HistSeries:
    __slots__ = ("bucket_counts", "sum", "count", "samples", "exemplars")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        self.samples: List[float] = []  # bounded rolling window
        # bucket index -> (trace_id, value, unix_ts): the most recent
        # traced observation landing in that bucket — the OpenMetrics
        # exemplar linking an aggregate bucket to a concrete request in
        # the flight recorder. Bounded by construction (<= n_buckets
        # entries per series); only observations made under a bound trace
        # id record one.
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}


class Histogram(_Metric):
    """Cumulative-bucket histogram + bounded sample window per label set.

    Buckets serve the Prometheus exposition (exact, unbounded count);
    the ``keep``-bounded sample window serves the JSON p50/p99 view with
    ``_Latency``'s memory contract (a year-old server holds ``keep``
    floats per series, not per-request history).
    """

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS, keep: int = 1000):
        super().__init__(name, help, labelnames)
        bounds = sorted(float(b) for b in buckets)
        if not bounds or bounds[-1] != INF:
            bounds.append(INF)
        self.buckets = tuple(bounds)
        self.keep = keep
        self._series: Dict[Tuple[str, ...], _HistSeries] = {}

    def labels(self, *values: str) -> "_BoundHistogram":
        return _BoundHistogram(self, self._check_values(values))

    def observe(self, value: float) -> None:
        self._observe((), value)

    def _observe(self, values: Tuple[str, ...], value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        # exemplar capture outside the lock: one contextvar read, and a
        # wall-clock read only when a trace is actually bound
        trace_id = _current_trace_id()
        exemplar = (trace_id, value, time.time()) if trace_id else None
        with self._lock:
            series = self._series.get(values)
            if series is None:
                series = self._series[values] = _HistSeries(len(self.buckets))
            series.bucket_counts[i] += 1
            series.sum += value
            series.count += 1
            series.samples.append(value)
            if len(series.samples) > self.keep:
                del series.samples[: -self.keep]
            if exemplar is not None:
                series.exemplars[i] = exemplar

    def collect(self) -> Dict[Tuple[str, ...], Dict[str, Any]]:
        """Snapshot copy: ``{labelvalues: {"buckets": [(le, cumulative)],
        "sum": s, "count": n, "samples": [...], "exemplars":
        {bucket_index: (trace_id, value, ts)}}}``."""
        with self._lock:
            copied = {
                values: (list(s.bucket_counts), s.sum, s.count,
                         list(s.samples), dict(s.exemplars))
                for values, s in self._series.items()
            }
        out: Dict[Tuple[str, ...], Dict[str, Any]] = {}
        for values, (counts, total, count, samples, exemplars) in copied.items():
            cumulative, acc = [], 0
            for le, n in zip(self.buckets, counts):
                acc += n
                cumulative.append((le, acc))
            out[values] = {
                "buckets": cumulative,
                "sum": total,
                "count": count,
                "samples": samples,
                "exemplars": exemplars,
            }
        return out

    def stats(self) -> Dict[Tuple[str, ...], Dict[str, float]]:
        """Percentile view per series (p50/p99/mean over the bounded sample
        window, count over the full lifetime) — the JSON ``/metrics``
        shape the retired ``_Latency.snapshot`` produced."""
        out = {}
        for values, data in self.collect().items():
            samples = data["samples"]
            out[values] = {
                "count": data["count"],
                "p50": _percentile(samples, 0.50),
                "p99": _percentile(samples, 0.99),
                "mean": sum(samples) / len(samples) if samples else 0.0,
            }
        return out


class _BoundHistogram:
    __slots__ = ("_metric", "_values")

    def __init__(self, metric: Histogram, values: Tuple[str, ...]):
        self._metric = metric
        self._values = values

    def observe(self, value: float) -> None:
        self._metric._observe(self._values, value)


class Registry:
    """Named metric collection with get-or-create registration."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kwargs) -> Any:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}; "
                        f"requested {cls.kind} with labels {labelnames}"
                    )
                if isinstance(existing, Histogram):
                    # same silent-incompatibility hazard as kind/labels:
                    # observations from a call site expecting different
                    # bucket bounds (or window size) would be binned wrong
                    requested = Histogram(name, help, labelnames, **kwargs)
                    if (existing.buckets != requested.buckets
                            or existing.keep != requested.keep):
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {existing.buckets} / keep "
                            f"{existing.keep}; requested "
                            f"{requested.buckets} / keep {requested.keep}"
                        )
                return existing
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  keep: int = 1000) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets, keep=keep
        )

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every metric: counters/gauges as plain values,
        histograms as {count, sum, mean, p50, p99} per series (keyed
        prometheus-style: ``endpoint="healthz"``)."""
        out: Dict[str, Any] = {}
        for metric in self.metrics():
            if isinstance(metric, Histogram):
                collected = bound_machine_cardinality(
                    metric, metric.collect()
                )
                series = {
                    _label_key(metric.labelnames, values): {
                        "count": data["count"],
                        "sum": data["sum"],
                        "mean": (
                            sum(data["samples"]) / len(data["samples"])
                            if data["samples"] else 0.0
                        ),
                        "p50": _percentile(data["samples"], 0.50),
                        "p99": _percentile(data["samples"], 0.99),
                    }
                    for values, data in collected.items()
                }
            else:
                series = {
                    _label_key(metric.labelnames, values): value
                    for values, value in bound_machine_cardinality(
                        metric, metric.collect()
                    ).items()
                }
            out[metric.name] = {
                "kind": metric.kind,
                "help": metric.help,
                "series": series,
            }
        return out


# THE process-wide registry every layer records to. Tests exercising
# registry semantics construct their own Registry; everything shipping
# telemetry uses this one so one scrape sees the whole process.
REGISTRY = Registry()


def get_registry() -> Registry:
    return REGISTRY
